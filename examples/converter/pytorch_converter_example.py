"""Dataframe -> torch DataLoader via the converter (parity: reference
examples/spark_dataset_converter/pytorch_converter_example.py)."""

import argparse

import numpy as np
import pandas as pd
import torch

from petastorm_tpu.converter import make_converter


def run(cache_dir='/tmp/converter_cache_torch', rows=512, steps=20):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(rows, 4)).astype(np.float32)
    df = pd.DataFrame({**{'x{}'.format(i): x[:, i] for i in range(4)},
                       'y': (x.sum(axis=1) > 0).astype(np.int64)})
    converter = make_converter(df, parent_cache_dir_url='file://{}'.format(cache_dir))

    model = torch.nn.Sequential(torch.nn.Linear(4, 16), torch.nn.ReLU(),
                                torch.nn.Linear(16, 2))
    optimizer = torch.optim.Adam(model.parameters(), lr=1e-2)
    loss = None
    with converter.make_torch_dataloader(batch_size=64, num_epochs=None) as loader:
        for step, batch in enumerate(loader):
            if step >= steps:
                break
            inputs = torch.stack([batch['x{}'.format(i)] for i in range(4)], dim=1)
            optimizer.zero_grad()
            loss = torch.nn.functional.cross_entropy(model(inputs), batch['y'])
            loss.backward()
            optimizer.step()
    print('final loss {:.4f}'.format(loss.item()))
    converter.delete()
    return loss.item()


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--cache-dir', default='/tmp/converter_cache_torch')
    args = parser.parse_args()
    run(args.cache_dir)


if __name__ == '__main__':
    main()
