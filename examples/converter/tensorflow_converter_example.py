"""Dataframe -> tf.data via the converter (parity: reference
examples/spark_dataset_converter/tensorflow_converter_example.py)."""

import argparse

import numpy as np
import pandas as pd

from petastorm_tpu.converter import make_converter


def run(cache_dir='/tmp/converter_cache_tf', rows=512, steps=20):
    import tensorflow as tf

    rng = np.random.default_rng(0)
    x = rng.normal(size=(rows, 4)).astype(np.float32)
    df = pd.DataFrame({**{'x{}'.format(i): x[:, i] for i in range(4)},
                       'y': (x.sum(axis=1) > 0).astype(np.int64)})
    converter = make_converter(df, parent_cache_dir_url='file://{}'.format(cache_dir))

    model = tf.keras.Sequential([tf.keras.layers.Dense(16, activation='relu'),
                                 tf.keras.layers.Dense(2, activation='softmax')])
    model.compile(optimizer='adam', loss='sparse_categorical_crossentropy')
    with converter.make_tf_dataset(batch_size=64, num_epochs=None) as dataset:
        features = dataset.map(
            lambda row: (tf.stack([row.x0, row.x1, row.x2, row.x3], axis=1), row.y))
        history = model.fit(features, steps_per_epoch=steps, epochs=1, verbose=0)
    loss = history.history['loss'][-1]
    print('final loss {:.4f}'.format(loss))
    converter.delete()
    return loss


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--cache-dir', default='/tmp/converter_cache_tf')
    args = parser.parse_args()
    run(args.cache_dir)


if __name__ == '__main__':
    main()
