"""Dataframe -> TPU in three lines: ``make_converter`` materializes a (pandas / Arrow /
Spark) dataframe to Parquet once, then hands out mesh-sharded JAX loaders. TPU-native
analog of the reference's Spark converter examples
(examples/spark_dataset_converter/*_converter_example.py).

Run: ``python -m examples.converter.jax_converter_example``
"""

import argparse

import jax.numpy as jnp
import numpy as np
import optax
import pandas as pd

from petastorm_tpu.converter import make_converter


def run(cache_dir='/tmp/converter_cache', rows=1024, steps=30):
    rng = np.random.default_rng(0)
    features = rng.normal(size=(rows, 8)).astype(np.float32)
    true_w = rng.normal(size=(8,)).astype(np.float32)
    df = pd.DataFrame({
        **{'f{}'.format(i): features[:, i] for i in range(8)},
        'y': features @ true_w + 0.01 * rng.normal(size=rows).astype(np.float32),
    })

    converter = make_converter(df, parent_cache_dir_url='file://{}'.format(cache_dir))
    print('materialized {} rows'.format(len(converter)))

    import jax
    w = jnp.zeros(8)
    optimizer = optax.sgd(0.1)
    opt_state = optimizer.init(w)

    @jax.jit
    def train_step(w, opt_state, x, y):
        def loss_fn(w):
            return jnp.mean((x @ w - y) ** 2)
        loss, grads = jax.value_and_grad(loss_fn)(w)
        updates, opt_state = optimizer.update(grads, opt_state, w)
        return optax.apply_updates(w, updates), opt_state, loss

    loader = converter.make_jax_loader(batch_size=128, num_epochs=None)
    loss = None
    for step, batch in enumerate(loader):
        if step >= steps:
            break
        x = jnp.stack([batch['f{}'.format(i)] for i in range(8)], axis=1)
        w, opt_state, loss = train_step(w, opt_state, x, batch['y'])
    loader.stop()
    print('final loss {:.5f}; w error {:.4f}'.format(
        loss, float(jnp.linalg.norm(w - true_w))))
    converter.delete()
    return float(loss)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--cache-dir', default='/tmp/converter_cache')
    args = parser.parse_args()
    run(args.cache_dir)


if __name__ == '__main__':
    main()
