"""Runnable examples for petastorm_tpu (parity target: reference examples/ tree —
hello_world, mnist, imagenet, spark_dataset_converter). Every example runs offline on
synthetic data; the JAX variants are the primary path, torch/TF show the parity adapters."""
