"""Long-context training from Parquet: sequence-parallel attention fed by
sequence-sharded loader batches.

The end-to-end long-context story (SURVEY.md §5.7's idiomatic extension point — the
reference only *constructs* sequences via NGram; it has no compute-side sequence
parallelism):

1. tokenized documents live in a petastorm_tpu store (one ``(seq_len,)`` int32
   NdarrayCodec field per row);
2. ``JaxDataLoader`` emits batches sharded over a 2-D ``(data, seq)`` mesh with
   ``PartitionSpec('data', 'seq')`` — each device holds a [B/data, T/seq] token shard,
   assembled straight from the host pipeline (no resharding step);
3. the shared :class:`petastorm_tpu.models.TransformerLM` trains with
   ``ops.ring_attention`` injected as its attention backend (K/V shards rotate around
   the ``seq`` ring via ``ppermute`` on ICI), so sequences longer than one chip's HBM
   are trained without gathering the full sequence anywhere — and the model code is
   identical to the single-chip dense/flash configurations.

Two dataset modes:

- default: pre-tokenized fixed-length documents (one ``(seq_len,)`` row per doc);
- ``--ngram-frames N``: the store holds short token *frames* of a stream and the
  training sequence is assembled by :class:`petastorm_tpu.ngram.NGram` — N consecutive
  frames per window, gap-checked on ``frame_id`` — flowing straight into the device
  layer as ``(batch, N, frame_len)`` sequence-sharded arrays (the reference can only
  emit NGram windows as python dicts; here they feed the mesh, SURVEY.md §5.7).

Run: ``python -m examples.long_context.jax_example --seq-len 512``
     ``python -m examples.long_context.jax_example --ngram-frames 8``
"""

import argparse
import os
import tempfile

import numpy as np

VOCAB = 256
EMBED = 64
HEADS = 4


def build_dataset(url, num_docs=256, seq_len=512, seed=0):
    """Materialize synthetic tokenized documents (stand-in for a tokenized corpus)."""
    from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_rows
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('Docs', [
        UnischemaField('doc_id', np.int64, (), ScalarCodec(), False),
        UnischemaField('tokens', np.int32, (seq_len,), NdarrayCodec(), False),
    ])
    rng = np.random.RandomState(seed)
    # a learnable synthetic language: each doc repeats a per-doc token bigram pattern
    rows = []
    for i in range(num_docs):
        base = rng.randint(0, VOCAB, size=8, dtype=np.int32)
        tokens = np.tile(base, seq_len // 8 + 1)[:seq_len].astype(np.int32)
        rows.append({'doc_id': i, 'tokens': tokens})
    write_rows(url, schema, rows, n_files=4)
    return schema


def build_frame_dataset(url, num_frames=512, frame_len=64, seed=0):
    """Materialize a token STREAM as consecutive frames: ``frame_id`` orders them and is
    the NGram timestamp; windows of N frames become N*frame_len-token sequences. Frames
    of one stream segment live in one rowgroup (windows never cross rowgroups —
    reference caveat ngram.py:85-91), so rows_per_file bounds the window range."""
    from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_rows
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('Frames', [
        UnischemaField('frame_id', np.int64, (), ScalarCodec(), False),
        UnischemaField('tokens', np.int32, (frame_len,), NdarrayCodec(), False),
    ])
    rng = np.random.RandomState(seed)
    base = rng.randint(0, VOCAB, size=8, dtype=np.int32)
    stream = np.tile(base, num_frames * frame_len // 8 + 1)[:num_frames * frame_len]
    rows = [{'frame_id': i, 'tokens': stream[i * frame_len:(i + 1) * frame_len]
             .astype(np.int32)} for i in range(num_frames)]
    write_rows(url, schema, rows, rows_per_file=max(64, num_frames // 4),
               rowgroup_size_mb=64)
    return schema


def build_ragged_dataset(url, num_docs=256, max_len=48, seed=0):
    """Native Parquet list<int32> store of VARIABLE-length documents (the packed
    mode's input: no Unischema codec — ``make_batch_reader``'s native contract)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths

    fs, path = get_filesystem_and_path_or_paths(url)
    fs.create_dir(path, recursive=True)
    rng = np.random.RandomState(seed)
    docs = []
    for _ in range(num_docs):
        base = rng.randint(0, VOCAB, size=8, dtype=np.int32)
        n = int(rng.randint(8, max_len + 1))
        docs.append(np.tile(base, n // 8 + 1)[:n].astype(np.int32).tolist())
    per_file = max(1, num_docs // 4)
    for part in range(0, num_docs, per_file):
        chunk = docs[part:part + per_file]
        table = pa.table({
            'doc_id': np.arange(part, part + len(chunk), dtype=np.int64),
            'tokens': pa.array(chunk, type=pa.list_(pa.int32())),
        })
        with fs.open_output_stream('{}/part_{}.parquet'.format(path, part)) as sink:
            pq.write_table(table, sink)


def _make_data_seq_mesh(data_axis):
    """ONE definition of the example's (data, seq) device factoring: default data
    axis 2 on even device counts, seq takes the rest."""
    import jax

    from petastorm_tpu.parallel import make_mesh

    n_dev = len(jax.devices())
    if data_axis is None:
        data_axis = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
    if n_dev % data_axis:
        raise ValueError('data_axis {} does not divide device count {}'
                         .format(data_axis, n_dev))
    return make_mesh(('data', 'seq'), axis_sizes=(data_axis, n_dev // data_axis))


def train_packed(dataset_url, seq_len=64, batch_size=8, epochs=2, data_axis=None,
                 learning_rate=1e-2):
    """Packed-mode training, sequence-parallel: ragged docs -> worker-side first-fit
    packing (ops.packing.make_packing_transform) -> dense [batch, seq_len] device
    batches sharded ``P('data', 'seq')`` -> TransformerLM with SEGMENT-masked RING
    attention (segment ids ring-rotate with their K/V blocks), so packing composes
    with sequences longer than one chip. The model is constructed INSIDE the jitted
    step so each batch's segment ids flow through one compiled program — the pattern
    to copy for packed training."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from petastorm_tpu import make_batch_reader
    from petastorm_tpu.models import TransformerLM
    from petastorm_tpu.ops.packing import (make_packing_transform,
                                           packed_next_token_loss)
    from petastorm_tpu.ops.ring_attention import ring_attention_sharded
    from petastorm_tpu.parallel import JaxDataLoader

    mesh = _make_data_seq_mesh(data_axis)
    if seq_len % mesh.shape['seq']:
        raise ValueError('seq_len {} not divisible by the seq mesh axis ({}); pick '
                         'a multiple or set --data-axis'
                         .format(seq_len, mesh.shape['seq']))
    if batch_size % mesh.shape['data']:
        raise ValueError('batch_size {} not divisible by the data mesh axis ({})'
                         .format(batch_size, mesh.shape['data']))
    optimizer = optax.adam(learning_rate)
    ring = ring_attention_sharded(mesh, 'seq', causal=True, with_segments=True,
                                  batch_axis='data')

    def model_for(segments):
        return TransformerLM(vocab=VOCAB, embed=EMBED, heads=HEADS, layers=1,
                             dtype=jnp.float32, max_len=seq_len,
                             attention_fn=lambda q, k, v: ring(q, k, v, segments))

    @jax.jit
    def train_step(params, opt_state, tokens, segments, positions):
        model = model_for(segments)

        def loss_fn(p):
            # positions: the packer's per-segment restart column, so every packed
            # document's position embedding starts at 0 (the attention mask alone
            # only isolates segments — it does not fix their positions).
            return packed_next_token_loss(model.apply(p, tokens, positions),
                                          tokens, segments)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    reader = make_batch_reader(
        dataset_url, transform_spec=make_packing_transform('tokens', seq_len),
        num_epochs=epochs, shuffle_row_groups=True, seed=7)
    spec = {'tokens': P('data', 'seq'), 'tokens_segments': P('data', 'seq'),
            'tokens_positions': P('data', 'seq')}
    loss = params = opt_state = None
    with mesh:
        with JaxDataLoader(reader, batch_size=batch_size, mesh=mesh,
                           partition_spec=spec) as loader:
            for step, batch in enumerate(loader):
                tokens, segments = batch['tokens'], batch['tokens_segments']
                positions = batch['tokens_positions']
                if params is None:
                    # Params are independent of the (parameter-free) attention
                    # backend: init once with any segments.
                    params = model_for(segments).init(jax.random.PRNGKey(0), tokens,
                                                      positions)
                    opt_state = optimizer.init(params)
                params, opt_state, loss = train_step(params, opt_state, tokens,
                                                     segments, positions)
                if step % 20 == 0:
                    print('step {} loss {:.4f}'.format(step, float(loss)))
            print('input pipeline stats:', loader.stats.as_dict())
    if loss is None:
        raise ValueError(
            'no batches: the corpus packs into fewer than batch_size={} bins '
            '(packing compresses docs ~seq_len/mean_len-fold) — lower the batch '
            'size or add data'.format(batch_size))
    return params, float(loss)


def make_model(mesh):
    """The shared TransformerLM with ring attention injected over the mesh's ``seq``
    axis — the model family's documented sequence-parallel injection point
    (petastorm_tpu/models/transformer.py); the model itself stays mesh-agnostic."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from petastorm_tpu.models import TransformerLM
    from petastorm_tpu.ops.ring_attention import ring_attention
    from petastorm_tpu.parallel.mesh import shard_map_compat

    attn_spec = P('data', 'seq', None, None)
    ring = shard_map_compat(
        lambda q, k, v: ring_attention(q, k, v, axis_name='seq', causal=True),
        mesh, (attn_spec, attn_spec, attn_spec), attn_spec)
    return TransformerLM(vocab=VOCAB, embed=EMBED, heads=HEADS, layers=1,
                         dtype=jnp.float32, attention_fn=ring)


def make_train_step(mesh, model, learning_rate=1e-2):
    """Jitted train step over the (data, seq) mesh: embeddings/matmuls are GSPMD-sharded
    by the batch's PartitionSpec; attention runs as ring attention over the seq axis."""
    import jax
    import optax

    from petastorm_tpu.models import next_token_loss

    optimizer = optax.adam(learning_rate)

    @jax.jit
    def train_step(params, opt_state, tokens):
        if tokens.ndim == 3:
            # NGram window batch (batch, frames, frame_len): frames are consecutive
            # stream chunks, so flattening yields the contiguous training sequence.
            # With the frame axis sharded over 'seq' this reshape is shard-local.
            tokens = tokens.reshape(tokens.shape[0], -1)
        loss, grads = jax.value_and_grad(
            lambda p: next_token_loss(model.apply(p, tokens), tokens))(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return train_step, optimizer


def train(dataset_url, batch_size=8, epochs=2, data_axis=None, ngram_frames=0):
    import jax
    from jax.sharding import PartitionSpec as P

    from petastorm_tpu import make_reader
    from petastorm_tpu.parallel import JaxDataLoader

    mesh = _make_data_seq_mesh(data_axis)
    model = make_model(mesh)
    train_step, optimizer = make_train_step(mesh, model)

    if ngram_frames:
        from petastorm_tpu.ngram import NGram
        ngram = NGram({i: ['tokens'] for i in range(ngram_frames)},
                      delta_threshold=1, timestamp_field='frame_id')
        reader = make_reader(dataset_url, schema_fields=ngram, num_epochs=epochs,
                             shuffle_row_groups=True, seed=7)
        # windows arrive (batch, frames, frame_len): shard the frame axis over 'seq'
        spec = {'tokens': P('data', 'seq'), 'frame_id': P('data', 'seq')}
    else:
        reader = make_reader(dataset_url, schema_fields=['tokens'], num_epochs=epochs,
                             shuffle_row_groups=True, seed=7)
        spec = P('data', 'seq')

    loss = None
    params = opt_state = None
    with mesh:
        with JaxDataLoader(reader, batch_size=batch_size, mesh=mesh,
                           partition_spec=spec) as loader:
            for step, batch in enumerate(loader):
                if params is None:
                    # leading dim is the GLOBAL batch (batch_size x process_count)
                    tokens = batch['tokens']
                    params = model.init(jax.random.PRNGKey(0),
                                        jax.numpy.reshape(tokens,
                                                          (tokens.shape[0], -1)))
                    opt_state = optimizer.init(params)
                params, opt_state, loss = train_step(params, opt_state,
                                                     batch['tokens'])
                if step % 20 == 0:
                    print('step {} loss {:.4f}'.format(step, float(loss)))
            print('input pipeline stats:', loader.stats.as_dict())
    return params, float(loss)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default=None)
    parser.add_argument('--num-docs', type=int, default=256)
    parser.add_argument('--seq-len', type=int, default=512)
    parser.add_argument('--batch-size', type=int, default=8)
    parser.add_argument('--epochs', type=int, default=2)
    parser.add_argument('--data-axis', type=int, default=None,
                        help='mesh data-axis size (default: 2 if the device count is '
                             'even, else 1; seq axis gets the rest)')
    parser.add_argument('--ngram-frames', type=int, default=0,
                        help='assemble training sequences as NGram windows of this many '
                             'consecutive token frames (0 = pre-tokenized docs mode)')
    parser.add_argument('--packed', action='store_true',
                        help='variable-length docs packed into fixed bins inside the '
                             'reader workers (segment-masked attention + loss)')
    args = parser.parse_args()

    if args.packed:
        if args.ngram_frames:
            parser.error('--packed and --ngram-frames are mutually exclusive')
        if args.dataset_url:
            # Never write synthetic data into a user-provided store: packed mode
            # only auto-generates into its own tmp default.
            url = args.dataset_url
        else:
            # Doc lengths are capped by --seq-len (a doc longer than a bin cannot
            # pack); the cache path is keyed by the full geometry.
            max_len = min(48, args.seq_len)
            url = os.path.join(tempfile.gettempdir(),
                               'long_context_ragged_{}x{}'.format(args.num_docs,
                                                                  max_len))
            fs_path = url.replace('file://', '')
            if not os.path.exists(fs_path) or not os.listdir(fs_path):
                print('materializing {} ragged docs to {}'.format(args.num_docs,
                                                                  url))
                build_ragged_dataset(url, num_docs=args.num_docs, max_len=max_len)
        _, final_loss = train_packed(url, seq_len=args.seq_len,
                                     batch_size=args.batch_size,
                                     epochs=args.epochs,
                                     data_axis=args.data_axis)
        print('final loss: {:.4f}'.format(final_loss))
        return

    if args.ngram_frames:
        if args.seq_len % args.ngram_frames or args.seq_len < args.ngram_frames:
            parser.error('--ngram-frames ({}) must divide --seq-len ({})'
                         .format(args.ngram_frames, args.seq_len))
        # cache path keyed by the geometry: changing the flags must not silently
        # reuse a store with a different frame length
        suffix = '_frames_{}x{}'.format(args.num_docs,
                                        args.seq_len // args.ngram_frames)
    else:
        suffix = ''
    url = args.dataset_url or os.path.join(tempfile.gettempdir(),
                                           'long_context_demo' + suffix)
    if not os.path.exists(os.path.join(url.replace('file://', ''), '_common_metadata')):
        if args.ngram_frames:
            frame_len = args.seq_len // args.ngram_frames
            print('materializing {} frames x {} tokens to {}'.format(
                args.num_docs, frame_len, url))
            build_frame_dataset(url, num_frames=args.num_docs, frame_len=frame_len)
        else:
            print('materializing {} docs x {} tokens to {}'.format(
                args.num_docs, args.seq_len, url))
            build_dataset(url, args.num_docs, args.seq_len)
    _, final_loss = train(url, batch_size=args.batch_size, epochs=args.epochs,
                          data_axis=args.data_axis, ngram_frames=args.ngram_frames)
    print('final loss: {:.4f}'.format(final_loss))


if __name__ == '__main__':
    main()
