"""Scaling-families demo: expert-parallel MoE and pipeline-parallel training fed from
a petastorm_tpu store.

The reference's examples only scale data-parallel (torch DistributedSampler / Horovod
shard-by-rank); this example shows the two TPU-native families beyond dp, both fed by
the SAME input pipeline (``write_rows`` → ``make_reader`` → ``JaxDataLoader``):

- **default (ep)**: :class:`petastorm_tpu.models.MoETransformerLM` on a
  ``(data, expert)`` mesh — Switch-routed expert MLPs, expert weights placed by
  ``expert_partition_specs`` (leading experts axis over the ``'expert'`` mesh axis),
  the token all-to-all inserted by XLA from the sharding annotations.
- **``--pipeline-stages N`` (pp)**: dense transformer blocks pipelined over a
  ``('stage', 'data')`` mesh via :func:`petastorm_tpu.parallel.make_pipeline` — the
  GPipe microbatch schedule as one jitted SPMD program, gradients through
  ``ppermute``.

Run: ``python -m examples.moe.jax_example``
     ``python -m examples.moe.jax_example --pipeline-stages 4``
"""

import argparse
import os
import tempfile

import numpy as np

VOCAB = 256
EMBED = 64
HEADS = 4


def build_dataset(url, num_docs=256, seq_len=128, seed=0):
    """Synthetic learnable corpus — delegates to the long_context example's builder
    (ONE definition of the repeating-bigram language; both examples share VOCAB=256)
    so the two examples cannot diverge."""
    from examples.long_context.jax_example import build_dataset as build_docs
    return build_docs(url, num_docs=num_docs, seq_len=seq_len, seed=seed)


def train_moe(dataset_url, batch_size=8, epochs=2, expert_axis_size=None,
              learning_rate=1e-2):
    """Expert-parallel training: one step per loader batch on a (data, expert) mesh."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from petastorm_tpu import make_reader
    from petastorm_tpu.models import (MoETransformerLM, expert_partition_specs,
                                      moe_aux_total, next_token_loss)
    from petastorm_tpu.parallel import JaxDataLoader, make_mesh

    n_dev = len(jax.devices())
    if expert_axis_size is None:
        expert_axis_size = 4 if n_dev % 4 == 0 else (2 if n_dev % 2 == 0 else 1)
    if n_dev % expert_axis_size:
        raise ValueError('expert axis {} does not divide device count {}'
                         .format(expert_axis_size, n_dev))
    mesh = make_mesh(('data', 'expert'),
                     axis_sizes=(n_dev // expert_axis_size, expert_axis_size))
    model = MoETransformerLM(vocab=VOCAB, embed=EMBED, heads=HEADS, layers=2,
                             num_experts=max(2, expert_axis_size), moe_every=2,
                             dtype=jnp.float32, expert_axis='expert')
    optimizer = optax.adam(learning_rate)

    def loss_fn(params, tokens):
        logits, mods = model.apply(params, tokens, mutable='losses')
        return next_token_loss(logits, tokens) + moe_aux_total(mods, weight=0.01)

    @jax.jit
    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    reader = make_reader(dataset_url, schema_fields=['tokens'], num_epochs=epochs,
                         shuffle_row_groups=True, seed=7)
    loss = params = opt_state = None
    with mesh:
        with JaxDataLoader(reader, batch_size=batch_size, mesh=mesh,
                           partition_spec=P('data')) as loader:
            for step, batch in enumerate(loader):
                if params is None:
                    params = {'params': model.init(jax.random.PRNGKey(0),
                                                   batch['tokens'])['params']}
                    specs = expert_partition_specs(params)
                    params = jax.device_put(params, jax.tree.map(
                        lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda leaf: isinstance(leaf, P)))
                    opt_state = optimizer.init(params)
                params, opt_state, loss = train_step(params, opt_state,
                                                     batch['tokens'])
                if step % 20 == 0:
                    print('step {} loss {:.4f}'.format(step, float(loss)))
            print('input pipeline stats:', loader.stats.as_dict())
    return params, float(loss)


def train_pipeline(dataset_url, n_stages=4, batch_size=8, n_micro=2, epochs=2,
                   learning_rate=1e-2):
    """Pipeline-parallel training: embed → N pipelined Blocks → logits head, stage
    params sharded over 'stage', batch sharded over 'data', microbatches streamed
    through the GPipe schedule."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from petastorm_tpu import make_reader
    from petastorm_tpu.models.transformer import Block, dense_causal_attention
    from petastorm_tpu.parallel import (JaxDataLoader, make_mesh, make_pipeline,
                                        microbatch, stack_stage_params,
                                        stage_partition_specs)

    n_dev = len(jax.devices())
    if n_dev % n_stages:
        raise ValueError('stages {} do not divide device count {}'
                         .format(n_stages, n_dev))
    mesh = make_mesh(('stage', 'data'), axis_sizes=(n_stages, n_dev // n_stages))
    block = Block(heads=HEADS, attention_fn=dense_causal_attention,
                  dtype=jnp.float32)
    pipe = make_pipeline(lambda p, mb: block.apply({'params': p}, mb), mesh,
                         xs_spec=P(None, 'data', None, None),
                         out_spec=P(None, 'data', None, None))
    optimizer = optax.adam(learning_rate)

    def init_params(rng_key, seq_len):
        rng = np.random.RandomState(0)
        probe = jnp.zeros((2, seq_len, EMBED), jnp.float32)
        stacked = stack_stage_params(
            [block.init(jax.random.fold_in(rng_key, i), probe)['params']
             for i in range(n_stages)])
        stacked = jax.device_put(stacked, jax.tree.map(
            lambda s: NamedSharding(mesh, s), stage_partition_specs(stacked),
            is_leaf=lambda leaf: isinstance(leaf, P)))
        replicated = NamedSharding(mesh, P(None, None))
        extra = {
            'embed': jax.device_put(
                jnp.asarray(rng.randn(VOCAB, EMBED), jnp.float32) * 0.02, replicated),
            'w_out': jax.device_put(
                jnp.asarray(rng.randn(EMBED, VOCAB), jnp.float32) * 0.02, replicated),
        }
        return (stacked, extra)

    def loss_fn(params, tokens):
        stacked, extra = params
        xs = microbatch(extra['embed'][tokens], n_micro)   # [M, mb, T, E]
        logits = pipe(stacked, xs) @ extra['w_out']        # [M, mb, T, V]
        logp = jax.nn.log_softmax(logits[:, :, :-1], axis=-1)
        targets = microbatch(tokens, n_micro)[:, :, 1:]
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
        return jnp.mean(nll)

    @jax.jit
    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    reader = make_reader(dataset_url, schema_fields=['tokens'], num_epochs=epochs,
                         shuffle_row_groups=True, seed=7)
    loss = params = opt_state = None
    with mesh:
        with JaxDataLoader(reader, batch_size=batch_size, mesh=mesh,
                           partition_spec=P('data')) as loader:
            for step, batch in enumerate(loader):
                if params is None:
                    params = init_params(jax.random.PRNGKey(0),
                                         batch['tokens'].shape[1])
                    opt_state = optimizer.init(params)
                params, opt_state, loss = train_step(params, opt_state,
                                                     batch['tokens'])
                if step % 20 == 0:
                    print('step {} loss {:.4f}'.format(step, float(loss)))
            print('input pipeline stats:', loader.stats.as_dict())
    return params, float(loss)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default=None)
    parser.add_argument('--num-docs', type=int, default=256)
    parser.add_argument('--seq-len', type=int, default=128)
    parser.add_argument('--batch-size', type=int, default=8)
    parser.add_argument('--epochs', type=int, default=2)
    parser.add_argument('--expert-axis', type=int, default=None,
                        help='expert mesh-axis size (default: 4 when the device '
                             'count divides, else 2, else 1)')
    parser.add_argument('--pipeline-stages', type=int, default=0,
                        help='train the pipeline-parallel configuration with this '
                             'many stages instead of the MoE one (0 = MoE)')
    parser.add_argument('--microbatches', type=int, default=2)
    args = parser.parse_args()

    url = args.dataset_url or os.path.join(
        tempfile.gettempdir(), 'moe_demo_{}x{}'.format(args.num_docs, args.seq_len))
    if not os.path.exists(os.path.join(url.replace('file://', ''),
                                       '_common_metadata')):
        print('materializing {} docs x {} tokens to {}'.format(
            args.num_docs, args.seq_len, url))
        build_dataset(url, args.num_docs, args.seq_len)
    if args.pipeline_stages:
        _, final_loss = train_pipeline(url, n_stages=args.pipeline_stages,
                                       batch_size=args.batch_size,
                                       n_micro=args.microbatches,
                                       epochs=args.epochs)
    else:
        _, final_loss = train_moe(url, batch_size=args.batch_size,
                                  epochs=args.epochs,
                                  expert_axis_size=args.expert_axis)
    print('final loss: {:.4f}'.format(final_loss))


if __name__ == '__main__':
    main()
