"""ImageNet Unischema (parity: reference examples/imagenet/schema.py — a noun id, the
label text, and a variable-size RGB image stored through CompressedImageCodec png)."""

import numpy as np

from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
from petastorm_tpu.unischema import Unischema, UnischemaField

ImagenetSchema = Unischema('ImagenetSchema', [
    UnischemaField('noun_id', np.str_, (), ScalarCodec(np.str_), False),
    UnischemaField('text', np.str_, (), ScalarCodec(np.str_), False),
    UnischemaField('image', np.uint8, (None, None, 3), CompressedImageCodec('png'), False),
])
