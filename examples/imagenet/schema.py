"""ImageNet Unischema (parity: reference examples/imagenet/schema.py — a noun id, the
label text, and a variable-size RGB image stored through CompressedImageCodec png)."""

import numpy as np

from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
from petastorm_tpu.unischema import Unischema, UnischemaField

ImagenetSchema = Unischema('ImagenetSchema', [
    UnischemaField('noun_id', np.str_, (), ScalarCodec(np.str_), False),
    UnischemaField('text', np.str_, (), ScalarCodec(np.str_), False),
    UnischemaField('image', np.uint8, (None, None, 3), CompressedImageCodec('png'), False),
])


def dct_imagenet_schema(image_hw, quality=90):
    """Fixed-size DCT-domain variant (SURVEY.md §7.3 on-chip decode): images resized to
    ``image_hw`` at write time and stored as quantized DCT coefficients, so readers can
    either decode on the host (default) or ship int16 coefficients straight to the chip
    (``make_reader(..., field_overrides=[dct_coefficients_field(image_hw)])``)."""
    from petastorm_tpu.codecs import DctImageCodec
    if image_hw % 8:
        raise ValueError('image_hw must be a multiple of 8, got {}'.format(image_hw))
    return Unischema('DctImagenetSchema', [
        UnischemaField('noun_id', np.str_, (), ScalarCodec(np.str_), False),
        UnischemaField('text', np.str_, (), ScalarCodec(np.str_), False),
        UnischemaField('image', np.uint8, (image_hw, image_hw, 3),
                       DctImageCodec(quality=quality), False),
    ])


def dct_coefficients_field(image_hw, quality=90):
    """The read-time override that makes workers emit raw coefficient blocks."""
    from petastorm_tpu.codecs import DctCoefficientsCodec
    if image_hw % 8:
        raise ValueError('image_hw must be a multiple of 8, got {}'.format(image_hw))
    return UnischemaField('image', np.int16, (image_hw // 8, image_hw // 8, 8, 8, 3),
                          DctCoefficientsCodec(quality=quality), False)
