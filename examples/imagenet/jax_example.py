"""Train ResNet from an ImageNet-style petastorm_tpu dataset — TPU-native flagship image
pipeline (no direct reference analog: the reference only materializes ImageNet,
examples/imagenet/generate_petastorm_imagenet.py; here we also consume it). Variable-size
stored images are center-cropped/resized on the host worker (TransformSpec) to a static
shape so every device batch is XLA-friendly; normalization + augmentation run on-chip
(petastorm_tpu.ops.image).

Run: ``python -m examples.imagenet.jax_example --dataset-url file:///tmp/imagenet``
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from examples.imagenet.schema import ImagenetSchema  # noqa: F401  (schema parity anchor)
from petastorm_tpu import make_reader
from petastorm_tpu.models.resnet import ResNet
from petastorm_tpu.ops.image import normalize_image, random_crop_flip
from petastorm_tpu.parallel.loader import JaxDataLoader
from petastorm_tpu.transform import TransformSpec

IMAGE_HW = 64


def make_transform(class_to_label, image_hw=IMAGE_HW):
    def _transform(row):
        image = row['image']
        h, w = image.shape[:2]
        side = min(h, w)
        top, left = (h - side) // 2, (w - side) // 2
        square = image[top:top + side, left:left + side]
        # Nearest-neighbor host resize (index gather) — cheap and codec-agnostic.
        idx = (np.arange(image_hw) * side // image_hw)
        row['image'] = square[idx][:, idx]
        row['label'] = np.int32(class_to_label[row['noun_id']])
        return row

    return TransformSpec(_transform,
                         edit_fields=[('image', np.uint8, (image_hw, image_hw, 3), False),
                                      ('label', np.int32, (), False)],
                         selected_fields=['image', 'label'])


def train(dataset_url, batch_size=8, epochs=1, learning_rate=1e-3,
          stage_sizes=(1, 1, 1, 1), num_filters=16):
    with make_reader(dataset_url, schema_fields=['noun_id'], num_epochs=1,
                     shuffle_row_groups=False) as scan_reader:
        nouns = sorted({row.noun_id for row in scan_reader})
    class_to_label = {noun: i for i, noun in enumerate(nouns)}

    model = ResNet(stage_sizes=list(stage_sizes), num_classes=len(nouns),
                   num_filters=num_filters)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, IMAGE_HW, IMAGE_HW, 3)))
    params, batch_stats = variables['params'], variables['batch_stats']
    optimizer = optax.adam(learning_rate)
    opt_state = optimizer.init(params)

    @jax.jit
    def train_step(params, batch_stats, opt_state, rng, images, labels):
        # On-chip preprocessing: crop/flip augment + bf16 normalize (ops/image.py).
        images = random_crop_flip(rng, images, (IMAGE_HW - 8, IMAGE_HW - 8))
        images = normalize_image(images, mean=127.5, std=127.5)

        def loss_fn(p):
            logits, updates = model.apply({'params': p, 'batch_stats': batch_stats},
                                          images, train=True, mutable=['batch_stats'])
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
            return loss, updates['batch_stats']

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_stats, opt_state, loss

    rng = jax.random.PRNGKey(1)
    loss = None
    transform = make_transform(class_to_label)
    with make_reader(dataset_url, num_epochs=epochs, transform_spec=transform,
                     shuffle_rows=True, seed=0) as reader:
        loader = JaxDataLoader(reader, batch_size=batch_size, drop_last=True)
        for step, batch in enumerate(loader):
            rng, step_rng = jax.random.split(rng)
            params, batch_stats, opt_state, loss = train_step(
                params, batch_stats, opt_state, step_rng,
                batch['image'], batch['label'])
            print('step {} loss {:.4f}'.format(step, loss))
    return params, batch_stats, (float(loss) if loss is not None else None)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/imagenet')
    parser.add_argument('--batch-size', type=int, default=8)
    parser.add_argument('--epochs', type=int, default=1)
    args = parser.parse_args()
    train(args.dataset_url, batch_size=args.batch_size, epochs=args.epochs)


if __name__ == '__main__':
    main()
