"""Train ResNet from an ImageNet-style petastorm_tpu dataset — TPU-native flagship image
pipeline (no direct reference analog: the reference only materializes ImageNet,
examples/imagenet/generate_petastorm_imagenet.py; here we also consume it). Variable-size
stored images are center-cropped/resized on the host worker (TransformSpec) to a static
shape so every device batch is XLA-friendly; normalization + augmentation run on-chip
(petastorm_tpu.ops.image).

Run: ``python -m examples.imagenet.jax_example --dataset-url file:///tmp/imagenet``
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from examples.imagenet.schema import ImagenetSchema  # noqa: F401  (schema parity anchor)
from petastorm_tpu import make_reader
from petastorm_tpu.models.resnet import ResNet
from petastorm_tpu.ops.image import normalize_image, random_crop_flip
from petastorm_tpu.parallel.loader import JaxDataLoader
from petastorm_tpu.transform import TransformSpec

IMAGE_HW = 64


def make_transform(class_to_label, image_hw=IMAGE_HW):
    from examples.imagenet.generate_petastorm_imagenet import _center_resize

    def _transform(row):
        row['image'] = _center_resize(row['image'], image_hw)
        row['label'] = np.int32(class_to_label[row['noun_id']])
        return row

    return TransformSpec(_transform,
                         edit_fields=[('image', np.uint8, (image_hw, image_hw, 3), False),
                                      ('label', np.int32, (), False)],
                         selected_fields=['image', 'label'])


def make_label_transform(class_to_label, image_field_spec):
    """Label mapping for a fixed-size store (DCT or raw): keeps the image field as-is
    (host decode already yields a static shape — or raw coefficient blocks under a
    field override) and adds the integer label."""
    def _transform(row):
        row['label'] = np.int32(class_to_label[row['noun_id']])
        return row

    return TransformSpec(_transform,
                         edit_fields=[image_field_spec, ('label', np.int32, (), False)],
                         selected_fields=['image', 'label'])


def train(dataset_url, batch_size=8, epochs=1, learning_rate=1e-3,
          stage_sizes=(1, 1, 1, 1), num_filters=16, on_chip_decode=False,
          image_hw=IMAGE_HW, dct_quality=90, reader_pool_type='thread',
          workers_count=4, prefetch=2, scan_chunk=0, verbose=True):
    """``on_chip_decode=True`` reads a DCT-domain store (generate with ``--dct-hw``)
    through a field override so workers ship raw int16 coefficient blocks; dequant +
    IDCT + color conversion then run inside the jitted train step on the device
    (SURVEY.md §7.3 — the decode FLOPs land on the MXU, the host never runs an IDCT)."""
    with make_reader(dataset_url, schema_fields=['noun_id'], num_epochs=1,
                     shuffle_row_groups=False) as scan_reader:
        nouns = sorted({row.noun_id for row in scan_reader})
    class_to_label = {noun: i for i, noun in enumerate(nouns)}

    model = ResNet(stage_sizes=list(stage_sizes), num_classes=len(nouns),
                   num_filters=num_filters)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, image_hw, image_hw, 3)))
    params, batch_stats = variables['params'], variables['batch_stats']
    optimizer = optax.adam(learning_rate)
    opt_state = optimizer.init(params)

    @jax.jit
    def train_step(params, batch_stats, opt_state, rng, images, labels):
        if on_chip_decode:
            from petastorm_tpu.ops.image_decode import dct_decode_images_jax
            images = dct_decode_images_jax(images, quality=dct_quality)
        # On-chip preprocessing: crop/flip augment + bf16 normalize (ops/image.py).
        images = random_crop_flip(rng, images, (image_hw - 8, image_hw - 8))
        images = normalize_image(images, mean=127.5, std=127.5)

        def loss_fn(p):
            logits, updates = model.apply({'params': p, 'batch_stats': batch_stats},
                                          images, train=True, mutable=['batch_stats'])
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
            return loss, updates['batch_stats']

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_stats, opt_state, loss

    rng = jax.random.PRNGKey(1)
    loss = None
    if on_chip_decode:
        from examples.imagenet.schema import dct_coefficients_field
        override = dct_coefficients_field(image_hw, quality=dct_quality)
        transform = make_label_transform(
            class_to_label, ('image', np.int16,
                             (image_hw // 8, image_hw // 8, 8, 8, 3), False))
        reader_kwargs = dict(field_overrides=[override], transform_spec=transform)
    else:
        reader_kwargs = dict(transform_spec=make_transform(class_to_label,
                                                           image_hw=image_hw))
    with make_reader(dataset_url, num_epochs=epochs, shuffle_rows=True, seed=0,
                     reader_pool_type=reader_pool_type, workers_count=workers_count,
                     **reader_kwargs) as reader:
        loader = JaxDataLoader(reader, batch_size=batch_size, drop_last=True,
                               prefetch=prefetch)
        if scan_chunk:
            # Compiled-chunk streaming: one upload + one dispatch per scan_chunk
            # batches (JaxDataLoader.scan_stream) — the dispatch-bound config for
            # larger-than-HBM stores; the augmentation rng rides the carry.
            def scan_body(carry, batch):
                params, batch_stats, opt_state, rng = carry
                rng, step_rng = jax.random.split(rng)
                params, batch_stats, opt_state, loss = train_step(
                    params, batch_stats, opt_state, step_rng,
                    batch['image'], batch['label'])
                return (params, batch_stats, opt_state, rng), loss

            (params, batch_stats, opt_state, rng), losses = loader.scan_stream(
                scan_body, (params, batch_stats, opt_state, rng),
                chunk_batches=scan_chunk, seed=0)
            loss = losses[-1][-1] if losses else None
            if verbose:
                for chunk in losses:
                    for l in np.asarray(chunk):
                        print('loss {:.4f}'.format(float(l)))
        else:
            for step, batch in enumerate(loader):
                rng, step_rng = jax.random.split(rng)
                params, batch_stats, opt_state, loss = train_step(
                    params, batch_stats, opt_state, step_rng,
                    batch['image'], batch['label'])
                if verbose:
                    print('step {} loss {:.4f}'.format(step, loss))
        stats = loader.stats.as_dict()
        if verbose:
            print('input pipeline stats:', stats)
    return params, batch_stats, (float(loss) if loss is not None else None), stats


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/imagenet')
    parser.add_argument('--batch-size', type=int, default=8)
    parser.add_argument('--epochs', type=int, default=1)
    parser.add_argument('--on-chip-decode', action='store_true',
                        help='read a --dct-hw store and decode on the device')
    parser.add_argument('--image-hw', type=int, default=IMAGE_HW)
    parser.add_argument('--stage-sizes', type=int, nargs='+', default=[1, 1, 1, 1],
                        help='ResNet stage depths, e.g. 3 4 6 3 for ResNet50')
    parser.add_argument('--num-filters', type=int, default=16)
    parser.add_argument('--pool', default='thread',
                        choices=['thread', 'process', 'dummy'],
                        help='reader worker pool (process = spawned workers + '
                             'Arrow IPC wire; the larger-than-HBM streaming config)')
    parser.add_argument('--workers', type=int, default=4)
    parser.add_argument('--prefetch', type=int, default=2)
    parser.add_argument('--scan-chunk', type=int, default=0,
                        help='>0: drive training through scan_stream with this '
                             'many batches per compiled chunk (one H2D + one '
                             'dispatch per chunk)')
    args = parser.parse_args()
    train(args.dataset_url, batch_size=args.batch_size, epochs=args.epochs,
          on_chip_decode=args.on_chip_decode, image_hw=args.image_hw,
          stage_sizes=tuple(args.stage_sizes), num_filters=args.num_filters,
          reader_pool_type=args.pool, workers_count=args.workers,
          prefetch=args.prefetch, scan_chunk=args.scan_chunk)


if __name__ == '__main__':
    main()
