"""Materialize an ImageNet-style petastorm_tpu dataset (parity: reference
examples/imagenet/generate_petastorm_imagenet.py, which scans an on-disk ImageNet tree
with Spark; here either a directory of ``<noun_id>/*.jpg|png`` images or an offline
synthetic mode).

Run: ``python -m examples.imagenet.generate_petastorm_imagenet -o file:///tmp/imagenet
--synthetic``
"""

import argparse
import os

import numpy as np

from examples.imagenet.schema import ImagenetSchema
from petastorm_tpu.etl.dataset_metadata import write_rows

SYNTHETIC_NOUNS = {'n01440764': 'tench', 'n01443537': 'goldfish', 'n01484850': 'shark'}


def synthetic_imagenet_rows(images_per_class=4, seed=0, hw=(96, 128)):
    rng = np.random.default_rng(seed)
    rows = []
    for noun_id, text in SYNTHETIC_NOUNS.items():
        for _ in range(images_per_class):
            h = int(rng.integers(hw[0], hw[1]))
            w = int(rng.integers(hw[0], hw[1]))
            rows.append({'noun_id': noun_id, 'text': text,
                         'image': rng.integers(0, 255, size=(h, w, 3),
                                               dtype=np.uint8)})
    return rows


def directory_imagenet_rows(imagenet_dir, noun_id_to_text=None):
    """Scan ``<imagenet_dir>/<noun_id>/*`` images into rows."""
    import cv2
    rows = []
    for noun_id in sorted(os.listdir(imagenet_dir)):
        class_dir = os.path.join(imagenet_dir, noun_id)
        if not os.path.isdir(class_dir):
            continue
        text = (noun_id_to_text or {}).get(noun_id, noun_id)
        for name in sorted(os.listdir(class_dir)):
            image_bgr = cv2.imread(os.path.join(class_dir, name))
            if image_bgr is None:
                continue
            rows.append({'noun_id': noun_id, 'text': text,
                         'image': cv2.cvtColor(image_bgr, cv2.COLOR_BGR2RGB)})
    return rows


def _center_resize(image, hw):
    """Center-crop to square + nearest-neighbor resize to (hw, hw) — host numpy."""
    h, w = image.shape[:2]
    side = min(h, w)
    top, left = (h - side) // 2, (w - side) // 2
    square = image[top:top + side, left:left + side]
    idx = np.arange(hw) * side // hw
    return np.ascontiguousarray(square[idx][:, idx])


def generate_petastorm_imagenet(output_url, imagenet_dir=None, synthetic=False,
                                rowgroup_size_mb=8, dct_hw=None, dct_quality=90):
    """``dct_hw`` switches to the fixed-size DCT-domain store (schema.py
    dct_imagenet_schema): images are resized at write time and stored as quantized DCT
    coefficient blocks so readers can decode on-chip."""
    rows = (synthetic_imagenet_rows() if synthetic
            else directory_imagenet_rows(imagenet_dir))
    if dct_hw is not None:
        from examples.imagenet.schema import dct_imagenet_schema
        for row in rows:
            row['image'] = _center_resize(row['image'], dct_hw)
        schema = dct_imagenet_schema(dct_hw, quality=dct_quality)
    else:
        schema = ImagenetSchema
    write_rows(output_url, schema, rows, rowgroup_size_mb=rowgroup_size_mb)
    print('wrote {} rows to {}'.format(len(rows), output_url))


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('-o', '--output-url', default='file:///tmp/imagenet')
    parser.add_argument('-i', '--imagenet-dir', default=None,
                        help='directory of <noun_id>/*.jpg class folders')
    parser.add_argument('--synthetic', action='store_true',
                        help='generate random images instead of scanning a directory')
    parser.add_argument('--dct-hw', type=int, default=None,
                        help='write the DCT-domain store with images resized to this '
                             'size (multiple of 8) for on-chip decode')
    parser.add_argument('--dct-quality', type=int, default=90)
    args = parser.parse_args()
    generate_petastorm_imagenet(args.output_url, imagenet_dir=args.imagenet_dir,
                                synthetic=args.synthetic or args.imagenet_dir is None,
                                dct_hw=args.dct_hw, dct_quality=args.dct_quality)


if __name__ == '__main__':
    main()
