"""Train a small torch CNN from a petastorm_tpu dataset (parity: reference
examples/mnist/pytorch_example.py — kept as an adapter demo; the JAX example is the
primary TPU path)."""

import argparse

import numpy as np
import torch
import torch.nn as tnn
import torch.nn.functional as F

from examples.mnist import DEFAULT_MNIST_DATA_PATH
from petastorm_tpu import make_reader
from petastorm_tpu.pytorch import DataLoader
from petastorm_tpu.transform import TransformSpec


class Net(tnn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = tnn.Conv2d(1, 10, kernel_size=5)
        self.conv2 = tnn.Conv2d(10, 20, kernel_size=5)
        self.fc1 = tnn.Linear(320, 50)
        self.fc2 = tnn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2(x), 2))
        x = x.view(-1, 320)
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def _transform_row(row):
    row['image'] = ((row['image'].astype(np.float32) - 127.5) / 127.5)[None, ...]
    return row


TRANSFORM = TransformSpec(_transform_row,
                          edit_fields=[('image', np.float32, (1, 28, 28), False)])


def train(model, device, train_loader, optimizer, log_interval=50):
    model.train()
    for batch_idx, batch in enumerate(train_loader):
        data, target = batch['image'].to(device), batch['digit'].to(device)
        optimizer.zero_grad()
        loss = F.nll_loss(model(data), target)
        loss.backward()
        optimizer.step()
        if batch_idx % log_interval == 0:
            print('train batch {} loss {:.4f}'.format(batch_idx, loss.item()))


def test(model, device, test_loader):
    model.eval()
    correct = total = 0
    with torch.no_grad():
        for batch in test_loader:
            data, target = batch['image'].to(device), batch['digit'].to(device)
            pred = model(data).argmax(dim=1)
            correct += int((pred == target).sum())
            total += int(target.shape[0])
    print('test accuracy: {}/{}'.format(correct, total))
    return correct / max(1, total)


def main(args=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url',
                        default='file://{}'.format(DEFAULT_MNIST_DATA_PATH))
    parser.add_argument('--batch-size', type=int, default=64)
    parser.add_argument('--epochs', type=int, default=1)
    parser.add_argument('--lr', type=float, default=1e-3)
    opts = parser.parse_args(args)

    device = torch.device('cpu')
    model = Net().to(device)
    optimizer = torch.optim.Adam(model.parameters(), lr=opts.lr)
    base = opts.dataset_url.rstrip('/')
    for _ in range(opts.epochs):
        with DataLoader(make_reader('{}/train'.format(base), transform_spec=TRANSFORM,
                                    num_epochs=1),
                        batch_size=opts.batch_size) as train_loader:
            train(model, device, train_loader, optimizer)
    with DataLoader(make_reader('{}/test'.format(base), transform_spec=TRANSFORM,
                                num_epochs=1),
                    batch_size=opts.batch_size) as test_loader:
        return test(model, device, test_loader)


if __name__ == '__main__':
    main()
