"""MNIST Unischema (parity: reference examples/mnist/schema.py:21-25 — idx/digit scalars
plus a (28, 28) uint8 image stored through NdarrayCodec)."""

import numpy as np

from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.unischema import Unischema, UnischemaField

MnistSchema = Unischema('MnistSchema', [
    UnischemaField('idx', np.int64, (), ScalarCodec(np.int64), False),
    UnischemaField('digit', np.int64, (), ScalarCodec(np.int64), False),
    UnischemaField('image', np.uint8, (28, 28), NdarrayCodec(), False),
])
