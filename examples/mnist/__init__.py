import os

DEFAULT_MNIST_DATA_PATH = os.path.join(os.path.abspath(os.sep), 'tmp', 'mnist')
