"""Materialize MNIST (train + test splits) as petastorm_tpu datasets (parity: reference
examples/mnist/generate_petastorm_mnist.py, minus its Spark dependency).

Two sources:
- ``--source torchvision`` downloads real MNIST via torchvision (requires network).
- ``--source synthetic`` (default) generates MNIST-shaped random digits offline —
  each digit's image is a noisy constant block so a model can actually learn to
  separate the classes in smoke tests.

Run: ``python -m examples.mnist.generate_petastorm_mnist -o file:///tmp/mnist``
"""

import argparse

import numpy as np

from examples.mnist import DEFAULT_MNIST_DATA_PATH
from examples.mnist.schema import MnistSchema
from petastorm_tpu.etl.dataset_metadata import write_rows


def synthetic_mnist_rows(count, seed=0):
    """MNIST-shaped rows: label-dependent mean intensity + noise (learnable)."""
    rng = np.random.default_rng(seed)
    rows = []
    for idx in range(count):
        digit = int(rng.integers(10))
        base = np.full((28, 28), 20 + digit * 23, dtype=np.float32)
        noise = rng.normal(0, 10, size=(28, 28)).astype(np.float32)
        image = np.clip(base + noise, 0, 255).astype(np.uint8)
        rows.append({'idx': idx, 'digit': digit, 'image': image})
    return rows


def torchvision_mnist_rows(download_dir, train=True):
    from torchvision import datasets
    data = datasets.MNIST(download_dir, train=train, download=True)
    return [{'idx': idx, 'digit': int(digit), 'image': np.array(image, dtype=np.uint8)}
            for idx, (image, digit) in enumerate(data)]


def mnist_data_to_petastorm_dataset(output_url, source='synthetic', download_dir=None,
                                    train_count=600, test_count=100,
                                    rowgroup_size_mb=1):
    for split, count in (('train', train_count), ('test', test_count)):
        if source == 'torchvision':
            rows = torchvision_mnist_rows(download_dir, train=(split == 'train'))
        else:
            rows = synthetic_mnist_rows(count, seed=0 if split == 'train' else 1)
        split_url = '{}/{}'.format(output_url.rstrip('/'), split)
        write_rows(split_url, MnistSchema, rows, rowgroup_size_mb=rowgroup_size_mb)
        print('wrote {} rows to {}'.format(len(rows), split_url))


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('-o', '--output-url',
                        default='file://{}'.format(DEFAULT_MNIST_DATA_PATH))
    parser.add_argument('-s', '--source', choices=['synthetic', 'torchvision'],
                        default='synthetic')
    parser.add_argument('-d', '--download-dir', default='/tmp/mnist_download')
    parser.add_argument('--train-count', type=int, default=600)
    parser.add_argument('--test-count', type=int, default=100)
    args = parser.parse_args()
    mnist_data_to_petastorm_dataset(args.output_url, source=args.source,
                                    download_dir=args.download_dir,
                                    train_count=args.train_count,
                                    test_count=args.test_count)


if __name__ == '__main__':
    main()
