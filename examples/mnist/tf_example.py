"""Train a small Keras CNN from a petastorm_tpu dataset via tf.data (parity: reference
examples/mnist/tf_example.py — adapter demo; the JAX example is the primary TPU path)."""

import argparse

import numpy as np

from examples.mnist import DEFAULT_MNIST_DATA_PATH
from petastorm_tpu import make_reader
from petastorm_tpu.tf_utils import make_petastorm_dataset
from petastorm_tpu.transform import TransformSpec


def _transform_row(row):
    row['image'] = ((row['image'].astype(np.float32) - 127.5) / 127.5)[..., None]
    return row


TRANSFORM = TransformSpec(_transform_row,
                          edit_fields=[('image', np.float32, (28, 28, 1), False)],
                          selected_fields=['digit', 'image'])


def train_and_test(dataset_url, batch_size=64, epochs=1, steps=50):
    import tensorflow as tf

    model = tf.keras.Sequential([
        tf.keras.layers.Conv2D(16, 3, activation='relu', input_shape=(28, 28, 1)),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(64, activation='relu'),
        tf.keras.layers.Dense(10, activation='softmax'),
    ])
    model.compile(optimizer='adam', loss='sparse_categorical_crossentropy',
                  metrics=['accuracy'])

    base = dataset_url.rstrip('/')
    with make_reader('{}/train'.format(base), transform_spec=TRANSFORM,
                     num_epochs=None) as train_reader:
        with make_reader('{}/test'.format(base), transform_spec=TRANSFORM,
                         num_epochs=None) as test_reader:
            train_ds = (make_petastorm_dataset(train_reader)
                        .map(lambda row: (row.image, row.digit))
                        .batch(batch_size))
            test_ds = (make_petastorm_dataset(test_reader)
                       .map(lambda row: (row.image, row.digit))
                       .batch(batch_size))
            model.fit(train_ds, epochs=epochs, steps_per_epoch=steps, verbose=1)
            metrics = model.evaluate(test_ds, steps=max(1, steps // 5), verbose=0)
    print('test loss/accuracy:', metrics)
    return metrics


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url',
                        default='file://{}'.format(DEFAULT_MNIST_DATA_PATH))
    parser.add_argument('--batch-size', type=int, default=64)
    parser.add_argument('--epochs', type=int, default=1)
    parser.add_argument('--steps', type=int, default=50)
    args = parser.parse_args()
    train_and_test(args.dataset_url, batch_size=args.batch_size, epochs=args.epochs,
                   steps=args.steps)


if __name__ == '__main__':
    main()
