"""Train the flax MNIST CNN from a petastorm_tpu dataset — the TPU-native flagship
example (replaces the reference's torch/TF MNIST mains, examples/mnist/pytorch_example.py
/ tf_example.py, as the primary consumer). The loader feeds device-sharded bf16 batches;
the train step is a single jitted function (MXU-friendly, no host round-trips per step).

Run: ``python -m examples.mnist.jax_example --dataset-url file:///tmp/mnist``
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from examples.mnist import DEFAULT_MNIST_DATA_PATH
from petastorm_tpu import make_reader
from petastorm_tpu.models.mnist import MnistCNN
from petastorm_tpu.parallel.loader import JaxDataLoader
from petastorm_tpu.transform import TransformSpec


def _transform_row(row):
    # Normalize on the host worker; stays uint8->float32 here, cast to bf16 on device.
    row['image'] = (row['image'].astype(np.float32) - 127.5) / 127.5
    return row


TRANSFORM = TransformSpec(_transform_row, edit_fields=[('image', np.float32, (28, 28), False)])


def make_train_step(model, optimizer):
    def loss_fn(params, images, labels):
        logits = model.apply({'params': params}, images[..., None])
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
        accuracy = (logits.argmax(-1) == labels).mean()
        return loss, accuracy

    @jax.jit
    def train_step(params, opt_state, images, labels):
        (loss, accuracy), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, images, labels)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, accuracy

    return train_step


def train(dataset_url, batch_size=128, epochs=1, learning_rate=1e-3,
          shuffling_queue_capacity=None, checkpoint_dir=None, save_every=100,
          max_steps=None):
    """Streaming training. With ``checkpoint_dir``, the model AND the input position
    save atomically every ``save_every`` steps (``TrainingCheckpointer``) and a
    restart resumes mid-epoch from the saved position — item-granular,
    at-least-once (a partially delivered rowgroup is re-read whole; see
    ``JaxDataLoader.state_dict``). Delivery-exact input accounting needs an
    unbuffered stream, so the checkpointed configuration runs without the shuffling
    buffer (rowgroup + in-rowgroup shuffle still apply) and rejects an explicit
    ``shuffling_queue_capacity``."""
    if checkpoint_dir and shuffling_queue_capacity:
        raise ValueError('checkpoint_dir needs the unbuffered stream; do not pass '
                         'shuffling_queue_capacity with it')
    if shuffling_queue_capacity is None:
        shuffling_queue_capacity = 0 if checkpoint_dir else 1024
    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))['params']
    optimizer = optax.adam(learning_rate)
    opt_state = optimizer.init(params)
    train_step = make_train_step(model, optimizer)

    ckpt = resume_state = None
    start_step = 0
    loss = accuracy = None
    try:
        if checkpoint_dir:
            from petastorm_tpu.parallel import TrainingCheckpointer
            ckpt = TrainingCheckpointer(checkpoint_dir,
                                        save_interval_steps=save_every)
            if ckpt.latest_step is not None:
                (params, opt_state), loader_state = ckpt.restore((params, opt_state))
                resume_state = loader_state['reader'] if loader_state else None
                start_step = int(ckpt.latest_step) + 1
                print('resuming from step {} (input position restored)'.format(
                    start_step))
        try:
            reader = make_reader('{}/train'.format(dataset_url.rstrip('/')),
                                 num_epochs=epochs, transform_spec=TRANSFORM,
                                 shuffle_rows=True, seed=42,
                                 resume_state=resume_state)
        except ValueError as exc:
            if resume_state is not None and 'already consumed' in str(exc):
                # The reader refuses an all-consumed resume by design; for the
                # example a completed run restarting is informational, not an error.
                print('nothing left to train: input fully consumed at resume point')
                return params, None, None
            raise
        with reader:
            loader = JaxDataLoader(reader, batch_size=batch_size,
                                   shuffling_queue_capacity=shuffling_queue_capacity,
                                   seed=42)
            for step, batch in enumerate(loader, start=start_step):
                params, opt_state, loss, accuracy = train_step(
                    params, opt_state, batch['image'], batch['digit'])
                if ckpt is not None:
                    ckpt.save(step, (params, opt_state), loader=loader)
                if step % 50 == 0:
                    print('step {} loss {:.4f} acc {:.3f}'.format(step, loss,
                                                                  accuracy))
                if max_steps is not None and step - start_step + 1 >= max_steps:
                    break
            print('input pipeline stats:', loader.stats.as_dict())
    finally:
        if ckpt is not None:
            ckpt.wait_until_finished()
            ckpt.close()
    if loss is None:
        # A resume can also yield zero batches without tripping the reader's
        # all-consumed guard (e.g. only a drop_last partial batch remained).
        print('nothing left to train: input fully consumed at resume point')
        return params, None, None
    return params, float(loss), float(accuracy)


def train_inmem(dataset_url, batch_size=128, epochs=1, learning_rate=1e-3):
    """The recommended configuration for fits-in-HBM datasets: fill once, then run
    each epoch — shuffle, gather, and every train step — as ONE compiled program via
    ``InMemJaxLoader.scan_epochs`` (zero host involvement after the fill)."""
    from petastorm_tpu.parallel import InMemJaxLoader

    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))['params']
    optimizer = optax.adam(learning_rate)
    opt_state = optimizer.init(params)
    train_step = make_train_step(model, optimizer)

    reader = make_reader('{}/train'.format(dataset_url.rstrip('/')), num_epochs=1,
                         transform_spec=TRANSFORM)
    loader = InMemJaxLoader(reader, batch_size=batch_size, num_epochs=None, seed=42)

    def step(carry, batch):
        params, opt_state = carry
        params, opt_state, loss, accuracy = train_step(
            params, opt_state, batch['image'], batch['digit'])
        return (params, opt_state), (loss, accuracy)

    (params, opt_state), per_epoch = loader.scan_epochs(
        step, (params, opt_state), num_epochs=epochs)
    for epoch, (losses, accs) in enumerate(per_epoch):
        print('epoch {}: loss {:.4f} acc {:.3f}'.format(
            epoch, float(losses[-1]), float(accs[-1])))
    return params, float(per_epoch[-1][0][-1]), float(per_epoch[-1][1][-1])


def train_scan_stream(dataset_url, batch_size=128, epochs=1, learning_rate=1e-3,
                      chunk_batches=32):
    """The dispatch-bound streaming configuration for datasets that do NOT fit in
    HBM: ``JaxDataLoader.scan_stream`` re-reads the store each epoch but runs every
    ``chunk_batches`` batches as one compiled program with a single host->device
    transfer — memory bounded at one chunk, per-batch dispatch overhead gone."""
    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))['params']
    optimizer = optax.adam(learning_rate)
    opt_state = optimizer.init(params)
    train_step = make_train_step(model, optimizer)

    def step(carry, batch):
        params, opt_state = carry
        params, opt_state, loss, accuracy = train_step(
            params, opt_state, batch['image'], batch['digit'])
        return (params, opt_state), (loss, accuracy)

    reader = make_reader('{}/train'.format(dataset_url.rstrip('/')), num_epochs=1,
                         transform_spec=TRANSFORM, shuffle_row_groups=True, seed=42)
    loader = JaxDataLoader(reader, batch_size=batch_size)
    loss = accuracy = None
    try:
        for epoch in range(epochs):  # consumed readers auto-reset per pass
            (params, opt_state), chunks = loader.scan_stream(
                step, (params, opt_state), chunk_batches=chunk_batches, seed=epoch)
            losses, accs = chunks[-1]
            loss, accuracy = float(losses[-1]), float(accs[-1])
            print('epoch {}: loss {:.4f} acc {:.3f} ({} chunks)'.format(
                epoch, loss, accuracy, len(chunks)))
    finally:
        reader.stop()
        reader.join()
    return params, loss, accuracy


def evaluate(params, dataset_url, batch_size=128):
    model = MnistCNN()

    @jax.jit
    def eval_step(images, labels):
        logits = model.apply({'params': params}, images[..., None])
        return (logits.argmax(-1) == labels).sum()

    correct = total = 0
    with make_reader('{}/test'.format(dataset_url.rstrip('/')), num_epochs=1,
                     transform_spec=TRANSFORM, shuffle_row_groups=False) as reader:
        loader = JaxDataLoader(reader, batch_size=batch_size, drop_last=True)
        for batch in loader:
            correct += int(eval_step(batch['image'], batch['digit']))
            total += batch['digit'].shape[0]
    print('test accuracy: {}/{} = {:.3f}'.format(correct, total, correct / max(1, total)))
    return correct / max(1, total)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url',
                        default='file://{}'.format(DEFAULT_MNIST_DATA_PATH))
    parser.add_argument('--batch-size', type=int, default=128)
    parser.add_argument('--epochs', type=int, default=1)
    parser.add_argument('--learning-rate', type=float, default=1e-3)
    parser.add_argument('--inmem', action='store_true',
                        help='HBM-resident epochs via InMemJaxLoader.scan_epochs '
                             '(recommended when the dataset fits in HBM)')
    parser.add_argument('--scan-stream', action='store_true',
                        help='compiled-chunk streaming via JaxDataLoader.scan_stream '
                             '(recommended when it does NOT fit in HBM)')
    parser.add_argument('--checkpoint-dir',
                        help='save (model, input position) atomically every '
                             '--save-every steps and resume from it on restart '
                             '(streaming mode only)')
    parser.add_argument('--save-every', type=int, default=100)
    args = parser.parse_args()
    if args.inmem and args.scan_stream:
        parser.error('--inmem and --scan-stream are mutually exclusive')
    if args.checkpoint_dir and (args.inmem or args.scan_stream):
        parser.error('--checkpoint-dir applies to the streaming mode')
    if args.inmem or args.scan_stream:
        train_fn = train_inmem if args.inmem else train_scan_stream
        params, _, _ = train_fn(args.dataset_url, batch_size=args.batch_size,
                                epochs=args.epochs,
                                learning_rate=args.learning_rate)
    else:
        params, _, _ = train(args.dataset_url, batch_size=args.batch_size,
                             epochs=args.epochs, learning_rate=args.learning_rate,
                             checkpoint_dir=args.checkpoint_dir,
                             save_every=args.save_every)
    evaluate(params, args.dataset_url, batch_size=args.batch_size)


if __name__ == '__main__':
    main()
