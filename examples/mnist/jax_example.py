"""Train the flax MNIST CNN from a petastorm_tpu dataset — the TPU-native flagship
example (replaces the reference's torch/TF MNIST mains, examples/mnist/pytorch_example.py
/ tf_example.py, as the primary consumer). The loader feeds device-sharded bf16 batches;
the train step is a single jitted function (MXU-friendly, no host round-trips per step).

Run: ``python -m examples.mnist.jax_example --dataset-url file:///tmp/mnist``
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from examples.mnist import DEFAULT_MNIST_DATA_PATH
from petastorm_tpu import make_reader
from petastorm_tpu.models.mnist import MnistCNN
from petastorm_tpu.parallel.loader import JaxDataLoader
from petastorm_tpu.transform import TransformSpec


def _transform_row(row):
    # Normalize on the host worker; stays uint8->float32 here, cast to bf16 on device.
    row['image'] = (row['image'].astype(np.float32) - 127.5) / 127.5
    return row


TRANSFORM = TransformSpec(_transform_row, edit_fields=[('image', np.float32, (28, 28), False)])


def make_train_step(model, optimizer):
    def loss_fn(params, images, labels):
        logits = model.apply({'params': params}, images[..., None])
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
        accuracy = (logits.argmax(-1) == labels).mean()
        return loss, accuracy

    @jax.jit
    def train_step(params, opt_state, images, labels):
        (loss, accuracy), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, images, labels)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, accuracy

    return train_step


def train(dataset_url, batch_size=128, epochs=1, learning_rate=1e-3,
          shuffling_queue_capacity=1024):
    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))['params']
    optimizer = optax.adam(learning_rate)
    opt_state = optimizer.init(params)
    train_step = make_train_step(model, optimizer)

    loss = accuracy = None
    with make_reader('{}/train'.format(dataset_url.rstrip('/')), num_epochs=epochs,
                     transform_spec=TRANSFORM, shuffle_rows=True, seed=42) as reader:
        loader = JaxDataLoader(reader, batch_size=batch_size,
                               shuffling_queue_capacity=shuffling_queue_capacity, seed=42)
        for step, batch in enumerate(loader):
            params, opt_state, loss, accuracy = train_step(
                params, opt_state, batch['image'], batch['digit'])
            if step % 50 == 0:
                print('step {} loss {:.4f} acc {:.3f}'.format(step, loss, accuracy))
        print('input pipeline stats:', loader.stats.as_dict())
    return params, float(loss), float(accuracy)


def train_inmem(dataset_url, batch_size=128, epochs=1, learning_rate=1e-3):
    """The recommended configuration for fits-in-HBM datasets: fill once, then run
    each epoch — shuffle, gather, and every train step — as ONE compiled program via
    ``InMemJaxLoader.scan_epochs`` (zero host involvement after the fill)."""
    from petastorm_tpu.parallel import InMemJaxLoader

    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))['params']
    optimizer = optax.adam(learning_rate)
    opt_state = optimizer.init(params)
    train_step = make_train_step(model, optimizer)

    reader = make_reader('{}/train'.format(dataset_url.rstrip('/')), num_epochs=1,
                         transform_spec=TRANSFORM)
    loader = InMemJaxLoader(reader, batch_size=batch_size, num_epochs=None, seed=42)

    def step(carry, batch):
        params, opt_state = carry
        params, opt_state, loss, accuracy = train_step(
            params, opt_state, batch['image'], batch['digit'])
        return (params, opt_state), (loss, accuracy)

    (params, opt_state), per_epoch = loader.scan_epochs(
        step, (params, opt_state), num_epochs=epochs)
    for epoch, (losses, accs) in enumerate(per_epoch):
        print('epoch {}: loss {:.4f} acc {:.3f}'.format(
            epoch, float(losses[-1]), float(accs[-1])))
    return params, float(per_epoch[-1][0][-1]), float(per_epoch[-1][1][-1])


def train_scan_stream(dataset_url, batch_size=128, epochs=1, learning_rate=1e-3,
                      chunk_batches=32):
    """The dispatch-bound streaming configuration for datasets that do NOT fit in
    HBM: ``JaxDataLoader.scan_stream`` re-reads the store each epoch but runs every
    ``chunk_batches`` batches as one compiled program with a single host->device
    transfer — memory bounded at one chunk, per-batch dispatch overhead gone."""
    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))['params']
    optimizer = optax.adam(learning_rate)
    opt_state = optimizer.init(params)
    train_step = make_train_step(model, optimizer)

    def step(carry, batch):
        params, opt_state = carry
        params, opt_state, loss, accuracy = train_step(
            params, opt_state, batch['image'], batch['digit'])
        return (params, opt_state), (loss, accuracy)

    reader = make_reader('{}/train'.format(dataset_url.rstrip('/')), num_epochs=1,
                         transform_spec=TRANSFORM, shuffle_row_groups=True, seed=42)
    loader = JaxDataLoader(reader, batch_size=batch_size)
    loss = accuracy = None
    try:
        for epoch in range(epochs):  # consumed readers auto-reset per pass
            (params, opt_state), chunks = loader.scan_stream(
                step, (params, opt_state), chunk_batches=chunk_batches, seed=epoch)
            losses, accs = chunks[-1]
            loss, accuracy = float(losses[-1]), float(accs[-1])
            print('epoch {}: loss {:.4f} acc {:.3f} ({} chunks)'.format(
                epoch, loss, accuracy, len(chunks)))
    finally:
        reader.stop()
        reader.join()
    return params, loss, accuracy


def evaluate(params, dataset_url, batch_size=128):
    model = MnistCNN()

    @jax.jit
    def eval_step(images, labels):
        logits = model.apply({'params': params}, images[..., None])
        return (logits.argmax(-1) == labels).sum()

    correct = total = 0
    with make_reader('{}/test'.format(dataset_url.rstrip('/')), num_epochs=1,
                     transform_spec=TRANSFORM, shuffle_row_groups=False) as reader:
        loader = JaxDataLoader(reader, batch_size=batch_size, drop_last=True)
        for batch in loader:
            correct += int(eval_step(batch['image'], batch['digit']))
            total += batch['digit'].shape[0]
    print('test accuracy: {}/{} = {:.3f}'.format(correct, total, correct / max(1, total)))
    return correct / max(1, total)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url',
                        default='file://{}'.format(DEFAULT_MNIST_DATA_PATH))
    parser.add_argument('--batch-size', type=int, default=128)
    parser.add_argument('--epochs', type=int, default=1)
    parser.add_argument('--learning-rate', type=float, default=1e-3)
    parser.add_argument('--inmem', action='store_true',
                        help='HBM-resident epochs via InMemJaxLoader.scan_epochs '
                             '(recommended when the dataset fits in HBM)')
    parser.add_argument('--scan-stream', action='store_true',
                        help='compiled-chunk streaming via JaxDataLoader.scan_stream '
                             '(recommended when it does NOT fit in HBM)')
    args = parser.parse_args()
    if args.inmem and args.scan_stream:
        parser.error('--inmem and --scan-stream are mutually exclusive')
    train_fn = (train_inmem if args.inmem
                else train_scan_stream if args.scan_stream else train)
    params, _, _ = train_fn(args.dataset_url, batch_size=args.batch_size,
                            epochs=args.epochs, learning_rate=args.learning_rate)
    evaluate(params, args.dataset_url, batch_size=args.batch_size)


if __name__ == '__main__':
    main()
