"""Feed a plain Parquet store to JAX: batched reader → JaxDataLoader → device arrays."""

import argparse

from petastorm_tpu import make_batch_reader
from petastorm_tpu.parallel.loader import JaxDataLoader


def jax_hello_world(dataset_url='file:///tmp/external_dataset'):
    with make_batch_reader(dataset_url, num_epochs=1) as reader:
        loader = JaxDataLoader(reader, batch_size=16, drop_last=False)
        for batch in loader:
            print('ids', batch['id'][:4], 'value1 mean', float(batch['value1'].mean()))


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('-d', '--dataset-url', default='file:///tmp/external_dataset')
    args = parser.parse_args()
    jax_hello_world(args.dataset_url)


if __name__ == '__main__':
    main()
