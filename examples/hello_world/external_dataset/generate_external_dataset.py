"""Generate a plain (non-petastorm) Parquet store with pyarrow — demonstrates that
``make_batch_reader`` consumes any Parquet dataset, no Unischema metadata required
(parity: reference examples/hello_world/external_dataset/generate_external_dataset.py,
which used Spark; plain pyarrow here).

Run: ``python -m examples.hello_world.external_dataset.generate_external_dataset
-o file:///tmp/external_dataset``
"""

import argparse

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths


def generate_external_dataset(output_url='file:///tmp/external_dataset', rows_count=100):
    fs, path = get_filesystem_and_path_or_paths(output_url)
    fs.create_dir(path, recursive=True)
    ids = np.arange(rows_count, dtype=np.int64)
    table = pa.table({
        'id': ids,
        'value1': np.sin(ids.astype(np.float64)),
        'value2': ids * 2,
    })
    with fs.open_output_stream(path + '/data_0.parquet') as sink:
        pq.write_table(table, sink, row_group_size=max(1, rows_count // 4))


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('-o', '--output-url', default='file:///tmp/external_dataset')
    parser.add_argument('-n', '--rows-count', type=int, default=100)
    args = parser.parse_args()
    generate_external_dataset(args.output_url, args.rows_count)
    print('wrote {} rows to {}'.format(args.rows_count, args.output_url))


if __name__ == '__main__':
    main()
