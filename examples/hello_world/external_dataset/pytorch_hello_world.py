"""PyTorch read of a plain Parquet store (parity: reference
examples/hello_world/external_dataset/pytorch_hello_world.py)."""

import argparse

from petastorm_tpu import make_batch_reader
from petastorm_tpu.pytorch import DataLoader


def pytorch_hello_world(dataset_url='file:///tmp/external_dataset'):
    with DataLoader(make_batch_reader(dataset_url), batch_size=8) as train_loader:
        sample = next(iter(train_loader))
        print(sample['id'])


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('-d', '--dataset-url', default='file:///tmp/external_dataset')
    args = parser.parse_args()
    pytorch_hello_world(args.dataset_url)


if __name__ == '__main__':
    main()
