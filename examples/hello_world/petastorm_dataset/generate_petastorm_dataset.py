"""Generate a tiny "hello world" petastorm_tpu dataset — the smallest end-to-end write
path demo (parity: reference examples/hello_world/petastorm_dataset/
generate_petastorm_dataset.py, which needs a Spark session; here the pure-pyarrow
``write_rows`` path makes Spark optional per SURVEY.md §7.1 step 3).

Run: ``python -m examples.hello_world.petastorm_dataset.generate_petastorm_dataset
-o file:///tmp/hello_world_dataset``
"""

import argparse

import numpy as np

from petastorm_tpu.codecs import CompressedImageCodec, NdarrayCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import write_rows
from petastorm_tpu.unischema import Unischema, UnischemaField

HelloWorldSchema = Unischema('HelloWorldSchema', [
    UnischemaField('id', np.int32, (), ScalarCodec(np.int32), False),
    UnischemaField('image1', np.uint8, (128, 256, 3), CompressedImageCodec('png'), False),
    UnischemaField('array_4d', np.uint8, (None, 128, 30, None), NdarrayCodec(), False),
])


def row_generator(x):
    """Returns a single entry in the generated dataset. Keyed by the ``id`` field."""
    return {'id': x,
            'image1': np.asarray(x % 255, dtype=np.uint8) *
            np.ones((128, 256, 3), dtype=np.uint8),
            'array_4d': np.random.randint(0, 255, dtype=np.uint8,
                                          size=(4, 128, 30, 3))}


def generate_petastorm_dataset(output_url='file:///tmp/hello_world_dataset',
                               rows_count=10, rowgroup_size_mb=1):
    rows = [row_generator(x) for x in range(rows_count)]
    write_rows(output_url, HelloWorldSchema, rows, rowgroup_size_mb=rowgroup_size_mb)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('-o', '--output-url', default='file:///tmp/hello_world_dataset',
                        help='file:/// or s3://... url the dataset is written to')
    parser.add_argument('-n', '--rows-count', type=int, default=10)
    args = parser.parse_args()
    generate_petastorm_dataset(args.output_url, args.rows_count)
    print('wrote {} rows to {}'.format(args.rows_count, args.output_url))


if __name__ == '__main__':
    main()
