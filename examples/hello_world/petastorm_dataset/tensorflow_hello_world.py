"""Minimal tf.data read of a petastorm_tpu dataset (parity: reference
examples/hello_world/petastorm_dataset/tensorflow_hello_world.py; eager tf.data only —
graph-mode ``tf_tensors`` is demonstrated in petastorm_tpu.tf_utils docs)."""

import argparse

from petastorm_tpu import make_reader
from petastorm_tpu.tf_utils import make_petastorm_dataset


def tensorflow_hello_world(dataset_url='file:///tmp/hello_world_dataset'):
    with make_reader(dataset_url) as reader:
        dataset = make_petastorm_dataset(reader)
        for sample in dataset.take(3):
            print(sample.id.numpy())


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('-d', '--dataset-url', default='file:///tmp/hello_world_dataset')
    args = parser.parse_args()
    tensorflow_hello_world(args.dataset_url)


if __name__ == '__main__':
    main()
