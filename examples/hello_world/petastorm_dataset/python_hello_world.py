"""Minimal pure-python read of a petastorm_tpu dataset (parity: reference
examples/hello_world/petastorm_dataset/python_hello_world.py)."""

import argparse

from petastorm_tpu import make_reader


def python_hello_world(dataset_url='file:///tmp/hello_world_dataset'):
    with make_reader(dataset_url) as reader:
        for sample in reader:
            print(sample.id)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('-d', '--dataset-url', default='file:///tmp/hello_world_dataset')
    args = parser.parse_args()
    python_hello_world(args.dataset_url)


if __name__ == '__main__':
    main()
