"""Minimal PyTorch DataLoader read of a petastorm_tpu dataset (parity: reference
examples/hello_world/petastorm_dataset/pytorch_hello_world.py)."""

import argparse

from petastorm_tpu import make_reader
from petastorm_tpu.pytorch import DataLoader


def pytorch_hello_world(dataset_url='file:///tmp/hello_world_dataset'):
    with DataLoader(make_reader(dataset_url)) as train_loader:
        sample = next(iter(train_loader))
        print(sample['id'])


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('-d', '--dataset-url', default='file:///tmp/hello_world_dataset')
    args = parser.parse_args()
    pytorch_hello_world(args.dataset_url)


if __name__ == '__main__':
    main()
