"""Read a petastorm_tpu dataset straight onto the accelerator as sharded ``jax.Array``
batches — the TPU-native primary path (no reference analog; this replaces the
pytorch/tensorflow hello worlds as the first-class consumer)."""

import argparse

import jax

from petastorm_tpu import make_reader
from petastorm_tpu.parallel.loader import JaxDataLoader


def jax_hello_world(dataset_url='file:///tmp/hello_world_dataset'):
    # `array_4d` has variable dims; keep the demo to the statically-shaped fields, as XLA
    # requires static shapes (ragged fields need JaxDataLoader(pad_ragged=...)).
    with make_reader(dataset_url, schema_fields=['id', 'image1'], num_epochs=1) as reader:
        loader = JaxDataLoader(reader, batch_size=2, drop_last=False)
        for batch in loader:
            assert isinstance(batch['image1'], jax.Array)
            print('ids', batch['id'], 'image batch shape', batch['image1'].shape,
                  'on', batch['image1'].device)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('-d', '--dataset-url', default='file:///tmp/hello_world_dataset')
    args = parser.parse_args()
    jax_hello_world(args.dataset_url)


if __name__ == '__main__':
    main()
