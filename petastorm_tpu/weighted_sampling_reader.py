"""Mix several readers by sampling probability (reference:
petastorm/weighted_sampling_reader.py:20-115)."""

import numpy as np


class WeightedSamplingReader(object):
    """On every ``next()``, draws one of the underlying readers according to normalized
    ``probabilities`` and returns its next sample. Stops when ANY underlying reader is
    exhausted (reference semantics :89-92). All readers must emit the same schema and
    batched/ngram mode (:64-77)."""

    def __init__(self, readers, probabilities, seed=None):
        if len(readers) != len(probabilities) or not readers:
            raise ValueError('readers and probabilities must be equal-length, non-empty')
        if any(p < 0 for p in probabilities):
            raise ValueError('probabilities must be non-negative')
        total = float(sum(probabilities))
        if total <= 0:
            raise ValueError('probabilities must not all be zero')
        self._readers = list(readers)
        self._cdf = np.cumsum([p / total for p in probabilities])
        self._random = np.random.default_rng(seed)

        first = readers[0]
        for other in readers[1:]:
            if getattr(other, 'is_batched_reader', False) != \
                    getattr(first, 'is_batched_reader', False):
                raise ValueError('All readers must share batched/row mode')
            if getattr(other, 'ngram', None) is not None or \
                    getattr(first, 'ngram', None) is not None:
                if getattr(other, 'ngram', None) != getattr(first, 'ngram', None):
                    raise ValueError('All readers must share the same NGram spec')
            first_fields = set(first.result_schema.fields)
            other_fields = set(other.result_schema.fields)
            if first_fields != other_fields:
                raise ValueError('All readers must emit the same fields; {} != {}'
                                 .format(sorted(first_fields), sorted(other_fields)))

    @property
    def is_batched_reader(self):
        return getattr(self._readers[0], 'is_batched_reader', False)

    @property
    def result_schema(self):
        return self._readers[0].result_schema

    @property
    def ngram(self):
        return getattr(self._readers[0], 'ngram', None)

    @property
    def last_row_consumed(self):
        return any(getattr(r, 'last_row_consumed', False) for r in self._readers)

    def reset(self):
        # Mixing stops when ANY reader exhausts, so the others are mid-stream; only the
        # exhausted ones can (and need to) restart — the rest keep their position.
        for reader in self._readers:
            if getattr(reader, 'last_row_consumed', False):
                reader.reset()

    def __iter__(self):
        return self

    def __next__(self):
        draw = self._random.random()
        index = int(np.searchsorted(self._cdf, draw, side='right'))
        index = min(index, len(self._readers) - 1)
        return next(self._readers[index])

    next = __next__

    def stop(self):
        for reader in self._readers:
            reader.stop()

    def join(self):
        for reader in self._readers:
            reader.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        self.join()
