"""Rowgroup cache (reference: petastorm/cache.py:21-39, petastorm/local_disk_cache.py:23-66).

The reference delegates to the ``diskcache`` package; this is a self-contained sharded
disk cache with atomic writes and size-capped LRU eviction (by file mtime), so repeated
epochs over remote storage hit local disk.

Two on-disk value formats:

- :class:`LocalDiskCache` — whole-value pickle (the reference's semantics): every hit
  pays a full unpickle round trip (read + object-graph materialization).
- :class:`ArrowIpcDiskCache` — the zero-copy format of the decoded-rowgroup data
  plane: columnar values are written as one Arrow IPC stream (the exact byte layout
  of the process-pool wire, ``workers/serializers.py``) plus a pickled sidecar for
  non-Arrow columns, in a single atomically-renamed file. A hit MEMORY-MAPS the file
  and serves the numeric columns as read-only zero-copy views straight into the
  consumer (e.g. ``JaxDataLoader``'s coalesced-upload path) — no Parquet read, no
  decode, no unpickle, no copy. Non-columnar values degrade to an embedded pickle
  record transparently (``stats['pickle_hits']`` makes the degradation visible).

Both keep a ``stats`` dict (hits/misses/bytes); process-pool workers hold their own
unpickled copy, so for that pool the numbers are per-worker (the per-batch
``cache_hit`` sidecar on the results channel is the cross-process aggregate —
see ``Reader.diagnostics``).
"""

import hashlib
import logging
import os
import pickle
import struct
import tempfile
import threading
import time
import zlib

from petastorm_tpu.errors import CacheCorruptionError
from petastorm_tpu.telemetry.spans import record_stage, stage_span

logger = logging.getLogger(__name__)

MB = 1 << 20

#: Arrow-IPC cache file header: magic + mode byte ('A' columnar / 'P' pickle) +
#: uint64-LE length of the IPC stream region (0 in pickle mode)
_ARROW_MAGIC = b'PTUAC001'
_HEADER = struct.Struct('<8scQ')
#: Arrow-IPC cache file footer: magic + CRC-32 of the body (everything between
#: header and footer) + uint64-LE body length. Verified on every hit BEFORE any
#: byte of the body is interpreted; entries written before the footer existed
#: fail the magic check and self-heal like any other corrupt entry
#: (docs/robustness.md "Hang detection & circuit breakers").
_FOOTER_MAGIC = b'PTUCRC01'
_FOOTER = struct.Struct('<8sIQ')

#: cache-breaker defaults: consecutive read/store failures before ``get``
#: bypasses the cache entirely (direct fills), and the cooldown before a
#: half-open probe tries the cache again
DEFAULT_CACHE_BREAKER_THRESHOLD = 5
DEFAULT_CACHE_BREAKER_RECOVERY_S = 60.0


class CacheBase(object):
    """Rowgroup-cache interface (reference: petastorm/cache.py): ``get`` with a
    fill function; implementations decide storage and eviction."""

    def get(self, key, fill_cache_func):
        """Return the cached value for ``key``, calling ``fill_cache_func()`` and storing
        its result on a miss (reference: petastorm/cache.py:24-32)."""
        raise NotImplementedError()

    def cleanup(self):
        """Remove cache resources (best effort)."""


class NullCache(CacheBase):
    """Pass-through: always calls the fill function (reference: petastorm/cache.py:35-39)."""

    def get(self, key, fill_cache_func):
        return fill_cache_func()


def _new_cache_stats():
    """Fresh cache counters: ``hits``/``misses``, ``arrow_hits`` (zero-copy mmap
    hits) vs ``pickle_hits`` (unpickle-path hits — the fallback to copy-mode),
    ``bytes_mmapped`` (bytes served as views over the mapped file),
    ``bytes_written``, ``corrupt_entries`` (unreadable entries deleted by the
    self-heal path) and ``bypass_reads`` (fills served while the cache circuit
    breaker was open)."""
    return {'hits': 0, 'misses': 0, 'arrow_hits': 0, 'pickle_hits': 0,
            'bytes_mmapped': 0, 'bytes_written': 0, 'corrupt_entries': 0,
            'bypass_reads': 0}


class LocalDiskCache(CacheBase):
    """File-per-key cache under ``path``, sharded into 256 subdirectories, bounded by
    ``size_limit_bytes`` with mtime-LRU eviction (reference: local_disk_cache.py:23-66).

    :param path: cache root directory (created if absent)
    :param size_limit_bytes: max total bytes before eviction kicks in
    :param expected_row_size_bytes: sanity check — the limit must hold many rows
    :param cleanup: remove the whole cache directory on ``cleanup()``
    """

    #: per-key file suffix; eviction scans every known suffix so differently-
    #: formatted caches sharing one directory stay bounded together
    _SUFFIX = '.pkl'
    _ALL_SUFFIXES = ('.pkl', '.arrow')

    def __init__(self, path, size_limit_bytes, expected_row_size_bytes=0, cleanup=False,
                 shards=None, breaker=None):
        if expected_row_size_bytes and size_limit_bytes < 100 * expected_row_size_bytes:
            raise ValueError('Cache size_limit_bytes={} is too small for rows of ~{} bytes'
                             .format(size_limit_bytes, expected_row_size_bytes))
        self._path = path
        self._size_limit_bytes = size_limit_bytes
        self._cleanup = cleanup
        self._lock = threading.Lock()
        self.stats = _new_cache_stats()
        self._decode_failure_logged = False
        os.makedirs(path, exist_ok=True)
        # Circuit breaker (docs/robustness.md): repeated corrupt entries or IO
        # failures open it, and get() then BYPASSES the cache (direct fills, no
        # reads, no stores) until the cooldown's half-open probe succeeds — a
        # sick disk degrades throughput, not correctness. Registered on the
        # process-local default board so its state rides the results-channel
        # breaker sidecar into Reader.diagnostics; injectable for tests.
        self._breaker = breaker if breaker is not None else self._default_breaker()
        # Runtime bypass knob (docs/autotuning.md): forces get() onto the
        # direct-fill path exactly like an open breaker, without touching the
        # breaker's failure state. Turned by the autotuner when serving hits
        # is measured slower than refilling (e.g. the pickle format's
        # per-hit unpickle on a fast store).
        self._forced_bypass = False
        # Approximate running byte total: seeded from one scan, bumped per store; the
        # expensive full rescan happens only when this crosses the limit.
        self._approx_bytes = None

    def _default_breaker(self):
        from petastorm_tpu.resilience import default_board
        return default_board().breaker(
            'cache:{}'.format(self._path),
            failure_threshold=DEFAULT_CACHE_BREAKER_THRESHOLD,
            recovery_timeout_s=DEFAULT_CACHE_BREAKER_RECOVERY_S)

    def __getstate__(self):
        # Shipped to process-pool workers; the lock is per-process state, and so
        # is the breaker (each worker re-registers on ITS default board — states
        # reach the consumer via the results-channel sidecar, not via pickle).
        state = self.__dict__.copy()
        del state['_lock']
        del state['_breaker']
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()
        self._breaker = self._default_breaker()

    @property
    def state_home(self):
        """The cache root directory — the per-dataset local-state home the
        cost ledger and lineage manifest sidecars default into
        (``petastorm_tpu.dataset_state.cache_state_home``)."""
        return self._path

    def _key_path(self, key):
        digest = hashlib.sha1(str(key).encode('utf-8')).hexdigest()
        return os.path.join(self._path, digest[:2], digest + self._SUFFIX)

    # ------------------------------------------------------------- value codec

    def _encode_value(self, value):
        """Value -> file bytes (pickle format)."""
        return pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)

    def _decode_file(self, file_path):
        """File -> value; raising (corrupt/truncated entry) counts as a miss."""
        with open(file_path, 'rb') as f:
            value = pickle.load(f)
        with self._lock:
            self.stats['pickle_hits'] += 1
        return value

    # ------------------------------------------------------------------- get

    @property
    def bypass(self):
        """True while the runtime bypass knob routes ``get`` to direct fills."""
        return self._forced_bypass

    def set_bypass(self, flag):
        """Runtime cache-mode knob (docs/autotuning.md): ``True`` makes ``get``
        serve direct fills (no read, no store — counted in
        ``stats['bypass_reads']``) without touching the circuit breaker;
        ``False`` restores normal hit/miss serving. Live for in-process pools;
        process-pool workers capture the flag at spawn. Returns the flag."""
        self._forced_bypass = bool(flag)
        return self._forced_bypass

    def get(self, key, fill_cache_func):
        if self._forced_bypass or not self._breaker.allow():
            # Breaker open (or the bypass knob is set): the disk under this
            # cache keeps corrupting or erroring — bypass it entirely (no
            # read, no store) until the cooldown's half-open probe passes.
            # Degradation, never silence.
            with self._lock:
                self.stats['bypass_reads'] += 1
            return fill_cache_func()
        file_path = self._key_path(key)
        try:
            value = self._decode_file(file_path)
            # touch for LRU
            os.utime(file_path, None)
            with self._lock:
                self.stats['hits'] += 1
            self._breaker.record_success()
            return value
        except FileNotFoundError:
            pass  # plain miss
        except Exception:  # noqa: BLE001 - any unreadable entry degrades to a miss
            # Corrupt/truncated entries are expected (crash mid-eviction), but a
            # SYSTEMATIC decode failure (env/codec bug) would otherwise silently
            # turn every epoch cold — log the first one loudly, the rest quietly.
            if not self._decode_failure_logged:
                self._decode_failure_logged = True
                logger.warning('cache entry %s is unreadable; deleting it and '
                               'serving a miss (further decode failures logged '
                               'at DEBUG)', file_path, exc_info=True)
            else:
                logger.debug('cache entry %s is unreadable; deleting it and '
                             'serving a miss', file_path, exc_info=True)
            self._delete_corrupt_entry(file_path)
        with self._lock:
            self.stats['misses'] += 1
        value = fill_cache_func()
        try:
            self._store(file_path, value)
            # A successful store is breaker-neutral while closed (it must not
            # reset a corrupt-READ streak — a disk that stores fine but corrupts
            # everything it returns still needs to trip); it only counts as the
            # recovery probe's success when the breaker is half-open.
            if self._breaker.state == 'half_open':
                self._breaker.record_success()
        except OSError:
            # A failed store must not fail the read — the value is in hand. It
            # does feed the breaker: a disk that cannot store will not serve.
            self._breaker.record_failure()
            logger.warning('failed to store cache entry %s; serving the value '
                           'uncached', file_path, exc_info=True)
        return value

    def _delete_corrupt_entry(self, file_path):
        """Self-heal: a poisoned entry left on disk would re-pay the decode
        failure every warm epoch — delete it so the refill's store replaces it,
        and count it (``corrupt_entries`` stat, ``cache_corrupt`` stage — the
        latter rides the telemetry sidecar across process boundaries)."""
        delete_start = time.perf_counter()
        try:
            os.unlink(file_path)
        except OSError:
            pass  # a concurrent reader may have healed it already
        with self._lock:
            self.stats['corrupt_entries'] += 1
        self._breaker.record_failure()
        record_stage('cache_corrupt', time.perf_counter() - delete_start)

    def _store(self, file_path, value):
        # cache_store stage span (docs/observability.md): encode + write + publish
        # — first-epoch-only cost unless eviction churns
        with stage_span('cache_store'):
            os.makedirs(os.path.dirname(file_path), exist_ok=True)
            blob = self._encode_value(value)
            if len(blob) > self._size_limit_bytes:
                return  # single value larger than the cache: do not thrash
            # mkstemp + os.replace: concurrent fillers of the same key each write a
            # private temp file and atomically publish it — readers only ever see a
            # complete entry (last writer wins; both writers hold equivalent
            # values).
            fd, tmp_path = tempfile.mkstemp(dir=os.path.dirname(file_path))
            try:
                with os.fdopen(fd, 'wb') as f:
                    f.write(blob)
                os.replace(tmp_path, file_path)
            finally:
                # on the normal path os.replace already consumed the temp
                # name and this unlink is a no-op; on ANY failure (not just
                # OSError — encoding bugs included) the orphan is removed
                try:
                    os.unlink(tmp_path)
                except OSError:
                    pass
        with self._lock:
            self.stats['bytes_written'] += len(blob)
            if self._approx_bytes is None:
                self._approx_bytes = sum(size for _, size, _ in self._iter_entries())
            else:
                self._approx_bytes += len(blob)
            over_limit = self._approx_bytes > self._size_limit_bytes
        if over_limit:
            self._maybe_evict()

    def _iter_entries(self):
        for shard in os.listdir(self._path):
            shard_path = os.path.join(self._path, shard)
            if not os.path.isdir(shard_path):
                continue
            for name in os.listdir(shard_path):
                if not name.endswith(self._ALL_SUFFIXES):
                    continue  # skip other writers' in-progress mkstemp files
                full = os.path.join(shard_path, name)
                try:
                    stat = os.stat(full)
                except OSError:
                    continue
                yield full, stat.st_size, stat.st_mtime

    def _maybe_evict(self):
        with self._lock:
            entries = list(self._iter_entries())
            total = sum(size for _, size, _ in entries)
            if total > self._size_limit_bytes:
                # Evict least-recently-touched until under 90% of the limit.
                entries.sort(key=lambda e: e[2])
                target = int(self._size_limit_bytes * 0.9)
                for full, size, _ in entries:
                    if total <= target:
                        break
                    try:
                        os.unlink(full)
                        total -= size
                    except OSError:
                        continue
            self._approx_bytes = total

    @property
    def size(self):
        return sum(size for _, size, _ in self._iter_entries())

    def cleanup(self):
        if self._cleanup:
            import shutil
            shutil.rmtree(self._path, ignore_errors=True)


class ArrowIpcDiskCache(LocalDiskCache):
    """Decoded-rowgroup cache with mmap zero-copy hits (see module docstring).

    Columnar values (``{name: ndarray-or-list}`` — what the rowgroup worker caches)
    are stored as ``[header][arrow ipc stream][pickled sidecar]``; a hit memory-maps
    the file and returns numeric columns as READ-ONLY views over the map (in-place
    mutation of a warm-hit column raises numpy's read-only error — pass
    ``writable_hits=True``, or let ``make_reader`` set it when a ``transform_spec``
    is present, to receive writable copies instead: still no Parquet read, decode
    or unpickle, just one memcpy per column). Anything else (NGram payloads,
    arbitrary objects) is stored as an embedded pickle record with identical
    atomicity/eviction semantics. Constructor = :class:`LocalDiskCache` plus
    ``writable_hits`` (default False = zero-copy).
    """

    _SUFFIX = '.arrow'

    def __init__(self, path, size_limit_bytes, expected_row_size_bytes=0,
                 cleanup=False, shards=None, writable_hits=False, breaker=None):
        super().__init__(path, size_limit_bytes, expected_row_size_bytes,
                         cleanup=cleanup, shards=shards, breaker=breaker)
        self._writable_hits = writable_hits
        #: set by make_reader when the user passed an explicit
        #: cache_extra_settings={'writable_hits': ...} — a pinned hit mode is
        #: a consumer requirement, not an autotuner knob (docs/autotuning.md)
        self.writable_hits_pinned = False

    @property
    def writable_hits(self):
        """True when hits decode writable copies instead of read-only views."""
        return self._writable_hits

    def set_writable_hits(self, flag):
        """Runtime hit-mode knob (docs/autotuning.md): ``False`` serves hits as
        zero-copy read-only mmap views (fastest), ``True`` copies each column
        out writable (required by in-place ``transform_spec`` consumers — the
        autotuner only turns this knob on transform-free readers). Live for
        in-process pools; process-pool workers capture the flag at spawn.
        Returns the flag."""
        self._writable_hits = bool(flag)
        return self._writable_hits

    def _encode_value(self, value):
        from petastorm_tpu.workers.serializers import (_columns_num_rows,
                                                       encode_columnar)
        body = None
        if isinstance(value, dict):
            try:
                num_rows = _columns_num_rows(value)
                ipc_buf, sidecar_blob, _ = encode_columnar(value, num_rows)
                header = _HEADER.pack(_ARROW_MAGIC, b'A', len(ipc_buf))
                body = ipc_buf.to_pybytes() + sidecar_blob
            except Exception:  # noqa: BLE001 - non-columnar dict: pickle record
                logger.debug('value for arrow cache is not columnar; storing as '
                             'pickle record', exc_info=True)
        if body is None:
            header = _HEADER.pack(_ARROW_MAGIC, b'P', 0)
            body = pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
        footer = _FOOTER.pack(_FOOTER_MAGIC, zlib.crc32(body) & 0xFFFFFFFF,
                              len(body))
        return b''.join([header, body, footer])

    def _decode_file(self, file_path):
        import pyarrow as pa
        from petastorm_tpu.workers.serializers import decode_columnar
        mm = pa.memory_map(file_path, 'r')
        buf = mm.read_buffer()
        total = len(buf)
        if total < _HEADER.size + _FOOTER.size:
            raise CacheCorruptionError(
                'cache entry {} is {} bytes — shorter than header+footer'
                .format(file_path, total))
        magic, mode, ipc_len = _HEADER.unpack_from(memoryview(buf)[:_HEADER.size])
        if magic != _ARROW_MAGIC:
            raise ValueError('not an ArrowIpcDiskCache entry: {!r}'.format(magic))
        # Footer verification BEFORE interpreting a single body byte: truncation
        # shows as a length mismatch, a bit flip as a CRC mismatch, a
        # pre-footer-format entry as a footer-magic mismatch — all three
        # self-heal through get()'s delete-on-corrupt path.
        footer_magic, crc, body_len = _FOOTER.unpack_from(
            memoryview(buf)[total - _FOOTER.size:])
        if footer_magic != _FOOTER_MAGIC:
            raise CacheCorruptionError(
                'cache entry {} has no integrity footer (truncated, or written '
                'by a pre-footer version)'.format(file_path))
        if body_len != total - _HEADER.size - _FOOTER.size or ipc_len > body_len:
            raise CacheCorruptionError(
                'cache entry {} length mismatch: footer claims {} body bytes, '
                'file holds {}'.format(file_path, body_len,
                                       total - _HEADER.size - _FOOTER.size))
        body = buf.slice(_HEADER.size, body_len)
        if zlib.crc32(memoryview(body)) & 0xFFFFFFFF != crc:
            raise CacheCorruptionError(
                'cache entry {} failed CRC verification (bit rot or torn write)'
                .format(file_path))
        if mode == b'P':
            value = pickle.loads(memoryview(body))
            with self._lock:
                self.stats['pickle_hits'] += 1
            return value
        # Zero-copy decode: numeric columns are read-only views whose base buffers
        # keep the memory map alive; sidecar columns (ragged/object) unpickle.
        # writable_hits copies each column out of the map instead (mutating
        # consumers, e.g. in-place transform_specs).
        columns, _ = decode_columnar(body.slice(0, ipc_len), body.slice(ipc_len),
                                     writable=self._writable_hits)
        with self._lock:
            self.stats['arrow_hits'] += 1
            self.stats['bytes_mmapped'] += len(buf)
        return columns
