"""Rowgroup cache (reference: petastorm/cache.py:21-39, petastorm/local_disk_cache.py:23-66).

The reference delegates to the ``diskcache`` package; this is a self-contained sharded
disk cache with atomic writes and size-capped LRU eviction (by file mtime), so repeated
epochs over remote storage hit local disk.
"""

import hashlib
import os
import pickle
import tempfile
import threading

MB = 1 << 20


class CacheBase(object):
    """Rowgroup-cache interface (reference: petastorm/cache.py): ``get`` with a
    fill function; implementations decide storage and eviction."""

    def get(self, key, fill_cache_func):
        """Return the cached value for ``key``, calling ``fill_cache_func()`` and storing
        its result on a miss (reference: petastorm/cache.py:24-32)."""
        raise NotImplementedError()

    def cleanup(self):
        """Remove cache resources (best effort)."""


class NullCache(CacheBase):
    """Pass-through: always calls the fill function (reference: petastorm/cache.py:35-39)."""

    def get(self, key, fill_cache_func):
        return fill_cache_func()


class LocalDiskCache(CacheBase):
    """File-per-key cache under ``path``, sharded into 256 subdirectories, bounded by
    ``size_limit_bytes`` with mtime-LRU eviction (reference: local_disk_cache.py:23-66).

    :param path: cache root directory (created if absent)
    :param size_limit_bytes: max total bytes before eviction kicks in
    :param expected_row_size_bytes: sanity check — the limit must hold many rows
    :param cleanup: remove the whole cache directory on ``cleanup()``
    """

    def __init__(self, path, size_limit_bytes, expected_row_size_bytes=0, cleanup=False,
                 shards=None):
        if expected_row_size_bytes and size_limit_bytes < 100 * expected_row_size_bytes:
            raise ValueError('Cache size_limit_bytes={} is too small for rows of ~{} bytes'
                             .format(size_limit_bytes, expected_row_size_bytes))
        self._path = path
        self._size_limit_bytes = size_limit_bytes
        self._cleanup = cleanup
        self._lock = threading.Lock()
        os.makedirs(path, exist_ok=True)
        # Approximate running byte total: seeded from one scan, bumped per store; the
        # expensive full rescan happens only when this crosses the limit.
        self._approx_bytes = None

    def __getstate__(self):
        # Shipped to process-pool workers; the lock is per-process state.
        state = self.__dict__.copy()
        del state['_lock']
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()

    def _key_path(self, key):
        digest = hashlib.sha1(str(key).encode('utf-8')).hexdigest()
        return os.path.join(self._path, digest[:2], digest + '.pkl')

    def get(self, key, fill_cache_func):
        file_path = self._key_path(key)
        try:
            with open(file_path, 'rb') as f:
                value = pickle.load(f)
            # touch for LRU
            os.utime(file_path, None)
            return value
        except (OSError, pickle.UnpicklingError, EOFError):
            pass
        value = fill_cache_func()
        self._store(file_path, value)
        return value

    def _store(self, file_path, value):
        os.makedirs(os.path.dirname(file_path), exist_ok=True)
        blob = pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
        if len(blob) > self._size_limit_bytes:
            return  # single value larger than the cache: do not thrash
        fd, tmp_path = tempfile.mkstemp(dir=os.path.dirname(file_path))
        try:
            with os.fdopen(fd, 'wb') as f:
                f.write(blob)
            os.replace(tmp_path, file_path)
        except OSError:
            try:
                os.unlink(tmp_path)
            except OSError:
                pass
            raise
        with self._lock:
            if self._approx_bytes is None:
                self._approx_bytes = sum(size for _, size, _ in self._iter_entries())
            else:
                self._approx_bytes += len(blob)
            over_limit = self._approx_bytes > self._size_limit_bytes
        if over_limit:
            self._maybe_evict()

    def _iter_entries(self):
        for shard in os.listdir(self._path):
            shard_path = os.path.join(self._path, shard)
            if not os.path.isdir(shard_path):
                continue
            for name in os.listdir(shard_path):
                if not name.endswith('.pkl'):
                    continue  # skip other writers' in-progress mkstemp files
                full = os.path.join(shard_path, name)
                try:
                    stat = os.stat(full)
                except OSError:
                    continue
                yield full, stat.st_size, stat.st_mtime

    def _maybe_evict(self):
        with self._lock:
            entries = list(self._iter_entries())
            total = sum(size for _, size, _ in entries)
            if total > self._size_limit_bytes:
                # Evict least-recently-touched until under 90% of the limit.
                entries.sort(key=lambda e: e[2])
                target = int(self._size_limit_bytes * 0.9)
                for full, size, _ in entries:
                    if total <= target:
                        break
                    try:
                        os.unlink(full)
                        total -= size
                    except OSError:
                        continue
            self._approx_bytes = total

    @property
    def size(self):
        return sum(size for _, size, _ in self._iter_entries())

    def cleanup(self):
        if self._cleanup:
            import shutil
            shutil.rmtree(self._path, ignore_errors=True)
