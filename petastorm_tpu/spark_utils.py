"""Spark adapters (reference: petastorm/spark_utils.py:24-52 and the write-path helper
petastorm/unischema.py:348-413) — gated on pyspark being installed; the rest of the
framework has no Spark dependency.

Write path with Spark: codec-encode rows with :func:`dict_to_spark_row`, write the
DataFrame as Parquet, then attach metadata with
``petastorm_tpu.etl.dataset_metadata.materialize_dataset`` — or skip Spark entirely:
``write_rows`` is the first-class pure-Arrow writer (SURVEY.md §7.1 step 3 makes Spark
optional by design)."""


def dict_to_spark_row(schema, row_dict):
    """Validate + codec-encode one row dict and build an ordered ``pyspark.sql.Row``
    (reference: petastorm/unischema.py:348-384). The encode/validation logic is the
    shared :func:`~petastorm_tpu.unischema.dict_to_encoded_row`; this wrapper only adds
    the Spark Row rendering, so the pure-Arrow writer and the Spark writer cannot
    diverge."""
    try:
        from pyspark.sql import Row
    except ImportError:
        raise ImportError('dict_to_spark_row requires pyspark, which is not installed; '
                          'use petastorm_tpu.etl.dataset_metadata.write_rows instead')
    from petastorm_tpu.unischema import dict_to_encoded_row
    encoded = dict_to_encoded_row(schema, row_dict)
    # Stable field order: Row(**kwargs) sorts on some pyspark versions; build through
    # an ordered Row class instead (same approach as the reference).
    row_cls = Row(*schema.fields.keys())
    return row_cls(*[encoded[name] for name in schema.fields])


def dataset_as_rdd(dataset_url, spark_session, schema_fields=None, storage_options=None):
    """Load a dataset as a Spark RDD of decoded namedtuples (reference:
    spark_utils.py:24-52)."""
    try:
        import pyspark  # noqa: F401
    except ImportError:
        raise ImportError('dataset_as_rdd requires pyspark, which is not installed; '
                          'use make_reader / make_batch_reader instead')
    from petastorm_tpu.etl import dataset_metadata
    from petastorm_tpu.unischema import decode_row

    schema = dataset_metadata.get_schema_from_dataset_url(
        dataset_url, storage_options=storage_options)
    view = schema.create_schema_view(schema_fields) if schema_fields else schema
    dataframe = spark_session.read.parquet(dataset_url)
    dataframe = dataframe.select(*list(view.fields))

    def _to_namedtuple(record):
        decoded = decode_row(record.asDict(), view)
        return view.make_namedtuple(**decoded)

    return dataframe.rdd.map(_to_namedtuple)
