"""Spark adapter (reference: petastorm/spark_utils.py:24-52) — gated on pyspark being
installed; the rest of the framework has no Spark dependency."""


def dataset_as_rdd(dataset_url, spark_session, schema_fields=None, storage_options=None):
    """Load a dataset as a Spark RDD of decoded namedtuples (reference:
    spark_utils.py:24-52)."""
    try:
        import pyspark  # noqa: F401
    except ImportError:
        raise ImportError('dataset_as_rdd requires pyspark, which is not installed; '
                          'use make_reader / make_batch_reader instead')
    from petastorm_tpu.etl import dataset_metadata
    from petastorm_tpu.unischema import decode_row

    schema = dataset_metadata.get_schema_from_dataset_url(
        dataset_url, storage_options=storage_options)
    view = schema.create_schema_view(schema_fields) if schema_fields else schema
    dataframe = spark_session.read.parquet(dataset_url)
    dataframe = dataframe.select(*list(view.fields))

    def _to_namedtuple(record):
        decoded = decode_row(record.asDict(), view)
        return view.make_namedtuple(**decoded)

    return dataframe.rdd.map(_to_namedtuple)
