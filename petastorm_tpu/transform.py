"""User-defined on-worker transforms with schema mutation (reference:
petastorm/transform.py:27-89).

A :class:`TransformSpec` carries a function applied inside a reader worker — on a row dict
for the row reader, or on a pandas DataFrame for the batch reader — plus a declaration of
how the output schema differs from the input schema (edited / removed / selected fields).
"""

from petastorm_tpu.unischema import Unischema, UnischemaField


class TransformSpec(object):
    """Specification of a worker-side transform (reference: petastorm/transform.py:27-57).

    :param func: callable applied on the worker (row dict -> row dict for ``make_reader``;
        pandas DataFrame -> pandas DataFrame for ``make_batch_reader``). May be None when
        only field selection/removal is desired.
    :param edit_fields: list of 4-tuples ``(name, numpy_dtype, shape, nullable)`` or
        :class:`UnischemaField` describing fields added or modified by ``func``.
    :param removed_fields: list of field names deleted by the transform. Mutually exclusive
        with ``selected_fields``.
    :param selected_fields: ordered list of field names to keep (output column order).
    :param batched: row-reader vectorized mode (docs/performance.md "Vectorized
        decode engine"): ``func`` receives the whole decoded rowgroup as a
        ``{field: ndarray-or-list}`` columns dict and returns the transformed
        columns dict — the worker skips the per-row dict materialization
        entirely. Ignored by ``make_batch_reader`` (whose ``func`` is already
        batched via pandas). A ``func=None`` spec never materializes rows in
        either reader, ``batched`` or not.
    """

    def __init__(self, func=None, edit_fields=None, removed_fields=None, selected_fields=None,
                 batched=False):
        if removed_fields and selected_fields:
            raise ValueError('removed_fields and selected_fields are mutually exclusive '
                             '(reference semantics: petastorm/transform.py:49-52)')
        self.func = func
        self.edit_fields = edit_fields or []
        self.removed_fields = removed_fields or []
        self.selected_fields = selected_fields
        self.batched = bool(batched)


def transform_schema(schema, transform_spec):
    """Compute the post-transform schema (reference: petastorm/transform.py:60-89)."""
    edited = {}
    for edit in transform_spec.edit_fields:
        if isinstance(edit, UnischemaField):
            field = edit
        else:
            name, numpy_dtype, shape, nullable = edit
            field = UnischemaField(name, numpy_dtype, shape, codec=None, nullable=nullable)
        edited[field.name] = field

    removed = set(transform_spec.removed_fields)
    unknown_removed = removed - set(schema.fields) - set(edited)
    if unknown_removed:
        raise ValueError('removed_fields {} not present in schema {!r}'
                         .format(sorted(unknown_removed), schema.name))

    fields = {}
    for name, field in schema.fields.items():
        if name in removed:
            continue
        fields[name] = edited.pop(name, field)
    # Net-new edited fields append after existing ones, in edit order.
    for name, field in edited.items():
        if name not in removed:
            fields[name] = field

    if transform_spec.selected_fields is not None:
        unknown_selected = set(transform_spec.selected_fields) - set(fields)
        if unknown_selected:
            raise ValueError('selected_fields {} not present in transformed schema'
                             .format(sorted(unknown_selected)))
        fields = {name: fields[name] for name in transform_spec.selected_fields}

    return Unischema('{}_transformed'.format(schema.name), list(fields.values()))
