"""Unischema: a single schema definition rendering to multiple framework type systems.

Capability parity with petastorm/unischema.py (UnischemaField :50-85, Unischema :174-345,
dict_to_spark_row :348-413, match_unischema_fields :426-453, from_arrow_schema inference
:302-342), re-designed TPU-first:

- primary render targets are **Arrow** (storage) and **jax.ShapeDtypeStruct** (device)
  instead of Spark StructType / TF dtypes;
- schemas persist as **versioned JSON** (``to_json_dict``/``from_json_dict``), not pickles;
- rows render as namedtuples (cached per schema+field-set, like the reference's
  _NamedtupleCache unischema.py:88-125, so type identity is stable across calls).
"""

import copy
import re
import threading
from collections import namedtuple
from decimal import Decimal

import numpy as np
import pyarrow as pa

from petastorm_tpu.codecs import (FieldCodec, ScalarCodec, NdarrayCodec, codec_from_config,
                                  arrow_type_for_numpy)


class UnischemaField(object):
    """A single field: ``(name, numpy_dtype, shape, codec, nullable)``.

    ``shape`` dims may be ``None`` meaning variable length (reference:
    petastorm/unischema.py:50-85). ``numpy_dtype`` may be a numpy scalar type, ``np.dtype``,
    ``str`` (numpy string/unicode dtypes included), or ``decimal.Decimal``.

    Equality/hash are value-based over (name, dtype, shape, nullable) plus the codec's
    *config* (not object identity) — the reference relaxed codec comparison for pickle
    round-trip safety (petastorm/unischema.py:39-47,71-85).
    """

    __slots__ = ('name', 'numpy_dtype', 'shape', 'codec', 'nullable')

    def __init__(self, name, numpy_dtype, shape=(), codec=None, nullable=False):
        if codec is not None and not isinstance(codec, FieldCodec):
            raise TypeError('codec must be a FieldCodec or None, got {!r}'.format(codec))
        self.name = name
        self.numpy_dtype = numpy_dtype
        self.shape = tuple(shape)
        self.codec = codec
        self.nullable = nullable

    def _key(self):
        codec_config = self.codec.to_config() if self.codec is not None else None
        return (self.name, _dtype_token(self.numpy_dtype), self.shape,
                None if codec_config is None else tuple(sorted(codec_config.items())),
                self.nullable)

    def __eq__(self, other):
        return isinstance(other, UnischemaField) and self._key() == other._key()

    def __ne__(self, other):
        return not self == other

    def __hash__(self):
        return hash(self._key())

    def __repr__(self):
        return ('UnischemaField(name={!r}, numpy_dtype={}, shape={}, codec={}, nullable={})'
                .format(self.name, _dtype_token(self.numpy_dtype), self.shape, self.codec,
                        self.nullable))

    # -- renders ------------------------------------------------------------------

    def arrow_type(self):
        """Arrow storage type of this field's encoded column."""
        if self.codec is not None:
            return self.codec.arrow_type(self)
        if self.numpy_dtype is Decimal:
            return pa.string()
        if self.shape == ():
            return arrow_type_for_numpy(self.numpy_dtype)
        if len(self.shape) == 1:
            return pa.list_(arrow_type_for_numpy(self.numpy_dtype))
        raise ValueError('Field {} has shape {} but no codec; multidim fields require a codec'
                         .format(self.name, self.shape))

    def shape_dtype_struct(self, batch_dims=()):
        """``jax.ShapeDtypeStruct`` render (the TPU-side analog of the reference's TF dtype
        render, petastorm/tf_utils.py:27-43). None dims are not representable; callers must
        pad ragged fields first."""
        import jax
        if any(dim is None for dim in self.shape):
            raise ValueError('Field {} has variable shape {}; pad before device render'
                             .format(self.name, self.shape))
        if self.numpy_dtype is Decimal or np.dtype(self.numpy_dtype).kind in ('U', 'S', 'O'):
            raise ValueError('Field {} dtype has no device representation'.format(self.name))
        return jax.ShapeDtypeStruct(tuple(batch_dims) + self.shape, np.dtype(self.numpy_dtype))

    # -- JSON serialization -------------------------------------------------------

    def to_json_dict(self):
        return {
            'name': self.name,
            'numpy_dtype': _dtype_token(self.numpy_dtype),
            'shape': list(self.shape),
            'codec': self.codec.to_config() if self.codec is not None else None,
            'nullable': self.nullable,
        }

    @classmethod
    def from_json_dict(cls, field_dict):
        codec_config = field_dict.get('codec')
        return cls(
            name=field_dict['name'],
            numpy_dtype=_dtype_from_token(field_dict['numpy_dtype']),
            shape=tuple(field_dict['shape']),
            codec=codec_from_config(codec_config) if codec_config is not None else None,
            nullable=field_dict.get('nullable', False),
        )


def _dtype_token(numpy_dtype):
    """Stable string token for a field dtype (JSON store + hashing)."""
    if numpy_dtype is Decimal:
        return 'Decimal'
    return np.dtype(numpy_dtype).name if not _is_string_dtype(numpy_dtype) \
        else np.dtype(numpy_dtype).str.lstrip('<>=|')


def _is_string_dtype(numpy_dtype):
    if numpy_dtype is Decimal:
        return False
    return np.dtype(numpy_dtype).kind in ('U', 'S')


def _dtype_from_token(token):
    if token == 'Decimal':
        return Decimal
    return np.dtype(token)


class _NamedtupleCache(object):
    """One namedtuple class per (schema-name, field-names) so adapter layers relying on type
    identity (e.g. tf.data) see a consistent type (reference: petastorm/unischema.py:88-125)."""

    _lock = threading.Lock()
    _store = {}

    @classmethod
    def get(cls, parent_name, field_names):
        key = (parent_name, tuple(field_names))
        with cls._lock:
            if key not in cls._store:
                cls._store[key] = namedtuple(parent_name or 'UnischemaRow', field_names)
            return cls._store[key]


class Unischema(object):
    """An ordered collection of :class:`UnischemaField` (reference:
    petastorm/unischema.py:174-345). Field order is input order (the reference's
    ``preserve_input_order`` policy, unischema.py:33-36)."""

    def __init__(self, name, fields):
        self._name = name
        self._fields = {}
        for field in fields:
            if field.name in self._fields:
                raise ValueError('Duplicate field name {!r} in schema {!r}'
                                 .format(field.name, name))
            self._fields[field.name] = field
        # Dynamic attribute per field, e.g. ``schema.my_field`` (unischema.py:192-197).
        for field_name, field in self._fields.items():
            if not hasattr(self, field_name):
                setattr(self, field_name, field)

    @property
    def name(self):
        return self._name

    @property
    def fields(self):
        """Ordered dict of name -> UnischemaField (insertion order preserved)."""
        return self._fields

    def __iter__(self):
        return iter(self._fields.values())

    def __len__(self):
        return len(self._fields)

    def __eq__(self, other):
        return (isinstance(other, Unischema) and self._name == other._name
                and list(self._fields.values()) == list(other._fields.values()))

    def __ne__(self, other):
        return not self == other

    def __hash__(self):
        return hash((self._name, tuple(self._fields.values())))

    def __repr__(self):
        lines = ['  {!r}'.format(f) for f in self._fields.values()]
        return 'Unischema({!r}, [\n{}\n])'.format(self._name, ',\n'.join(lines))

    # -- views --------------------------------------------------------------------

    def create_schema_view(self, fields_or_patterns):
        """Subset view from UnischemaField instances, field names, or regex patterns
        (reference: petastorm/unischema.py:199-240). Field order follows *schema* order."""
        if isinstance(fields_or_patterns, (str, UnischemaField)):
            fields_or_patterns = [fields_or_patterns]
        patterns = []
        for item in fields_or_patterns:
            if isinstance(item, UnischemaField):
                if item.name not in self._fields:
                    raise ValueError('Field {!r} does not belong to schema {!r}'
                                     .format(item.name, self._name))
                patterns.append(re.escape(item.name))
            elif isinstance(item, str):
                patterns.append(item)
            else:
                raise ValueError('create_schema_view accepts UnischemaFields, names or '
                                 'regex patterns; got {!r}'.format(item))
        matched = match_unischema_fields(self, patterns)
        matched_names = {f.name for f in matched}
        view_fields = [f for f in self._fields.values() if f.name in matched_names]
        if not view_fields:
            raise ValueError('create_schema_view matched no fields of schema {!r} '
                             'with patterns {!r}'.format(self._name, patterns))
        return Unischema('{}_view'.format(self._name), view_fields)

    # -- row rendering ------------------------------------------------------------

    def make_namedtuple(self, **kwargs):
        """Build a row namedtuple from keyword args (reference: unischema.py:245-259)."""
        return self.namedtuple(**{k: kwargs[k] for k in self._fields})

    def make_namedtuple_from_dict(self, row_dict):
        return self.namedtuple(**{k: row_dict[k] for k in self._fields})

    @property
    def namedtuple(self):
        """The cached namedtuple class for this schema's field set."""
        return _NamedtupleCache.get(self._name, list(self._fields))

    # -- renders ------------------------------------------------------------------

    def as_arrow_schema(self):
        """Arrow schema of the *encoded* (storage) representation."""
        pa_fields = [pa.field(f.name, f.arrow_type(), nullable=bool(f.nullable))
                     for f in self._fields.values()]
        return pa.schema(pa_fields)

    def as_shape_dtype_structs(self, batch_dims=()):
        """Dict of field name -> jax.ShapeDtypeStruct for device-representable fields."""
        return {f.name: f.shape_dtype_struct(batch_dims) for f in self._fields.values()}

    # -- JSON serialization -------------------------------------------------------

    def to_json_dict(self):
        return {
            'version': 1,
            'name': self._name,
            'fields': [f.to_json_dict() for f in self._fields.values()],
        }

    @classmethod
    def from_json_dict(cls, schema_dict):
        version = schema_dict.get('version', 1)
        if version != 1:
            raise ValueError('Unsupported schema version {}'.format(version))
        return cls(schema_dict['name'],
                   [UnischemaField.from_json_dict(f) for f in schema_dict['fields']])

    # -- inference ----------------------------------------------------------------

    @classmethod
    def from_arrow_schema(cls, arrow_schema, omit_unsupported_fields=True, name='inferred'):
        """Infer a Unischema from a plain Parquet/Arrow schema for non-petastorm stores
        (reference: petastorm/unischema.py:302-342 + _numpy_and_codec_from_arrow_type
        :456-491). List types become shape ``(None,)``; unsupported types are skipped with
        a warning (or raise when ``omit_unsupported_fields=False``)."""
        import warnings
        fields = []
        for arrow_field in arrow_schema:
            try:
                numpy_dtype, shape = _numpy_from_arrow_type(arrow_field.type)
            except ValueError as exc:
                if omit_unsupported_fields:
                    warnings.warn('Surpressing unsupported field {!r}: {}'
                                  .format(arrow_field.name, exc))
                    continue
                raise
            fields.append(UnischemaField(arrow_field.name, numpy_dtype, shape,
                                         codec=None, nullable=arrow_field.nullable))
        return cls(name, fields)


def _numpy_from_arrow_type(arrow_type):
    """Map an Arrow type to (numpy_dtype, shape) (reference: unischema.py:456-491)."""
    import pyarrow.types as patypes
    if patypes.is_list(arrow_type) or patypes.is_large_list(arrow_type):
        inner_dtype, inner_shape = _numpy_from_arrow_type(arrow_type.value_type)
        if inner_shape != ():
            raise ValueError('Nested list type {} is not supported'.format(arrow_type))
        return inner_dtype, (None,)
    if patypes.is_decimal(arrow_type):
        return Decimal, ()
    if patypes.is_string(arrow_type) or patypes.is_large_string(arrow_type):
        return np.dtype('str_'), ()
    if patypes.is_binary(arrow_type) or patypes.is_large_binary(arrow_type):
        return np.dtype('bytes_'), ()
    if patypes.is_timestamp(arrow_type) or patypes.is_date(arrow_type):
        return np.dtype('datetime64[ns]'), ()
    try:
        return np.dtype(arrow_type.to_pandas_dtype()), ()
    except (NotImplementedError, pa.ArrowNotImplementedError):
        raise ValueError('Arrow type {} has no numpy mapping'.format(arrow_type))


def match_unischema_fields(schema, field_regexes):
    """Return schema fields whose names fullmatch any of the given regex patterns
    (reference: petastorm/unischema.py:426-453 — the legacy ``re.match`` prefix behavior is
    intentionally not reproduced; fullmatch is the documented modern semantics)."""
    if not field_regexes:
        return []
    compiled = [re.compile(p) for p in field_regexes]
    return [field for name, field in schema.fields.items()
            if any(c.fullmatch(name) for c in compiled)]


def dict_to_encoded_row(schema, row_dict):
    """Validate and codec-encode one row dict into its storage representation — the analog
    of the reference's ``dict_to_spark_row`` (petastorm/unischema.py:348-384) without the
    Spark Row dependency: the output feeds the Arrow writer (etl.dataset_metadata).

    Validates field membership and nullability; leaves ``None`` for nullable fields.
    """
    if not isinstance(row_dict, dict):
        raise TypeError('row_dict must be a dict, got {!r}'.format(type(row_dict)))
    input_names = set(row_dict)
    schema_names = set(schema.fields)
    unknown = input_names - schema_names
    if unknown:
        raise ValueError('Fields {} are not part of schema {!r}'.format(sorted(unknown),
                                                                        schema.name))
    full_dict = insert_explicit_nulls(schema, copy.copy(row_dict))
    encoded = {}
    for name, field in schema.fields.items():
        value = full_dict[name]
        if value is None:
            if not field.nullable:
                raise ValueError('Field {} is not nullable but got None'.format(name))
            encoded[name] = None
        elif field.codec is not None:
            encoded[name] = field.codec.encode(field, value)
        else:
            encoded[name] = _default_encode(field, value)
    return encoded


def _default_encode(field, value):
    """Encode a codec-less field (scalar or 1-d list column) for the Arrow writer."""
    if isinstance(value, np.ndarray):
        if value.ndim == 0:
            return value.item()
        if value.ndim == 1:
            return value.tolist()
        raise ValueError('Field {} has no codec; cannot store {}-dim array natively'
                         .format(field.name, value.ndim))
    if isinstance(value, np.generic):
        return value.item()
    return value


def insert_explicit_nulls(schema, row_dict):
    """Add explicit ``None`` entries for missing nullable fields; raise for missing
    non-nullable ones (reference: petastorm/unischema.py:387-401)."""
    for name, field in schema.fields.items():
        if name not in row_dict:
            if field.nullable:
                row_dict[name] = None
            else:
                raise ValueError('Field {} is not found in row and is not nullable'
                                 .format(name))
    return row_dict


def decode_row(row_dict, schema):
    """Decode one encoded row dict back to numpy values via codecs (reference:
    petastorm/utils.py:54-87)."""
    from petastorm_tpu.errors import DecodeFieldError
    decoded = {}
    for name, value in row_dict.items():
        field = schema.fields.get(name)
        if field is None:
            decoded[name] = value
            continue
        if value is None:
            decoded[name] = None
            continue
        try:
            if field.codec is not None:
                decoded[name] = field.codec.decode(field, value)
            elif field.numpy_dtype is Decimal:
                decoded[name] = value if isinstance(value, Decimal) else Decimal(str(value))
            elif field.shape == () and np.dtype(field.numpy_dtype).kind not in ('U', 'S', 'O'):
                decoded[name] = np.dtype(field.numpy_dtype).type(value)
            elif field.shape != ():
                decoded[name] = np.asarray(value, dtype=_list_item_dtype(field))
            else:
                decoded[name] = value
        except Exception as exc:
            raise DecodeFieldError('Failed to decode field {!r}: {}'.format(name, exc),
                                   field_name=name) from exc
    return decoded


def _list_item_dtype(field):
    dtype = np.dtype(field.numpy_dtype)
    if dtype.kind in ('U', 'S', 'O'):
        return object
    return dtype
