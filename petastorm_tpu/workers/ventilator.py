"""Work ventilation with bounded in-flight items and per-epoch reshuffling (reference:
petastorm/workers_pool/ventilator.py:26-168).

The ventilator is the scheduler's output stage: it feeds work items (rowgroup descriptors)
into a pool at a bounded rate so memory stays bounded regardless of dataset size, and
re-feeds them every epoch, optionally in a new seeded random order.
"""

import threading

import numpy as np


class Ventilator(object):
    """Abstract ventilator (reference: ventilator.py:26-60)."""

    def __init__(self, ventilate_fn):
        self._ventilate_fn = ventilate_fn

    def start(self):
        raise NotImplementedError()

    def processed_item(self):
        """Feedback from the consumer that one ventilated item finished — used for
        backpressure accounting."""
        raise NotImplementedError()

    def completed(self):
        """True when no more items will ever be ventilated."""
        raise NotImplementedError()

    def stop(self):
        raise NotImplementedError()


class ConcurrentVentilator(Ventilator):
    """Feeds ``items_to_ventilate`` (list of kwargs dicts) from a daemon thread, keeping at
    most ``max_ventilation_queue_size`` items in flight, for ``iterations`` epochs
    (None = infinite), optionally shuffling item order each epoch with a seeded RNG
    (reference: ventilator.py:63-168)."""

    def __init__(self, ventilate_fn, items_to_ventilate, iterations=1,
                 max_ventilation_queue_size=None, randomize_item_order=False,
                 random_seed=None, pre_shuffle_count=0, skip_ids_by_iteration=None,
                 item_id_fn=None, reset_iterations=None, tag_epoch=False,
                 order_fn=None):
        """Resume-from-checkpoint support: the RNG stream is advanced by
        ``pre_shuffle_count`` epoch-shuffles (reproducing the item order of the epoch
        being resumed); items whose ``item_id_fn(item)`` appears in
        ``skip_ids_by_iteration[k]`` are skipped during the k-th pass after construction
        (they were consumed before the checkpoint; results can straddle several epochs,
        hence a per-iteration map, not a single set). With ``tag_epoch`` every ventilated
        call gets an ``epoch_index`` kwarg carrying the absolute epoch
        (``pre_shuffle_count`` + completed passes) so consumers can attribute results to
        epochs even when completions interleave across an epoch boundary.
        ``reset_iterations`` is what :meth:`reset` restores (defaults to ``iterations``;
        a resumed reader passes its full ``num_epochs`` so reset keeps its documented
        meaning). ``order_fn(items, random_state) -> items`` replaces the plain seeded
        shuffle at every reorder point (epoch starts and resume pre-shuffles) — the
        cost-aware scheduler's hook (docs/performance.md "Cost-aware scheduling");
        None (default) keeps the byte-identical ``random_state.shuffle`` path."""
        super().__init__(ventilate_fn)
        if iterations is not None and (not isinstance(iterations, int) or iterations < 1):
            raise ValueError('iterations must be a positive integer or None, got {!r}'
                             .format(iterations))
        self._items_to_ventilate = list(items_to_ventilate)
        self._iterations = iterations
        self._iterations_remaining = iterations
        self._reset_iterations = (reset_iterations if reset_iterations is not None
                                  else iterations)
        self._max_ventilation_queue_size = (max_ventilation_queue_size
                                            or len(self._items_to_ventilate) or 1)
        self._randomize_item_order = randomize_item_order
        self._random_state = np.random.RandomState(random_seed)
        self._order_fn = order_fn
        if randomize_item_order:
            for _ in range(pre_shuffle_count):
                self._reorder()
        self._skip_ids_by_iteration = {int(k): set(v)
                                       for k, v in (skip_ids_by_iteration or {}).items()}
        self._item_id_fn = item_id_fn or (lambda item: None)
        self._tag_epoch = tag_epoch
        self._pass_offset = 0
        self._absolute_epoch = pre_shuffle_count

        self._in_flight = 0
        self._current_item_to_ventilate = 0
        self._stop_requested = threading.Event()
        self._completed = threading.Event()
        self._lock = threading.Lock()
        self._item_processed = threading.Condition(self._lock)
        self._thread = None
        #: exception raised by ventilate_fn, surfaced to the consumer via pools
        self.error = None

        if not self._items_to_ventilate:
            # Nothing will ever be ventilated: complete immediately (empty shard case).
            self._completed.set()

    def start(self):
        if self._thread is not None:
            raise RuntimeError('Ventilator already started')
        self._thread = threading.Thread(target=self._ventilate, daemon=True,
                                        name='petastorm-tpu-ventilator')
        self._thread.start()

    def _reorder(self):
        """One epoch reorder: the custom ``order_fn`` when set (it receives the
        RNG and consumes its stream exactly like the plain path), else the
        reference's in-place seeded shuffle."""
        if self._order_fn is not None:
            self._items_to_ventilate = list(
                self._order_fn(self._items_to_ventilate, self._random_state))
        else:
            self._random_state.shuffle(self._items_to_ventilate)

    def _ventilate(self):
        if self._randomize_item_order:
            self._reorder()
        while not self._stop_requested.is_set():
            if self._completed.is_set():
                return
            item = self._items_to_ventilate[self._current_item_to_ventilate]
            skip_ids = self._skip_ids_by_iteration.get(self._pass_offset)
            skip = bool(skip_ids) and self._item_id_fn(item) in skip_ids
            if not skip:
                with self._item_processed:
                    while (self._in_flight >= self._max_ventilation_queue_size
                           and not self._stop_requested.is_set()):
                        self._item_processed.wait(timeout=0.1)
                    if self._stop_requested.is_set():
                        return
                    self._in_flight += 1
            self._current_item_to_ventilate += 1
            try:
                if not skip:
                    if self._tag_epoch:
                        self._ventilate_fn(epoch_index=self._absolute_epoch, **item)
                    else:
                        self._ventilate_fn(**item)
            except Exception as exc:  # noqa: BLE001 - surface to consumer, never hang
                self.error = exc
                self._completed.set()
                return
            if self._current_item_to_ventilate >= len(self._items_to_ventilate):
                self._current_item_to_ventilate = 0
                self._skip_ids_by_iteration.pop(self._pass_offset, None)
                self._pass_offset += 1
                self._absolute_epoch += 1
                if self._iterations_remaining is not None:
                    self._iterations_remaining -= 1
                    if self._iterations_remaining <= 0:
                        self._completed.set()
                        return
                if self._randomize_item_order:
                    self._reorder()

    def processed_item(self):
        with self._item_processed:
            if self._in_flight > 0:
                self._in_flight -= 1
            self._item_processed.notify()

    @property
    def max_in_flight(self):
        """The current in-flight bound (``max_ventilation_queue_size``)."""
        with self._lock:
            return self._max_ventilation_queue_size

    def set_max_in_flight(self, value):
        """Bounded, thread-safe runtime resize of the in-flight window — the
        ventilation-depth knob the autotuner turns mid-epoch
        (docs/autotuning.md). Growing wakes the ventilation thread immediately
        (it may be parked in the backpressure wait); shrinking simply stops
        admitting new items until consumption drains below the new bound —
        items already in flight are never recalled. Returns the applied value."""
        value = int(value)
        if value < 1:
            raise ValueError('max_in_flight must be >= 1, got {}'.format(value))
        with self._item_processed:
            self._max_ventilation_queue_size = value
            self._item_processed.notify_all()
        return value

    def completed(self):
        # All epochs dispatched AND every dispatched item acknowledged (or failed).
        with self._lock:
            if self.error is not None:
                return True
            return self._completed.is_set() and self._in_flight == 0

    def reset(self):
        """Restart ventilation for another round of ``iterations`` epochs after the
        previous ones fully completed (reference: ventilator.py:127-136)."""
        if not self.completed():
            raise RuntimeError('Cannot reset a ventilator that has not completed all '
                               'items (in-flight work remains)')
        self._join_thread()
        self._completed.clear()
        self._stop_requested.clear()
        self._current_item_to_ventilate = 0
        # Full reset_iterations, not the (possibly resume-reduced) first-run iterations;
        # the RNG stream and absolute epoch counter continue uninterrupted.
        self._iterations_remaining = self._reset_iterations
        self._thread = None
        self.start()

    def stop(self):
        self._stop_requested.set()
        with self._item_processed:
            self._item_processed.notify_all()
        self._join_thread()

    def _join_thread(self):
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=10)
