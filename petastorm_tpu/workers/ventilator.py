"""Work ventilation with bounded in-flight items and per-epoch reshuffling (reference:
petastorm/workers_pool/ventilator.py:26-168).

The ventilator is the scheduler's output stage: it feeds work items (rowgroup descriptors)
into a pool at a bounded rate so memory stays bounded regardless of dataset size, and
re-feeds them every epoch, optionally in a new seeded random order.
"""

import threading

import numpy as np


class Ventilator(object):
    """Abstract ventilator (reference: ventilator.py:26-60)."""

    def __init__(self, ventilate_fn):
        self._ventilate_fn = ventilate_fn

    def start(self):
        raise NotImplementedError()

    def processed_item(self):
        """Feedback from the consumer that one ventilated item finished — used for
        backpressure accounting."""
        raise NotImplementedError()

    def completed(self):
        """True when no more items will ever be ventilated."""
        raise NotImplementedError()

    def stop(self):
        raise NotImplementedError()


class ConcurrentVentilator(Ventilator):
    """Feeds ``items_to_ventilate`` (list of kwargs dicts) from a daemon thread, keeping at
    most ``max_ventilation_queue_size`` items in flight, for ``iterations`` epochs
    (None = infinite), optionally shuffling item order each epoch with a seeded RNG
    (reference: ventilator.py:63-168)."""

    def __init__(self, ventilate_fn, items_to_ventilate, iterations=1,
                 max_ventilation_queue_size=None, randomize_item_order=False, random_seed=None):
        super().__init__(ventilate_fn)
        if iterations is not None and (not isinstance(iterations, int) or iterations < 1):
            raise ValueError('iterations must be a positive integer or None, got {!r}'
                             .format(iterations))
        self._items_to_ventilate = list(items_to_ventilate)
        self._iterations = iterations
        self._iterations_remaining = iterations
        self._max_ventilation_queue_size = (max_ventilation_queue_size
                                            or len(self._items_to_ventilate) or 1)
        self._randomize_item_order = randomize_item_order
        self._random_state = np.random.RandomState(random_seed)

        self._in_flight = 0
        self._current_item_to_ventilate = 0
        self._stop_requested = threading.Event()
        self._completed = threading.Event()
        self._lock = threading.Lock()
        self._item_processed = threading.Condition(self._lock)
        self._thread = None
        #: exception raised by ventilate_fn, surfaced to the consumer via pools
        self.error = None

        if not self._items_to_ventilate:
            # Nothing will ever be ventilated: complete immediately (empty shard case).
            self._completed.set()

    def start(self):
        if self._thread is not None:
            raise RuntimeError('Ventilator already started')
        self._thread = threading.Thread(target=self._ventilate, daemon=True,
                                        name='petastorm-tpu-ventilator')
        self._thread.start()

    def _ventilate(self):
        if self._randomize_item_order:
            self._random_state.shuffle(self._items_to_ventilate)
        while not self._stop_requested.is_set():
            if self._completed.is_set():
                return
            with self._item_processed:
                while (self._in_flight >= self._max_ventilation_queue_size
                       and not self._stop_requested.is_set()):
                    self._item_processed.wait(timeout=0.1)
                if self._stop_requested.is_set():
                    return
                self._in_flight += 1
            item = self._items_to_ventilate[self._current_item_to_ventilate]
            self._current_item_to_ventilate += 1
            try:
                self._ventilate_fn(**item)
            except Exception as exc:  # noqa: BLE001 - surface to consumer, never hang
                self.error = exc
                self._completed.set()
                return
            if self._current_item_to_ventilate >= len(self._items_to_ventilate):
                self._current_item_to_ventilate = 0
                if self._iterations_remaining is not None:
                    self._iterations_remaining -= 1
                    if self._iterations_remaining <= 0:
                        self._completed.set()
                        return
                if self._randomize_item_order:
                    self._random_state.shuffle(self._items_to_ventilate)

    def processed_item(self):
        with self._item_processed:
            if self._in_flight > 0:
                self._in_flight -= 1
            self._item_processed.notify()

    def completed(self):
        # All epochs dispatched AND every dispatched item acknowledged (or failed).
        with self._lock:
            if self.error is not None:
                return True
            return self._completed.is_set() and self._in_flight == 0

    def reset(self):
        """Restart ventilation for another round of ``iterations`` epochs after the
        previous ones fully completed (reference: ventilator.py:127-136)."""
        if not self.completed():
            raise RuntimeError('Cannot reset a ventilator that has not completed all '
                               'items (in-flight work remains)')
        self._join_thread()
        self._completed.clear()
        self._stop_requested.clear()
        self._current_item_to_ventilate = 0
        self._iterations_remaining = self._iterations
        self._thread = None
        self.start()

    def stop(self):
        self._stop_requested.set()
        with self._item_processed:
            self._item_processed.notify_all()
        self._join_thread()

    def _join_thread(self):
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=10)
