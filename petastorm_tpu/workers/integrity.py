"""Payload integrity helpers for the zero-copy data plane (docs/robustness.md
"Hang detection & circuit breakers").

The shm slot ring and the Arrow-IPC disk cache both hand the consumer bytes
that no kernel checksum protects end-to-end: a torn slot write (producer died
mid-copy with a reused generation), a bit flip in page cache, or a truncated
cache file would flow straight into training arrays. Every shm descriptor and
every cache entry therefore carries a CRC of its payload, verified on the
consuming side before a single byte is interpreted.

The checksum is CRC-32 via :func:`zlib.crc32` (castagnoli-polynomial ``crc32c``
would be preferable for hardware acceleration, but this image ships no crc32c
binding and the data plane must not grow a dependency for it); the chained-
update form lets multi-frame payloads be summed without concatenation. A
deterministic test-only corruption hook (:func:`corrupt_for_test`) flips one
byte of a freshly written slot when the ``PETASTORM_TPU_TEST_SHM_CORRUPT``
env var names a marker-file state dir — the same global-atomic-claim scheme
``test_util.fault_injection`` uses, so "corrupt the first N shm writes" is
exact across every worker process.
"""

from __future__ import annotations

import os
import zlib
from typing import Iterable, Union

Frame = Union[bytes, bytearray, memoryview]

#: env var enabling the deterministic shm-write corruption hook; value is
#: ``<state_dir>:<times>`` (flip one byte in each of the first <times> slot
#: writes, globally across worker processes)
TEST_SHM_CORRUPT_ENV = 'PETASTORM_TPU_TEST_SHM_CORRUPT'


def payload_checksum(frames: Iterable[Frame]) -> int:
    """Chained CRC-32 over ``frames`` in order (equal to the CRC of their
    concatenation); returns an unsigned 32-bit int."""
    crc = 0
    for frame in frames:
        crc = zlib.crc32(frame, crc)
    return crc & 0xFFFFFFFF


def _claim_marker(state_dir: str, prefix: str) -> int:
    """Atomically claim the next global sequence number for ``prefix`` in
    ``state_dir`` (``O_CREAT|O_EXCL`` marker files, exactly as
    ``test_util.fault_injection.FaultSchedule`` does)."""
    index = 0
    while True:
        marker = os.path.join(state_dir, '{}.{}'.format(prefix, index))
        try:
            fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            index += 1
            continue
        os.close(fd)
        return index


def corrupt_for_test(buf: memoryview, offset: int, length: int) -> bool:
    """Test-only hook: when :data:`TEST_SHM_CORRUPT_ENV` is set to
    ``<state_dir>:<times>``, flip one byte in the middle of
    ``buf[offset:offset+length]`` for each of the first ``times`` calls
    globally (across processes). Returns True when a byte was flipped. A no-op
    (False) in production — one env lookup per slot write."""
    spec = os.environ.get(TEST_SHM_CORRUPT_ENV)
    if not spec or length <= 0:
        return False
    state_dir, _, times_str = spec.rpartition(':')
    seq = _claim_marker(state_dir, 'shm-corrupt')
    if seq >= int(times_str):
        return False
    target = offset + length // 2
    buf[target] = buf[target] ^ 0xFF
    return True
