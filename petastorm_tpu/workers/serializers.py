"""Pluggable payload serializers for the process-pool wire (reference:
petastorm/reader_impl/pickle_serializer.py:17-23 and arrow_table_serializer.py:18-33;
selection plumbing at petastorm/workers_pool/process_pool.py:251-270).

The wire unit here is :class:`~petastorm_tpu.reader_worker.ColumnarBatch` (decoded numpy
columns), not a ``pa.Table`` as in the reference — decode happens worker-side, so the
serializer must move numpy, not Arrow-native, columns. :class:`ArrowIpcSerializer`
re-encodes the uniform numeric columns into ONE Arrow record batch shipped as a single
IPC-stream frame: the receive side maps it back with ``to_numpy(zero_copy_only=True)``
over the incoming ZMQ frame's memory — no per-column copy, no pickle of array data.
Columns Arrow can't hold zero-copy (ragged lists, object/string arrays, bit-packed
bools) ride a pickled sidecar frame. Any non-ColumnarBatch payload (e.g. NGram window
lists) falls back to plain pickle transparently.

A serializer turns a payload into a list of byte frames and back:

    serialize(obj) -> [frame, ...]      deserialize([frame, ...]) -> obj

Frames are whatever ZMQ ``send_multipart`` accepts (bytes / memoryview / pa.Buffer).
"""

import json
import pickle

import numpy as np

_MARKER_PICKLE = b'P'
_MARKER_ARROW = b'A'
_META_KEY = b'petastorm_tpu.columnar.v1'


class PickleSerializer(object):
    """Whole-object pickle — always correct, copies everything (reference:
    reader_impl/pickle_serializer.py:17-23)."""

    def serialize(self, obj):
        return [_MARKER_PICKLE, pickle.dumps(obj, protocol=5)]

    def deserialize(self, frames):
        return pickle.loads(_as_bytes(frames[1]))


class ArrowIpcSerializer(object):
    """Arrow IPC stream for the numeric columns of a ColumnarBatch (reference:
    reader_impl/arrow_table_serializer.py:18-33).

    Frame layout: ``[b'A', ipc_stream, pickled_sidecar]`` where the IPC stream holds one
    record batch (multi-dim columns flattened to FixedSizeList, original shapes/dtypes in
    schema metadata) and the sidecar holds ``{name: column}`` for non-Arrow-zero-copy
    columns plus ``num_rows``/``item_id``.

    ``writable=True`` (default) copies each numeric column once on receive, yielding
    ordinary writable numpy arrays — same observable behavior as the thread/dummy pools
    (one memcpy per column; still cheaper than pickle, which copies on both ends and
    re-allocates object graphs). ``writable=False`` is the true zero-copy mode: columns
    alias the single incoming IPC frame and are READ-ONLY — and because all numeric
    columns share that frame, retaining any row/column view pins the whole batch's frame
    memory. Use it when the consumer is a device loader that only reads
    (e.g. JaxDataLoader assembling device arrays)."""

    def __init__(self, writable=True):
        self._writable = writable

    def serialize(self, obj):
        from petastorm_tpu.reader_worker import ColumnarBatch
        if not isinstance(obj, ColumnarBatch):
            return PickleSerializer().serialize(obj)
        import pyarrow as pa

        arrow_arrays, arrow_names, col_meta = [], [], {}
        sidecar_cols = {}
        for name, col in obj.columns.items():
            if (isinstance(col, np.ndarray) and col.ndim >= 1
                    and col.dtype.kind in 'iuf' and len(col) == obj.num_rows):
                arr = np.ascontiguousarray(col)
                # explicit inner size: reshape(n, -1) cannot infer an axis when n == 0
                inner = int(np.prod(arr.shape[1:], dtype=np.int64)) if arr.ndim > 1 else 1
                flat = arr.reshape(len(arr), inner) if arr.ndim > 1 else arr
                pa_arr = pa.array(flat.ravel())
                if arr.ndim > 1:
                    pa_arr = pa.FixedSizeListArray.from_arrays(pa_arr, flat.shape[1])
                arrow_arrays.append(pa_arr)
                arrow_names.append(name)
                col_meta[name] = {'dtype': arr.dtype.str, 'shape': list(arr.shape[1:])}
            else:
                sidecar_cols[name] = col

        meta = {'num_rows': int(obj.num_rows),
                'item_id': ([int(part) for part in obj.item_id]
                            if obj.item_id is not None else None),
                'columns': col_meta,
                # resilience sidecar (docs/robustness.md): plain-JSON fields, so the
                # quarantine ledger and retry counters cross the process boundary
                # without pickling framework types
                'retries': int(getattr(obj, 'retries', 0) or 0),
                'quarantine': (obj.quarantine.as_dict()
                               if getattr(obj, 'quarantine', None) is not None
                               else None)}
        schema = pa.schema([pa.field(n, a.type) for n, a in zip(arrow_names, arrow_arrays)],
                           metadata={_META_KEY: json.dumps(meta).encode('utf-8')})
        batch = pa.record_batch(arrow_arrays, schema=schema)
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, schema) as writer:
            writer.write_batch(batch)
        return [_MARKER_ARROW, sink.getvalue(), pickle.dumps(sidecar_cols, protocol=5)]

    def deserialize(self, frames):
        marker = _as_bytes(frames[0])
        if marker == _MARKER_PICKLE:
            return PickleSerializer().deserialize(frames)
        import pyarrow as pa
        from petastorm_tpu.reader_worker import ColumnarBatch

        buf = pa.py_buffer(_as_memory(frames[1]))
        with pa.ipc.open_stream(buf) as reader:
            batch = reader.read_next_batch()
            meta = json.loads(batch.schema.metadata[_META_KEY].decode('utf-8'))
        columns = pickle.loads(_as_bytes(frames[2]))
        for i, field in enumerate(batch.schema):
            col = batch.column(i)
            spec = meta['columns'][field.name]
            shape = tuple(spec['shape'])
            if shape:
                values = col.flatten().to_numpy(zero_copy_only=(len(col) > 0))
                values = values.reshape((len(col),) + shape)
            else:
                values = col.to_numpy(zero_copy_only=(len(col) > 0))
            # astype(copy=False) is a no-op when dtypes already match (the usual case)
            values = values.astype(spec['dtype'], copy=False)
            if self._writable and not values.flags.writeable:
                values = values.copy()
            columns[field.name] = values
        item_id = meta['item_id']
        quarantine = meta.get('quarantine')
        if quarantine is not None:
            from petastorm_tpu.resilience import QuarantineRecord
            quarantine = QuarantineRecord(**quarantine)
        return ColumnarBatch(columns, meta['num_rows'],
                             item_id=tuple(item_id) if item_id is not None else None,
                             retries=meta.get('retries', 0), quarantine=quarantine)


def _as_bytes(frame):
    """bytes from a bytes / memoryview / zmq.Frame / pa.Buffer wire frame."""
    if isinstance(frame, bytes):
        return frame
    return bytes(_as_memory(frame))


def _as_memory(frame):
    if isinstance(frame, memoryview):
        return frame
    buffer = getattr(frame, 'buffer', None)  # zmq.Frame (copy=False receive)
    if buffer is not None:
        return buffer
    return memoryview(frame)
