"""Pluggable payload serializers for the process-pool wire (reference:
petastorm/reader_impl/pickle_serializer.py:17-23 and arrow_table_serializer.py:18-33;
selection plumbing at petastorm/workers_pool/process_pool.py:251-270).

The wire unit here is :class:`~petastorm_tpu.reader_worker.ColumnarBatch` (decoded numpy
columns), not a ``pa.Table`` as in the reference — decode happens worker-side, so the
serializer must move numpy, not Arrow-native, columns. :class:`ArrowIpcSerializer`
re-encodes the uniform numeric columns into ONE Arrow record batch shipped as a single
IPC-stream frame: the receive side maps it back with ``to_numpy(zero_copy_only=True)``
over the incoming frame's memory (a ZMQ frame, or a shared-memory ring slot — see
``workers/shm_ring.py``) — no per-column copy, no pickle of array data. Columns Arrow
can't hold zero-copy (ragged lists, object/string arrays, bit-packed bools) ride a
pickled sidecar frame. Any non-ColumnarBatch payload (e.g. NGram window lists) falls
back to plain pickle transparently.

The columnar encode/decode pair is exposed as module functions
(:func:`encode_columnar`, :func:`decode_columnar`) because the mmap rowgroup cache
(``petastorm_tpu.cache.ArrowIpcDiskCache``) stores exactly the same byte layout on
disk: one wire format, two transports (socket/shm ring and mmap file).

A serializer turns a payload into a list of byte frames and back:

    serialize(obj) -> [frame, ...]      deserialize([frame, ...]) -> obj

Frames are whatever ZMQ ``send_multipart`` accepts (bytes / memoryview / pa.Buffer).
Each serializer keeps a ``stats`` dict updated on the DESERIALIZE (consumer) side —
for the process pool that is the main process, so degradation to copy-mode (columns
falling off the Arrow zero-copy path into the pickled sidecar) is visible in
``ProcessPool.diagnostics`` / ``Reader.diagnostics`` without any extra channel.
"""

from __future__ import annotations

import json
import pickle
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

#: a wire frame: whatever ZMQ send_multipart accepts and recv hands back
#: (bytes / memoryview / zmq.Frame / pa.Buffer) — structurally Any because the
#: concrete types come from optional dependencies
Frame = Any

_MARKER_PICKLE = b'P'
_MARKER_ARROW = b'A'
_META_KEY = b'petastorm_tpu.columnar.v1'

#: cap on distinct column names remembered in stats['sidecar_column_names'] — the
#: counter must stay O(schema), not O(stream)
_SIDECAR_NAMES_CAP = 64


def _new_wire_stats() -> Dict[str, Any]:
    """Fresh consumer-side wire counters (see module docstring): ``batches`` received,
    ``bytes_copied`` (bytes materialized into new host memory on receive: pickle
    payloads, writable column copies, sidecar bytes), ``bytes_zero_copy`` (bytes served
    as views over the incoming frame), ``sidecar_columns`` (column instances that fell
    off the Arrow path into the pickled sidecar) and the distinct
    ``sidecar_column_names`` (capped)."""
    return {'batches': 0, 'bytes_copied': 0, 'bytes_zero_copy': 0,
            'sidecar_columns': 0, 'sidecar_column_names': []}


def _columns_num_rows(columns: Mapping[str, Any]) -> int:
    """The columnar row-count convention shared by the wire codec, the rowgroup
    worker and the cache: the first column's length (0 for an empty dict)."""
    for col in columns.values():
        return len(col)
    return 0


def encode_columnar(columns: Mapping[str, Any], num_rows: int,
                    meta_extra: Optional[Mapping[str, Any]] = None
                    ) -> Tuple[Any, bytes, List[str]]:
    """Encode ``{name: ndarray-or-list}`` into ``(ipc_bytes, sidecar_bytes,
    sidecar_names)``: uniform numeric ndarrays become ONE Arrow record batch
    (multi-dim columns flattened to FixedSizeList, original shapes/dtypes in schema
    metadata), everything else ships in a pickled sidecar dict. ``meta_extra`` is a
    JSON-safe dict merged into the schema metadata (the wire's resilience/cache
    sidecar fields ride here)."""
    import pyarrow as pa

    arrow_arrays: List[Any] = []
    arrow_names: List[str] = []
    col_meta: Dict[str, Any] = {}
    sidecar_cols: Dict[str, Any] = {}
    for name, col in columns.items():
        if (isinstance(col, np.ndarray) and col.ndim >= 1
                and col.dtype.kind in 'iuf' and len(col) == num_rows):
            arr = np.ascontiguousarray(col)
            # explicit inner size: reshape(n, -1) cannot infer an axis when n == 0
            inner = int(np.prod(arr.shape[1:], dtype=np.int64)) if arr.ndim > 1 else 1
            flat = arr.reshape(len(arr), inner) if arr.ndim > 1 else arr
            pa_arr = pa.array(flat.ravel())
            if arr.ndim > 1:
                pa_arr = pa.FixedSizeListArray.from_arrays(pa_arr, flat.shape[1])
            arrow_arrays.append(pa_arr)
            arrow_names.append(name)
            col_meta[name] = {'dtype': arr.dtype.str, 'shape': list(arr.shape[1:])}
        else:
            sidecar_cols[name] = col

    meta: Dict[str, Any] = {'num_rows': int(num_rows), 'columns': col_meta}
    if meta_extra:
        meta.update(meta_extra)
    schema = pa.schema([pa.field(n, a.type) for n, a in zip(arrow_names, arrow_arrays)],
                       metadata={_META_KEY: json.dumps(meta).encode('utf-8')})
    batch = pa.record_batch(arrow_arrays, schema=schema)
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, schema) as writer:
        writer.write_batch(batch)
    return (sink.getvalue(), pickle.dumps(sidecar_cols, protocol=5),
            sorted(sidecar_cols))


def decode_columnar(ipc_frame: Frame, sidecar_frame: Frame,
                    writable: bool = True,
                    stats: Optional[Dict[str, Any]] = None
                    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Decode the :func:`encode_columnar` pair back into ``(columns, meta)``.

    ``ipc_frame``/``sidecar_frame`` may be bytes, memoryviews (ZMQ frame or shm slot)
    or ``pa.Buffer`` (mmap region). With ``writable=False`` numeric columns are
    READ-ONLY zero-copy views aliasing ``ipc_frame``'s memory — the caller owns that
    memory's lifetime. ``stats`` (a :func:`_new_wire_stats` dict) is updated in place
    when given."""
    import pyarrow as pa

    buf = ipc_frame if isinstance(ipc_frame, pa.Buffer) \
        else pa.py_buffer(_as_memory(ipc_frame))
    with pa.ipc.open_stream(buf) as reader:
        batch = reader.read_next_batch()
        meta = json.loads(batch.schema.metadata[_META_KEY].decode('utf-8'))
    sidecar_blob = _as_bytes(sidecar_frame)
    columns: Dict[str, Any] = pickle.loads(sidecar_blob)
    if stats is not None:
        stats['batches'] += 1
        stats['bytes_copied'] += len(sidecar_blob)
        stats['sidecar_columns'] += len(columns)
        names = stats['sidecar_column_names']
        for name in columns:
            if name not in names and len(names) < _SIDECAR_NAMES_CAP:
                names.append(name)
    for i, field in enumerate(batch.schema):
        col = batch.column(i)
        spec = meta['columns'][field.name]
        shape = tuple(spec['shape'])
        if shape:
            values = col.flatten().to_numpy(zero_copy_only=(len(col) > 0))
            values = values.reshape((len(col),) + shape)
        else:
            values = col.to_numpy(zero_copy_only=(len(col) > 0))
        # astype(copy=False) is a no-op when dtypes already match (the usual case)
        values = values.astype(spec['dtype'], copy=False)
        if writable and not values.flags.writeable:
            values = values.copy()
            if stats is not None:
                stats['bytes_copied'] += values.nbytes
        elif stats is not None:
            stats['bytes_zero_copy'] += values.nbytes
        columns[field.name] = values
    return columns, meta


class PickleSerializer(object):
    """Whole-object pickle — always correct, copies everything (reference:
    reader_impl/pickle_serializer.py:17-23)."""

    def __init__(self) -> None:
        self.stats = _new_wire_stats()

    def serialize(self, obj: Any) -> List[Frame]:
        """Whole-object pickle into one payload frame."""
        return [_MARKER_PICKLE, pickle.dumps(obj, protocol=5)]

    def deserialize(self, frames: Sequence[Frame]) -> Any:
        """Unpickle the payload frame, counting the copy in ``stats``."""
        blob = _as_bytes(frames[1])
        self.stats['batches'] += 1
        # unpickling re-materializes the whole object graph: count the payload once
        self.stats['bytes_copied'] += len(blob)
        return pickle.loads(blob)


class ArrowIpcSerializer(object):
    """Arrow IPC stream for the numeric columns of a ColumnarBatch (reference:
    reader_impl/arrow_table_serializer.py:18-33).

    Frame layout: ``[b'A', ipc_stream, pickled_sidecar]`` where the IPC stream holds one
    record batch (multi-dim columns flattened to FixedSizeList, original shapes/dtypes in
    schema metadata) and the sidecar holds ``{name: column}`` for non-Arrow-zero-copy
    columns plus ``num_rows``/``item_id``.

    ``writable=True`` (default) copies each numeric column once on receive, yielding
    ordinary writable numpy arrays — same observable behavior as the thread/dummy pools
    (one memcpy per column; still cheaper than pickle, which copies on both ends and
    re-allocates object graphs). ``writable=False`` is the true zero-copy mode: columns
    alias the single incoming IPC frame and are READ-ONLY — and because all numeric
    columns share that frame, retaining any row/column view pins the whole batch's frame
    memory. Use it when the consumer is a device loader that only reads
    (e.g. JaxDataLoader assembling device arrays). The shm-ring transport requires
    ``writable=True``: its slot memory is handed back to the producing worker the
    moment ``deserialize`` returns, so nothing may keep aliasing it."""

    def __init__(self, writable: bool = True) -> None:
        self._writable = writable
        self.stats = _new_wire_stats()

    @property
    def writable(self) -> bool:
        """True when receive copies columns into ordinary writable arrays."""
        return self._writable

    def serialize(self, obj: Any) -> List[Frame]:
        """ColumnarBatch -> ``[marker, ipc_stream, pickled_sidecar]`` frames
        (anything else falls back to whole-object pickle)."""
        from petastorm_tpu.reader_worker import ColumnarBatch
        if not isinstance(obj, ColumnarBatch):
            return PickleSerializer().serialize(obj)
        meta_extra: Dict[str, Any] = {
            'item_id': ([int(part) for part in obj.item_id]
                        if obj.item_id is not None else None),
            # resilience sidecar (docs/robustness.md): plain-JSON fields, so the
            # quarantine ledger and retry counters cross the process boundary
            # without pickling framework types
            'retries': int(getattr(obj, 'retries', 0) or 0),
            'quarantine': (obj.quarantine.as_dict()
                           if getattr(obj, 'quarantine', None) is not None
                           else None),
            # cache-observability sidecar: None = cache bypassed/not applicable
            'cache_hit': getattr(obj, 'cache_hit', None),
            # stage-span telemetry sidecar (docs/observability.md): a JSON-safe
            # {stage: histogram_snapshot} dict the consumer merges into its
            # registry — how worker-process timings reach one global snapshot
            'telemetry': getattr(obj, 'telemetry', None),
            # circuit-breaker sidecar (docs/robustness.md): this process's
            # tripped-breaker states ({name: state_dict}, None when all healthy)
            # merged into Reader.diagnostics['breakers']
            'breakers': getattr(obj, 'breakers', None),
            # flight-recorder sidecar (docs/observability.md "Flight
            # recorder"): this process's drained trace events
            # ({'pid', 'events', 'dropped'}, None while tracing is off) merged
            # into the consumer-side recorder for Reader.dump_trace()
            'trace': getattr(obj, 'trace', None),
            # sample-lineage sidecar (docs/observability.md "Sample
            # lineage"): the producing worker's sampled content fingerprint
            # ({'crc32', 'fields'}, None when this piece was not sampled)
            'lineage': getattr(obj, 'lineage', None),
        }
        ipc_buf, sidecar_blob, _ = encode_columnar(obj.columns, obj.num_rows,
                                                   meta_extra)
        return [_MARKER_ARROW, ipc_buf, sidecar_blob]

    def deserialize(self, frames: Sequence[Frame]) -> Any:
        """Frames -> ColumnarBatch (or the pickled fallback object), updating
        the consumer-side ``stats``."""
        marker = _as_bytes(frames[0])
        if marker == _MARKER_PICKLE:
            self.stats['batches'] += 1
            self.stats['bytes_copied'] += len(_as_memory(frames[1]))
            return pickle.loads(_as_bytes(frames[1]))
        from petastorm_tpu.reader_worker import ColumnarBatch

        columns, meta = decode_columnar(frames[1], frames[2],
                                        writable=self._writable, stats=self.stats)
        item_id = meta['item_id']
        quarantine = meta.get('quarantine')
        if quarantine is not None:
            from petastorm_tpu.resilience import QuarantineRecord
            quarantine = QuarantineRecord(**quarantine)
        return ColumnarBatch(columns, meta['num_rows'],
                             item_id=tuple(item_id) if item_id is not None else None,
                             retries=meta.get('retries', 0), quarantine=quarantine,
                             cache_hit=meta.get('cache_hit'),
                             telemetry=meta.get('telemetry'),
                             breakers=meta.get('breakers'),
                             trace=meta.get('trace'),
                             lineage=meta.get('lineage'))


def _as_bytes(frame: Frame) -> bytes:
    """bytes from a bytes / memoryview / zmq.Frame / pa.Buffer wire frame."""
    if isinstance(frame, bytes):
        return frame
    return bytes(_as_memory(frame))


def _as_memory(frame: Frame) -> memoryview:
    if isinstance(frame, memoryview):
        return frame
    buffer = getattr(frame, 'buffer', None)  # zmq.Frame (copy=False receive)
    if buffer is not None:
        return memoryview(buffer)
    return memoryview(frame)
