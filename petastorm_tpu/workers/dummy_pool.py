"""Single-threaded pool for debugging/profiling: work executes lazily on the caller's
thread inside ``get_results`` (reference: petastorm/workers_pool/dummy_pool.py:20-91)."""

from collections import deque

from petastorm_tpu.telemetry.registry import MetricsRegistry
from petastorm_tpu.workers import EmptyResultError, VentilatedItemProcessedMessage


class DummyPool(object):
    """Zero-parallelism pool: ventilated items are processed synchronously inside
    ``get_results`` on the caller's thread (reference: workers_pool/dummy_pool.py)
    — determinism for tests and debugging."""

    def __init__(self, results_queue_size=None):
        self._ventilator_queue = deque()
        self._results = deque()
        self._worker = None
        self._ventilator = None
        self.workers_count = 1
        #: uniform pool-telemetry surface (docs/observability.md); worker stages
        #: still ride each batch's sidecar — inline execution means there is no
        #: consumer wait worth measuring here
        self.telemetry = MetricsRegistry()

    def start(self, worker_class, worker_args=None, ventilator=None):
        self._worker = worker_class(0, self._results.append, worker_args)
        if ventilator is not None:
            self._ventilator = ventilator
            self._ventilator.start()

    def ventilate(self, **kwargs):
        self._ventilator_queue.append(kwargs)

    def get_results(self, timeout=None):
        while True:
            while self._results:
                result = self._results.popleft()
                if isinstance(result, VentilatedItemProcessedMessage):
                    continue
                return result
            if self._ventilator_queue:
                item = self._ventilator_queue.popleft()
                self._worker.process(**item)
                if self._ventilator is not None:
                    self._ventilator.processed_item()
                continue
            if self._ventilator is not None and getattr(self._ventilator, 'error', None):
                raise self._ventilator.error
            if self._ventilator is None or self._ventilator.completed():
                raise EmptyResultError()
            # Ventilator thread may still be feeding; busy-wait briefly.
            import time
            time.sleep(0.005)

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()
        if self._worker is not None:
            self._worker.shutdown()

    def join(self):
        pass

    @property
    def diagnostics(self):
        return {'output_queue_size': len(self._results)}
