"""Thread worker pool (reference: petastorm/workers_pool/thread_pool.py:37-221).

The TPU-idiomatic default pool: Arrow's Parquet C++ reader releases the GIL, so thread
workers overlap IO + decompression with the consumer; no serialization cost crosses the
worker->consumer boundary (unlike the process pool's IPC).
"""

import logging
import queue
import threading
import time

from petastorm_tpu.telemetry.registry import MetricsRegistry, telemetry_enabled
from petastorm_tpu.workers import (EmptyResultError, TimeoutWaitingForResultError,
                                   VentilatedItemProcessedMessage)

logger = logging.getLogger(__name__)

DEFAULT_RESULTS_QUEUE_SIZE = 50
_STOP_SENTINEL = object()


class _WorkerError(object):
    def __init__(self, exc, tb):
        self.exc = exc
        self.tb = tb


class WorkerThread(threading.Thread):
    def __init__(self, pool, worker):
        super().__init__(daemon=True, name='petastorm-tpu-worker-{}'.format(worker.worker_id))
        self._pool = pool
        self._worker = worker

    def run(self):
        profiler = None
        if self._pool._profiling_enabled:
            import cProfile
            profiler = cProfile.Profile()
        while True:
            # Elastic park point (docs/autotuning.md): a worker whose id is
            # beyond the pool's current active count waits here instead of
            # pulling work, so set_workers_count can shrink the pool without
            # killing threads (and grow it again by just notifying).
            self._pool._await_active(self._worker.worker_id)
            item = self._pool._ventilator_queue.get()
            if item is _STOP_SENTINEL:
                break
            # CPython 3.12's cProfile registers a process-global sys.monitoring tool, so
            # only one profiler may be active at a time: workers contend for the lock
            # per item and whoever holds it profiles that item (a sample of all
            # workers' work rather than the reference's true per-thread profiles,
            # thread_pool.py:41-49 — py3.12 removed that option).
            profiling_this = profiler is not None and \
                self._pool._profiler_slot.acquire(blocking=False)
            if profiling_this:
                try:
                    profiler.enable()
                except ValueError:
                    # another tool (e.g. coverage) owns the global monitoring slot
                    self._pool._profiler_slot.release()
                    profiling_this = False
            try:
                try:
                    self._worker.process(**item)
                finally:
                    if profiling_this:
                        profiler.disable()
                        self._pool._profiler_slot.release()
                self._pool._put_result(VentilatedItemProcessedMessage())
            except Exception as exc:  # noqa: BLE001 - propagate to consumer
                import traceback
                self._pool._put_result(_WorkerError(exc, traceback.format_exc()))
        if profiler is not None:
            self._pool._collect_profile(profiler)
        self._worker.shutdown()


class ThreadPool(object):
    """N worker threads, each owning a worker instance; bounded results queue provides
    backpressure (reference: thread_pool.py)."""

    def __init__(self, workers_count, results_queue_size=DEFAULT_RESULTS_QUEUE_SIZE,
                 profiling_enabled=False, max_workers_count=None):
        """``max_workers_count`` bounds runtime growth via
        :meth:`set_workers_count` (default ``4 * workers_count``) — the elastic
        worker knob the autotuner turns (docs/autotuning.md)."""
        self._workers_count = workers_count
        self._max_workers_count = max(int(max_workers_count or 4 * workers_count),
                                      workers_count)
        self._results_queue = queue.Queue(results_queue_size)
        self._ventilator_queue = queue.Queue()
        self._threads = []
        self._ventilator = None
        self._stopped = threading.Event()
        self.workers_count = workers_count
        # ------------------------------------------------ elastic grow/park
        # _active_workers is the number of worker ids allowed to pull work;
        # threads with a higher id park on _resize_cond (see WorkerThread.run).
        # Worker construction args are kept so growth past the spawned set can
        # start fresh threads mid-epoch.
        self._resize_cond = threading.Condition()
        self._active_workers = workers_count
        self._worker_class = None
        self._worker_args = None
        #: per-worker cProfile, aggregated and logged on join() (reference:
        #: thread_pool.py:41-49,190-198)
        self._profiling_enabled = profiling_enabled
        self._profiles = []
        self._profiles_lock = threading.Lock()
        self._profiler_slot = threading.Lock()
        #: consumer-side telemetry: ``pool_wait`` (time the consumer spent inside
        #: get_results per result) — worker-side stages ride each batch's
        #: telemetry sidecar instead (docs/observability.md)
        self.telemetry = MetricsRegistry()

    def start(self, worker_class, worker_args=None, ventilator=None):
        if self._threads:
            raise RuntimeError('ThreadPool already started')
        self._worker_class = worker_class
        self._worker_args = worker_args
        for worker_id in range(self._workers_count):
            self._spawn_worker_thread(worker_id)
        if ventilator is not None:
            self._ventilator = ventilator
            self._ventilator.start()

    def _spawn_worker_thread(self, worker_id):
        worker = self._worker_class(worker_id, self._put_result, self._worker_args)
        thread = WorkerThread(self, worker)
        self._threads.append(thread)
        thread.start()

    # ------------------------------------------------------- elastic grow/park

    def _await_active(self, worker_id):
        """Park the calling worker thread while its id is beyond the active
        count (and the pool is not stopped) — the shrink half of
        :meth:`set_workers_count`."""
        with self._resize_cond:
            while (worker_id >= self._active_workers
                   and not self._stopped.is_set()):
                self._resize_cond.wait(timeout=0.5)

    def set_workers_count(self, value):
        """Bounded, thread-safe runtime resize of the worker set
        (docs/autotuning.md): growing beyond the threads already spawned starts
        fresh worker threads; shrinking parks the excess threads at their next
        item boundary (an in-progress item always completes — nothing is
        killed). Clamped to ``[1, max_workers_count]``; returns the applied
        value. No-op (returning the current count) after ``stop()``."""
        value = max(1, min(int(value), self._max_workers_count))
        with self._resize_cond:
            if self._stopped.is_set() or self._worker_class is None:
                return self._active_workers
            spawned = len(self._threads)
            for worker_id in range(spawned, value):
                self._spawn_worker_thread(worker_id)
            self._active_workers = value
            self.workers_count = value
            self._resize_cond.notify_all()
        return value

    def ventilate(self, *args, **kwargs):
        """Enqueue one work item (kwargs form is the worker.process signature)."""
        if args:
            raise TypeError('ventilate accepts keyword arguments only')
        self._ventilator_queue.put(kwargs)

    def _put_result(self, result):
        """Stop-aware bounded put: never deadlocks a worker against a stopped consumer
        (reference: thread_pool.py:200-214)."""
        while not self._stopped.is_set():
            try:
                self._results_queue.put(result, timeout=0.1)
                return
            except queue.Full:
                continue

    def get_results(self, timeout=None):
        """Next result payload; raises EmptyResultError when all ventilated work finished
        and the queue drained; re-raises worker exceptions (reference:
        thread_pool.py:139-172)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        wait_start = time.perf_counter()
        while True:
            try:
                result = self._results_queue.get_nowait()
            except queue.Empty:
                if self._ventilator is not None and getattr(self._ventilator, 'error', None):
                    self.stop()
                    raise self._ventilator.error
                if self._ventilator is not None and self._ventilator.completed():
                    raise EmptyResultError()
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutWaitingForResultError()
                try:
                    result = self._results_queue.get(timeout=0.1)
                except queue.Empty:
                    continue
            if isinstance(result, VentilatedItemProcessedMessage):
                if self._ventilator is not None:
                    self._ventilator.processed_item()
                continue
            if isinstance(result, _WorkerError):
                self.stop()
                logger.error('Worker failure re-raised in consumer:\n%s', result.tb)
                raise result.exc
            if telemetry_enabled():
                self.telemetry.observe('pool_wait',
                                       time.perf_counter() - wait_start)
            return result

    def stop(self):
        self._stopped.set()
        with self._resize_cond:
            # wake parked (shrunk-away) workers so they can take their sentinel
            self._resize_cond.notify_all()
        if self._ventilator is not None:
            self._ventilator.stop()
        for _ in self._threads:
            self._ventilator_queue.put(_STOP_SENTINEL)

    def _collect_profile(self, profiler):
        with self._profiles_lock:
            self._profiles.append(profiler)

    def join(self):
        if not self._stopped.is_set():
            raise RuntimeError('join() must be preceded by stop()')
        stragglers = []
        for thread in self._threads:
            thread.join(timeout=30)
            if thread.is_alive():
                stragglers.append(thread.name)
        self._threads = []
        if stragglers and self._profiling_enabled:
            logger.warning('Worker thread(s) %s still alive after join timeout; their '
                           'profile data is not included in the aggregate', stragglers)
        if self._profiling_enabled and self._profiles:
            import io
            import pstats
            stream = io.StringIO()
            stats = None
            with self._profiles_lock:
                for profiler in self._profiles:
                    try:
                        profiler.create_stats()
                    except Exception:  # noqa: BLE001 - never profiled anything
                        continue
                    if not getattr(profiler, 'stats', None):
                        continue  # worker never won the (py3.12-global) profiler slot
                    if stats is None:
                        stats = pstats.Stats(profiler, stream=stream)
                    else:
                        stats.add(profiler)
                self._profiles = []
            if stats is not None:
                stats.sort_stats('cumulative').print_stats(30)
                logger.info('Aggregated worker-thread profile:\n%s', stream.getvalue())

    @property
    def diagnostics(self):
        return {'output_queue_size': self._results_queue.qsize()}
