"""Shared-memory slot ring: the zero-copy transport under the process pool.

The pool (consumer side) owns ONE ``multiprocessing.shared_memory`` segment divided
into fixed-size slots, statically partitioned among the worker slots: worker ``w`` of
``n`` owns slots ``[w * k, (w + 1) * k)`` for ``k = slots_per_worker``. A worker
serializes each result (Arrow IPC stream + pickled sidecar — the same frames the ZMQ
wire carries) into one of ITS free slots and ships only a ~100-byte JSON descriptor
``{w, g, s, lens}`` over the existing results channel; the consumer maps the slot
zero-copy (``memoryview`` slices handed to the payload serializer, which reads them
through ``pa.BufferReader`` / ``to_numpy(zero_copy_only=True)``) and acks the slot back
to the producing worker with a ``release`` message on the dispatch ROUTER.

Correctness properties this layout buys:

- **Backpressure**: a worker with no free slot blocks (polling for release acks)
  before falling back to the ZMQ frames — the bounded slot count is the transport's
  flow control, mirroring the results queue HWM.
- **Leak-proof reclamation**: the segment has exactly one owner (the pool). Workers
  attach without registering with their resource tracker, so a SIGKILL-ed worker
  cannot unlink the segment behind the pool's back, and ``ProcessPool.join()`` always
  closes AND unlinks it — no ``/dev/shm`` residue regardless of worker deaths.
- **Respawn safety**: descriptors carry the producing worker's generation. After a
  respawn the pool bumps the slot generation, so a stale descriptor (written by the
  dead worker, still sitting in the results buffer) is dropped instead of read while
  the replacement worker may already be overwriting the slot; the replacement starts
  with its whole slot range free.
- **End-to-end integrity**: every descriptor carries a CRC-32 of the payload bytes
  (computed over the SOURCE frames while copying into the slot), verified by the
  pool before deserializing — a torn slot write or bit flip the generation stamp
  cannot see is detected instead of flowing into training arrays
  (docs/robustness.md).
- **Liveness**: the segment is prefixed with one 8-byte heartbeat word per worker
  slot; each worker's heartbeat thread stamps a monotone counter there, and the
  pool's watchdog reads it without any message traffic — a hung-but-alive worker
  (stalled heartbeat while holding assigned items) is reaped through the bounded
  respawn path.

Static partitioning (vs a shared free list) is what makes worker death trivial to
reason about: no cross-process allocator state can be corrupted mid-crash.
"""

from __future__ import annotations

import json
import logging
import secrets
import struct
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from petastorm_tpu.workers.integrity import corrupt_for_test

logger = logging.getLogger(__name__)

#: default payload capacity of one slot; a decoded rowgroup batch beyond this falls
#: back to the ZMQ frames (see the fallback matrix in docs/performance.md)
DEFAULT_SLOT_BYTES: int = 32 << 20
#: default slots owned by each worker — the transport's in-flight bound per worker
DEFAULT_SLOTS_PER_WORKER: int = 4
#: bytes reserved per worker at the head of the segment for its heartbeat word
#: (a cache line, so concurrent stamps by different workers never share one)
HEARTBEAT_BYTES: int = 64
_HEARTBEAT_WORD = struct.Struct('<q')


def _shared_memory_module():  # type: ignore[no-untyped-def]
    """Import hook kept separate so environments without ``multiprocessing.
    shared_memory`` (or with it disabled) degrade to the ZMQ wire, never crash."""
    from multiprocessing import shared_memory
    return shared_memory


class ShmSlotDescriptor:
    """Parsed wire descriptor of one shm-resident payload: producing worker slot,
    its generation, the ring slot index, the byte length of each serialized
    frame laid out back-to-back in the slot, and the CRC-32 of the payload
    (``None`` only for descriptors from a pre-integrity writer)."""

    __slots__ = ('worker_slot', 'generation', 'ring_slot', 'frame_lengths', 'crc')

    def __init__(self, worker_slot: int, generation: int, ring_slot: int,
                 frame_lengths: Sequence[int], crc: Optional[int] = None) -> None:
        self.worker_slot = worker_slot
        self.generation = generation
        self.ring_slot = ring_slot
        self.frame_lengths = list(frame_lengths)
        self.crc = crc

    @property
    def total_bytes(self) -> int:
        return sum(self.frame_lengths)

    def to_bytes(self) -> bytes:
        spec = {'w': self.worker_slot, 'g': self.generation,
                's': self.ring_slot, 'lens': self.frame_lengths}
        if self.crc is not None:
            spec['crc'] = self.crc
        return json.dumps(spec).encode('utf-8')

    @classmethod
    def from_bytes(cls, blob: bytes) -> 'ShmSlotDescriptor':
        spec = json.loads(bytes(blob).decode('utf-8'))
        crc = spec.get('crc')
        return cls(int(spec['w']), int(spec['g']), int(spec['s']),
                   [int(n) for n in spec['lens']],
                   crc=int(crc) if crc is not None else None)


class ShmRing:
    """Consumer-side owner of the shared-memory segment (create + unlink)."""

    def __init__(self, workers_count: int,
                 slots_per_worker: int = DEFAULT_SLOTS_PER_WORKER,
                 slot_bytes: int = DEFAULT_SLOT_BYTES) -> None:
        if workers_count < 1 or slots_per_worker < 1 or slot_bytes < 1024:
            raise ValueError('ShmRing needs >=1 worker, >=1 slot/worker and '
                             '>=1KiB slots')
        shared_memory = _shared_memory_module()
        self.workers_count = workers_count
        self.slots_per_worker = slots_per_worker
        self.slot_bytes = slot_bytes
        #: payload slots start after the per-worker heartbeat words
        self.data_offset = workers_count * HEARTBEAT_BYTES
        total = self.data_offset + workers_count * slots_per_worker * slot_bytes
        # Explicit name (not the psm_ default): tests and operators can find (and
        # assert the absence of) our segments in /dev/shm by prefix.
        self.name = 'ptpu-ring-' + secrets.token_hex(8)
        self._shm = shared_memory.SharedMemory(name=self.name, create=True,
                                               size=total)
        self._closed = False

    def heartbeat(self, worker_slot: int) -> int:
        """Current heartbeat counter stamped by worker ``worker_slot`` (0 until
        its first stamp). The pool's watchdog polls this — change detection is
        consumer-side, so no cross-process clock comparison is needed."""
        value: int = _HEARTBEAT_WORD.unpack_from(
            self._shm.buf, worker_slot * HEARTBEAT_BYTES)[0]
        return value

    def view(self, descriptor: ShmSlotDescriptor) -> List[memoryview]:
        """Zero-copy memoryviews over the descriptor's frames, in frame order."""
        if descriptor.ring_slot >= self.workers_count * self.slots_per_worker:
            raise ValueError('descriptor names slot {} outside the ring'
                             .format(descriptor.ring_slot))
        if descriptor.total_bytes > self.slot_bytes:
            raise ValueError('descriptor claims {} bytes > slot size {}'
                             .format(descriptor.total_bytes, self.slot_bytes))
        base = self.data_offset + descriptor.ring_slot * self.slot_bytes
        views: List[memoryview] = []
        offset = base
        for length in descriptor.frame_lengths:
            views.append(self._shm.buf[offset:offset + length])
            offset += length
        return views

    def close_and_unlink(self) -> None:
        """Release the mapping and remove the segment from /dev/shm (idempotent)."""
        if self._closed:
            return
        self._closed = True
        try:
            self._shm.close()
        finally:
            try:
                self._shm.unlink()
            except FileNotFoundError:  # already gone (double-unlink race)
                pass

    def worker_spec(self) -> Dict[str, int]:
        """The bootstrap fields a worker needs to attach its writer."""
        return {'slots_per_worker': self.slots_per_worker,
                'slot_bytes': self.slot_bytes,
                'data_offset': self.data_offset}


class ShmRingWriter:
    """Worker-side attachment: writes serialized frames into this worker's slot
    range and tracks which of its slots are awaiting a release ack."""

    def __init__(self, name: str, worker_slot: int, generation: int,
                 slots_per_worker: int, slot_bytes: int,
                 data_offset: int = 0, checksum: bool = True) -> None:
        shared_memory = _shared_memory_module()
        self.worker_slot = worker_slot
        self.generation = generation
        self.slot_bytes = slot_bytes
        self._data_offset = data_offset
        #: False skips the producer-side CRC entirely (descriptors carry
        #: crc=None and the pool skips verification) — the benchmark baseline;
        #: production keeps it on
        self.checksum = checksum
        self._first_slot = worker_slot * slots_per_worker
        self._slots_per_worker = slots_per_worker
        self._free = list(range(self._first_slot,
                                self._first_slot + slots_per_worker))
        try:
            self._shm = shared_memory.SharedMemory(name=name, track=False)  # type: ignore[call-arg]
        except TypeError:
            # Python < 3.13: attaching registers with THIS process's resource
            # tracker, which would unlink the pool's segment when the worker
            # exits. Undo the registration — the pool is the sole owner.
            self._shm = shared_memory.SharedMemory(name=name)
            try:
                from multiprocessing import resource_tracker
                resource_tracker.unregister(self._shm._name, 'shared_memory')  # type: ignore[attr-defined]
            except Exception:  # pragma: no cover - tracker internals shifted
                logger.warning('could not unregister shm segment from the '
                               'resource tracker; pool-side unlink still wins',
                               exc_info=True)

    @property
    def free_slots(self) -> int:
        return len(self._free)

    def fits(self, frames: Sequence[bytes]) -> bool:
        return sum(len(memoryview(f)) for f in frames) <= self.slot_bytes

    def stamp_heartbeat(self, value: int) -> None:
        """Write this worker's liveness counter into its heartbeat word (called
        by the worker's heartbeat thread; an aligned 8-byte store, so the pool
        never observes a torn value)."""
        _HEARTBEAT_WORD.pack_into(self._shm.buf,
                                  self.worker_slot * HEARTBEAT_BYTES, value)

    def try_write(self, frames: Sequence[bytes]) -> Optional[ShmSlotDescriptor]:
        """Copy ``frames`` back-to-back into a free slot; None when no slot is
        free or the payload exceeds the slot size (caller falls back to ZMQ).
        The returned descriptor carries the CRC-32 of the SOURCE frames — the
        consumer recomputes it over the slot, so any divergence between what
        was serialized and what gets mapped (torn write, bit flip, stale
        overwrite) is caught before deserialization."""
        if not self._free or not self.fits(frames):
            return None
        ring_slot = self._free.pop()
        base = self._data_offset + ring_slot * self.slot_bytes
        offset = base
        lengths: List[int] = []
        crc: Optional[int] = 0 if self.checksum else None
        for frame in frames:
            view = memoryview(frame).cast('B')
            self._shm.buf[offset:offset + view.nbytes] = view
            if crc is not None:
                crc = zlib.crc32(view, crc) & 0xFFFFFFFF
            offset += view.nbytes
            lengths.append(view.nbytes)
        corrupt_for_test(self._shm.buf, base, offset - base)
        return ShmSlotDescriptor(self.worker_slot, self.generation, ring_slot,
                                 lengths, crc=crc)

    def release(self, ring_slot: int) -> None:
        """Consumer ack arrived: the slot may be reused. Acks outside this
        writer's static partition (stale routing after a respawn) are ignored."""
        if not (self._first_slot <= ring_slot
                < self._first_slot + self._slots_per_worker):
            return
        if ring_slot not in self._free:
            self._free.append(ring_slot)

    def slot_range(self) -> Tuple[int, int]:
        """(first_slot, slots_per_worker) of this writer's static partition."""
        return self._first_slot, self._slots_per_worker

    def close(self) -> None:
        """Detach the mapping (the pool owns the unlink)."""
        try:
            self._shm.close()
        except Exception:  # pragma: no cover - already closed
            pass
