"""Worker-pool runtime: ventilation, pools, and the worker protocol (reference:
petastorm/workers_pool/)."""


class EmptyResultError(Exception):
    """Raised by a pool's ``get_results`` when all ventilated work completed and no more
    results will arrive (reference: petastorm/workers_pool/__init__.py)."""


class TimeoutWaitingForResultError(Exception):
    """Raised when waiting on results times out."""


class VentilatedItemProcessedMessage(object):
    """Control message a worker publishes after fully processing one ventilated item —
    drives the ventilator's bounded in-flight accounting (reference:
    petastorm/workers_pool/__init__.py)."""
