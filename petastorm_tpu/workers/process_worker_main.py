"""Entry point executed inside each spawned pool worker process (reference:
petastorm/workers_pool/process_pool.py:330-413 _worker_bootstrap +
exec_in_new_process.py/_entrypoint.py).

Dispatch is pull-based (see process_pool.py module docstring): the worker announces
itself idle with a 'ready' on its DEALER socket and receives exactly the items the pool
assigned to it; every result and the final 'done' ack carry the item's dispatch token so
the pool can re-ventilate un-acked items if this process dies and drop duplicate results
after a respawn. Dispatch messages are kind-prefixed: ``work`` carries an item,
``release`` acks a shared-memory slot back into this worker's free set.

With the shm transport (bootstrap ``shm`` spec), each serialized result is written
into one of this worker's ring slots and only the descriptor is sent
(``result_shm``). No free slot = backpressure: the worker polls its dispatch socket
for release acks up to a bounded wait, then falls back to plain ZMQ ``result``
frames — results are never lost to slot exhaustion. ``work`` messages may carry a
transport flag (``b'0'`` = the pool's shm circuit breaker is open: publish this
item's result over plain ZMQ frames — the temporary wire fallback after repeated
checksum failures, docs/robustness.md).

A daemon **heartbeat thread** stamps a monotone counter every
``heartbeat_interval_s`` — into this worker's shm heartbeat word when the ring is
attached, else as tiny ``heartbeat`` messages on a private PUSH socket to the
results channel. The pool's watchdog reads the stamps to tell "hung" from "slow":
a worker wedged process-wide (native deadlock holding the GIL, SIGSTOP) stops
stamping and is reaped; a worker merely blocked in a GIL-releasing call keeps
stamping and is instead bounded by the pool's per-item deadline."""

import os
import pickle
import sys
import threading
import time
import traceback

#: bounded wait for a slot release before a payload falls back to ZMQ frames; the
#: consumer releases every slot it reads, so a healthy pool frees one well within
#: this window — the timeout only fires when the consumer stalls or dies
_SLOT_WAIT_S = 10.0


def _watch_parent(parent_pid):
    """Exit if the main process dies, so no orphan workers linger (reference:
    process_pool.py:320-327)."""
    import psutil
    while True:
        if not psutil.pid_exists(parent_pid):
            os._exit(0)
        time.sleep(1)


def _heartbeat_loop(stop_event, ring_writer, context, results_addr, worker_id,
                    generation, interval_s):
    """Stamp liveness every ``interval_s`` until ``stop_event`` is set: the shm
    heartbeat word when the ring is attached (no traffic, works even when the
    results channel is saturated), else non-blocking ``heartbeat`` messages on a
    PRIVATE push socket (ZMQ sockets are not thread-safe — the main thread owns
    the results socket). Dropped sends (HWM) are fine: the watchdog only needs
    *some* stamp to land within its (much longer) staleness window."""
    import zmq
    socket = None
    if ring_writer is None:
        socket = context.socket(zmq.PUSH)
        socket.setsockopt(zmq.SNDHWM, 8)
        socket.setsockopt(zmq.LINGER, 0)
        socket.connect(results_addr)
    seq = 0
    try:
        while not stop_event.wait(interval_s):
            seq += 1
            try:
                if ring_writer is not None:
                    ring_writer.stamp_heartbeat(seq)
                elif socket is not None:
                    socket.send_multipart(
                        [b'heartbeat', b'%d' % worker_id, b'%d' % generation,
                         b'%d' % seq], zmq.NOBLOCK)
            except Exception:  # noqa: BLE001 - liveness must never kill a worker
                pass
    finally:
        if socket is not None:
            socket.close(linger=0)


def main(bootstrap_path):
    """Spawned worker-process entry: load the dill bootstrap file, connect the ZMQ
    sockets, attach the shm ring writer when configured, and request/process
    ventilated items until the stop message."""
    with open(bootstrap_path, 'rb') as f:
        bootstrap = pickle.load(f)
    try:
        os.unlink(bootstrap_path)
    except OSError:
        pass

    import dill
    import zmq

    worker_class = dill.loads(bootstrap['worker_class'])
    worker_args = dill.loads(bootstrap['worker_args'])
    serializer = dill.loads(bootstrap['serializer'])
    worker_id = bootstrap['worker_id']
    generation = bootstrap.get('generation', 0)

    threading.Thread(target=_watch_parent, args=(bootstrap['parent_pid'],),
                     daemon=True).start()

    context = zmq.Context()
    dispatch_socket = context.socket(zmq.DEALER)
    control_socket = context.socket(zmq.SUB)
    results_socket = context.socket(zmq.PUSH)
    ring_writer = None
    heartbeat_thread = None
    heartbeat_stop = threading.Event()
    heartbeat_interval_s = bootstrap.get('heartbeat_interval_s', 0.5)
    # Everything below runs under one try/finally: an uncaught error in
    # setup or the work loop must still close the sockets and terminate
    # the context, or the interpreter hangs in zmq teardown and the pool
    # reaps this worker by timeout instead of by exit code.
    try:
        dispatch_socket.connect(bootstrap['dispatch_addr'])
        control_socket.connect(bootstrap['control_addr'])
        control_socket.setsockopt(zmq.SUBSCRIBE, b'')
        results_socket.connect(bootstrap['results_addr'])
        shm_spec = bootstrap.get('shm')
        if shm_spec is not None:
            from petastorm_tpu.workers.shm_ring import ShmRingWriter
            try:
                ring_writer = ShmRingWriter(shm_spec['name'], worker_id, generation,
                                            shm_spec['slots_per_worker'],
                                            shm_spec['slot_bytes'],
                                            data_offset=shm_spec.get('data_offset', 0),
                                            checksum=shm_spec.get('checksum', True))
            except Exception:  # noqa: BLE001 - transport optional; ZMQ still works
                import logging
                logging.getLogger(__name__).warning(
                    'worker %d could not attach the shm ring; using ZMQ frames',
                    worker_id, exc_info=True)

        if heartbeat_interval_s and heartbeat_interval_s > 0:
            heartbeat_thread = threading.Thread(
                target=_heartbeat_loop,
                args=(heartbeat_stop, ring_writer, context,
                      bootstrap['results_addr'], worker_id, generation,
                      heartbeat_interval_s),
                daemon=True)
            heartbeat_thread.start()

        current_token = [b'']
        # b'0' when the pool's shm breaker routed this item to the ZMQ wire
        current_shm_allowed = [True]

        def drain_releases(timeout_ms=0):
            """Process queued ``release`` acks on the dispatch socket; returns any
            out-of-band ``work`` frames that arrived interleaved (deferred by the
            caller, never dropped)."""
            deferred = []
            while dispatch_socket.poll(timeout_ms, zmq.POLLIN):
                timeout_ms = 0
                frames = dispatch_socket.recv_multipart()
                if frames and frames[0] == b'release' and ring_writer is not None:
                    ring_writer.release(int(frames[1]))
                else:
                    deferred.append(frames)
            return deferred

        deferred_work = []

        def publish(result):
            # Stage spans land in the process-local recorder and ride the NEXT
            # published batch's telemetry sidecar (this one is already serialized) —
            # one item late, same process total (docs/observability.md).
            from petastorm_tpu.telemetry.spans import stage_span
            with stage_span('serialize'):
                frames = serializer.serialize(result)
            if ring_writer is not None and current_shm_allowed[0] \
                    and ring_writer.fits(frames):
                descriptor = ring_writer.try_write(frames)
                if descriptor is None:
                    # Backpressure: all our slots are in flight — wait (bounded) for
                    # the consumer's release acks before falling back to the wire.
                    deadline = time.monotonic() + _SLOT_WAIT_S
                    with stage_span('shm_slot_wait'):
                        while descriptor is None and time.monotonic() < deadline:
                            deferred_work.extend(drain_releases(timeout_ms=100))
                            descriptor = ring_writer.try_write(frames)
                if descriptor is not None:
                    results_socket.send_multipart(
                        [b'result_shm', current_token[0], descriptor.to_bytes()])
                    return
            results_socket.send_multipart([b'result', current_token[0]] + frames)

        worker = worker_class(worker_id, publish, worker_args)
        results_socket.send_multipart([b'started'])

        poller = zmq.Poller()
        poller.register(dispatch_socket, zmq.POLLIN)
        poller.register(control_socket, zmq.POLLIN)
        ready_msg = [b'ready', b'%d' % worker_id, b'%d' % generation]
        dispatch_socket.send_multipart(ready_msg)
        while True:
            events = dict(poller.poll(1000))
            if control_socket in events:
                if control_socket.recv() == b'stop':
                    break
            if dispatch_socket in events or deferred_work:
                if deferred_work:
                    frames = deferred_work.pop(0)
                else:
                    frames = dispatch_socket.recv_multipart()
                kind = frames[0]
                if kind == b'release':
                    if ring_writer is not None:
                        ring_writer.release(int(frames[1]))
                    continue
                if kind != b'work':
                    continue  # unknown kind from a newer pool: ignore
                token, blob = frames[1], frames[2]
                kwargs = dill.loads(blob)
                current_token[0] = token
                # optional 4th frame: shm transport flag (b'0' while the pool's shm
                # circuit breaker is open — docs/robustness.md); optional 5th: the
                # dispatch attempt number, echoed in 'done' so the pool can tell a
                # current ack from one flushed by a since-reaped worker
                current_shm_allowed[0] = len(frames) < 4 or frames[3] != b'0'
                attempt = frames[4] if len(frames) >= 5 else b'0'
                # Causal trace context, attempt leg (docs/observability.md "Flight
                # recorder"): the dispatch attempt rides the existing work frames;
                # installing it here lets the worker tag every span with the exact
                # delivery attempt — no new wire protocol needed.
                from petastorm_tpu.telemetry.tracing import set_dispatch_attempt
                set_dispatch_attempt(int(attempt))
                try:
                    worker.process(**kwargs)
                    results_socket.send_multipart([b'done', token, attempt])
                except Exception as exc:  # noqa: BLE001 - ship to consumer
                    blob = pickle.dumps((exc, traceback.format_exc()))
                    results_socket.send_multipart([b'error', token, blob])
                current_token[0] = b''
                current_shm_allowed[0] = True
                dispatch_socket.send_multipart(ready_msg)
        worker.shutdown()
    finally:
        # Stop the heartbeat thread BEFORE terminating the context: its
        # private push socket must close, or context.term() blocks forever.
        heartbeat_stop.set()
        if heartbeat_thread is not None:
            heartbeat_thread.join(timeout=2 * heartbeat_interval_s + 1)
        if ring_writer is not None:
            ring_writer.close()
        for sock in (dispatch_socket, control_socket, results_socket):
            sock.close(linger=1000)
        context.term()


if __name__ == '__main__':
    main(sys.argv[1])
