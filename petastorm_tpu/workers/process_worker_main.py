"""Entry point executed inside each spawned pool worker process (reference:
petastorm/workers_pool/process_pool.py:330-413 _worker_bootstrap +
exec_in_new_process.py/_entrypoint.py)."""

import os
import pickle
import sys
import threading
import time
import traceback


def _watch_parent(parent_pid):
    """Exit if the main process dies, so no orphan workers linger (reference:
    process_pool.py:320-327)."""
    import psutil
    while True:
        if not psutil.pid_exists(parent_pid):
            os._exit(0)
        time.sleep(1)


def main(bootstrap_path):
    """Spawned worker-process entry: load the dill bootstrap file, connect the ZMQ
    sockets, loop ventilated items until the stop message."""
    with open(bootstrap_path, 'rb') as f:
        bootstrap = pickle.load(f)
    try:
        os.unlink(bootstrap_path)
    except OSError:
        pass

    import dill
    import zmq

    worker_class = dill.loads(bootstrap['worker_class'])
    worker_args = dill.loads(bootstrap['worker_args'])
    serializer = dill.loads(bootstrap['serializer'])
    worker_id = bootstrap['worker_id']

    threading.Thread(target=_watch_parent, args=(bootstrap['parent_pid'],),
                     daemon=True).start()

    context = zmq.Context()
    vent_socket = context.socket(zmq.PULL)
    vent_socket.connect(bootstrap['vent_addr'])
    control_socket = context.socket(zmq.SUB)
    control_socket.connect(bootstrap['control_addr'])
    control_socket.setsockopt(zmq.SUBSCRIBE, b'')
    results_socket = context.socket(zmq.PUSH)
    results_socket.connect(bootstrap['results_addr'])

    def publish(result):
        results_socket.send_multipart([b'result'] + serializer.serialize(result))

    worker = worker_class(worker_id, publish, worker_args)
    results_socket.send_multipart([b'started'])

    poller = zmq.Poller()
    poller.register(vent_socket, zmq.POLLIN)
    poller.register(control_socket, zmq.POLLIN)
    while True:
        events = dict(poller.poll(1000))
        if control_socket in events:
            if control_socket.recv() == b'stop':
                break
        if vent_socket in events:
            kwargs = dill.loads(vent_socket.recv())
            try:
                worker.process(**kwargs)
                results_socket.send_multipart([b'done'])
            except Exception as exc:  # noqa: BLE001 - ship to consumer
                blob = pickle.dumps((exc, traceback.format_exc()))
                results_socket.send_multipart([b'error', blob])
    worker.shutdown()
    for sock in (vent_socket, control_socket, results_socket):
        sock.close(linger=1000)
    context.term()


if __name__ == '__main__':
    main(sys.argv[1])
