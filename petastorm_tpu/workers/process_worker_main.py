"""Entry point executed inside each spawned pool worker process (reference:
petastorm/workers_pool/process_pool.py:330-413 _worker_bootstrap +
exec_in_new_process.py/_entrypoint.py).

Dispatch is pull-based (see process_pool.py module docstring): the worker announces
itself idle with a 'ready' on its DEALER socket and receives exactly the items the pool
assigned to it; every result and the final 'done' ack carry the item's dispatch token so
the pool can re-ventilate un-acked items if this process dies and drop duplicate results
after a respawn."""

import os
import pickle
import sys
import threading
import time
import traceback


def _watch_parent(parent_pid):
    """Exit if the main process dies, so no orphan workers linger (reference:
    process_pool.py:320-327)."""
    import psutil
    while True:
        if not psutil.pid_exists(parent_pid):
            os._exit(0)
        time.sleep(1)


def main(bootstrap_path):
    """Spawned worker-process entry: load the dill bootstrap file, connect the ZMQ
    sockets, request/process ventilated items until the stop message."""
    with open(bootstrap_path, 'rb') as f:
        bootstrap = pickle.load(f)
    try:
        os.unlink(bootstrap_path)
    except OSError:
        pass

    import dill
    import zmq

    worker_class = dill.loads(bootstrap['worker_class'])
    worker_args = dill.loads(bootstrap['worker_args'])
    serializer = dill.loads(bootstrap['serializer'])
    worker_id = bootstrap['worker_id']
    generation = bootstrap.get('generation', 0)

    threading.Thread(target=_watch_parent, args=(bootstrap['parent_pid'],),
                     daemon=True).start()

    context = zmq.Context()
    dispatch_socket = context.socket(zmq.DEALER)
    dispatch_socket.connect(bootstrap['dispatch_addr'])
    control_socket = context.socket(zmq.SUB)
    control_socket.connect(bootstrap['control_addr'])
    control_socket.setsockopt(zmq.SUBSCRIBE, b'')
    results_socket = context.socket(zmq.PUSH)
    results_socket.connect(bootstrap['results_addr'])

    current_token = [b'']

    def publish(result):
        results_socket.send_multipart(
            [b'result', current_token[0]] + serializer.serialize(result))

    worker = worker_class(worker_id, publish, worker_args)
    results_socket.send_multipart([b'started'])

    poller = zmq.Poller()
    poller.register(dispatch_socket, zmq.POLLIN)
    poller.register(control_socket, zmq.POLLIN)
    ready_msg = [b'ready', b'%d' % worker_id, b'%d' % generation]
    dispatch_socket.send_multipart(ready_msg)
    while True:
        events = dict(poller.poll(1000))
        if control_socket in events:
            if control_socket.recv() == b'stop':
                break
        if dispatch_socket in events:
            token, blob = dispatch_socket.recv_multipart()
            kwargs = dill.loads(blob)
            current_token[0] = token
            try:
                worker.process(**kwargs)
                results_socket.send_multipart([b'done', token])
            except Exception as exc:  # noqa: BLE001 - ship to consumer
                blob = pickle.dumps((exc, traceback.format_exc()))
                results_socket.send_multipart([b'error', token, blob])
            current_token[0] = b''
            dispatch_socket.send_multipart(ready_msg)
    worker.shutdown()
    for sock in (dispatch_socket, control_socket, results_socket):
        sock.close(linger=1000)
    context.term()


if __name__ == '__main__':
    main(sys.argv[1])
