"""Entry point executed inside each spawned pool worker process (reference:
petastorm/workers_pool/process_pool.py:330-413 _worker_bootstrap +
exec_in_new_process.py/_entrypoint.py).

Dispatch is pull-based (see process_pool.py module docstring): the worker announces
itself idle with a 'ready' on its DEALER socket and receives exactly the items the pool
assigned to it; every result and the final 'done' ack carry the item's dispatch token so
the pool can re-ventilate un-acked items if this process dies and drop duplicate results
after a respawn. Dispatch messages are kind-prefixed: ``work`` carries an item,
``release`` acks a shared-memory slot back into this worker's free set.

With the shm transport (bootstrap ``shm`` spec), each serialized result is written
into one of this worker's ring slots and only the descriptor is sent
(``result_shm``). No free slot = backpressure: the worker polls its dispatch socket
for release acks up to a bounded wait, then falls back to plain ZMQ ``result``
frames — results are never lost to slot exhaustion."""

import os
import pickle
import sys
import threading
import time
import traceback

#: bounded wait for a slot release before a payload falls back to ZMQ frames; the
#: consumer releases every slot it reads, so a healthy pool frees one well within
#: this window — the timeout only fires when the consumer stalls or dies
_SLOT_WAIT_S = 10.0


def _watch_parent(parent_pid):
    """Exit if the main process dies, so no orphan workers linger (reference:
    process_pool.py:320-327)."""
    import psutil
    while True:
        if not psutil.pid_exists(parent_pid):
            os._exit(0)
        time.sleep(1)


def main(bootstrap_path):
    """Spawned worker-process entry: load the dill bootstrap file, connect the ZMQ
    sockets, attach the shm ring writer when configured, and request/process
    ventilated items until the stop message."""
    with open(bootstrap_path, 'rb') as f:
        bootstrap = pickle.load(f)
    try:
        os.unlink(bootstrap_path)
    except OSError:
        pass

    import dill
    import zmq

    worker_class = dill.loads(bootstrap['worker_class'])
    worker_args = dill.loads(bootstrap['worker_args'])
    serializer = dill.loads(bootstrap['serializer'])
    worker_id = bootstrap['worker_id']
    generation = bootstrap.get('generation', 0)

    threading.Thread(target=_watch_parent, args=(bootstrap['parent_pid'],),
                     daemon=True).start()

    context = zmq.Context()
    dispatch_socket = context.socket(zmq.DEALER)
    dispatch_socket.connect(bootstrap['dispatch_addr'])
    control_socket = context.socket(zmq.SUB)
    control_socket.connect(bootstrap['control_addr'])
    control_socket.setsockopt(zmq.SUBSCRIBE, b'')
    results_socket = context.socket(zmq.PUSH)
    results_socket.connect(bootstrap['results_addr'])

    ring_writer = None
    shm_spec = bootstrap.get('shm')
    if shm_spec is not None:
        from petastorm_tpu.workers.shm_ring import ShmRingWriter
        try:
            ring_writer = ShmRingWriter(shm_spec['name'], worker_id, generation,
                                        shm_spec['slots_per_worker'],
                                        shm_spec['slot_bytes'])
        except Exception:  # noqa: BLE001 - transport optional; ZMQ still works
            import logging
            logging.getLogger(__name__).warning(
                'worker %d could not attach the shm ring; using ZMQ frames',
                worker_id, exc_info=True)

    current_token = [b'']

    def drain_releases(timeout_ms=0):
        """Process queued ``release`` acks on the dispatch socket; returns any
        out-of-band ``work`` frames that arrived interleaved (deferred by the
        caller, never dropped)."""
        deferred = []
        while dispatch_socket.poll(timeout_ms, zmq.POLLIN):
            timeout_ms = 0
            frames = dispatch_socket.recv_multipart()
            if frames and frames[0] == b'release' and ring_writer is not None:
                ring_writer.release(int(frames[1]))
            else:
                deferred.append(frames)
        return deferred

    deferred_work = []

    def publish(result):
        # Stage spans land in the process-local recorder and ride the NEXT
        # published batch's telemetry sidecar (this one is already serialized) —
        # one item late, same process total (docs/observability.md).
        from petastorm_tpu.telemetry.spans import stage_span
        with stage_span('serialize'):
            frames = serializer.serialize(result)
        if ring_writer is not None and ring_writer.fits(frames):
            descriptor = ring_writer.try_write(frames)
            if descriptor is None:
                # Backpressure: all our slots are in flight — wait (bounded) for
                # the consumer's release acks before falling back to the wire.
                deadline = time.monotonic() + _SLOT_WAIT_S
                with stage_span('shm_slot_wait'):
                    while descriptor is None and time.monotonic() < deadline:
                        deferred_work.extend(drain_releases(timeout_ms=100))
                        descriptor = ring_writer.try_write(frames)
            if descriptor is not None:
                results_socket.send_multipart(
                    [b'result_shm', current_token[0], descriptor.to_bytes()])
                return
        results_socket.send_multipart([b'result', current_token[0]] + frames)

    worker = worker_class(worker_id, publish, worker_args)
    results_socket.send_multipart([b'started'])

    poller = zmq.Poller()
    poller.register(dispatch_socket, zmq.POLLIN)
    poller.register(control_socket, zmq.POLLIN)
    ready_msg = [b'ready', b'%d' % worker_id, b'%d' % generation]
    dispatch_socket.send_multipart(ready_msg)
    while True:
        events = dict(poller.poll(1000))
        if control_socket in events:
            if control_socket.recv() == b'stop':
                break
        if dispatch_socket in events or deferred_work:
            if deferred_work:
                frames = deferred_work.pop(0)
            else:
                frames = dispatch_socket.recv_multipart()
            kind = frames[0]
            if kind == b'release':
                if ring_writer is not None:
                    ring_writer.release(int(frames[1]))
                continue
            if kind != b'work':
                continue  # unknown kind from a newer pool: ignore
            token, blob = frames[1], frames[2]
            kwargs = dill.loads(blob)
            current_token[0] = token
            try:
                worker.process(**kwargs)
                results_socket.send_multipart([b'done', token])
            except Exception as exc:  # noqa: BLE001 - ship to consumer
                blob = pickle.dumps((exc, traceback.format_exc()))
                results_socket.send_multipart([b'error', token, blob])
            current_token[0] = b''
            dispatch_socket.send_multipart(ready_msg)
    worker.shutdown()
    if ring_writer is not None:
        ring_writer.close()
    for sock in (dispatch_socket, control_socket, results_socket):
        sock.close(linger=1000)
    context.term()


if __name__ == '__main__':
    main(sys.argv[1])
