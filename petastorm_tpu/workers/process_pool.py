"""Process worker pool over ZeroMQ (reference: petastorm/workers_pool/process_pool.py:114-424).

Socket topology (evolved from the reference's PUSH ventilation, process_pool.py:52-74):

    main ROUTER (dispatch)  <─> worker DEALER    ('ready' requests up, work items down)
    main PUB    (control)   ──> worker SUB       ('stop' broadcast)
    main PULL   (results)   <── worker PUSH      (handshake / result / done / error)

Dispatch is **pull-based**: a worker asks for work ('ready') and the pool assigns the
next pending item to that specific worker. Unlike PUSH round-robin, nothing ever sits in
a dead worker's socket buffer, and the pool knows exactly which items each worker holds —
that attribution is what makes worker **respawn** sound: when a worker dies mid-epoch
(OOM-kill, segfault in a native decoder), the pool respawns it (bounded by
``max_worker_respawns``) and re-ventilates its un-acked in-flight items instead of
aborting the epoch (docs/robustness.md; the tf.data-service recovery model,
arXiv 2210.14826). Items are acked per-token ('done'), and a duplicate result from an
item that was re-ventilated after its first result already reached the consumer is
dropped (``results_dropped`` in diagnostics) — re-ventilation assumes the petastorm_tpu
worker contract of exactly one published result per item.

Workers are spawned (never forked — fork breaks JVM/libhdfs state, reference
exec_in_new_process.py:15-17) as fresh interpreters running
``petastorm_tpu.workers.process_worker_main`` with a dill-serialized bootstrap file.
Each worker runs a parent-watchdog thread and exits if the main process dies
(reference: process_pool.py:320-327)."""

import collections
import logging
import os
import pickle
import subprocess
import sys
import tempfile
import threading
import time

from petastorm_tpu.workers import EmptyResultError, TimeoutWaitingForResultError

logger = logging.getLogger(__name__)

_WORKER_STARTUP_TIMEOUT_S = 30
#: message kinds on the results channel
MSG_STARTED, MSG_RESULT, MSG_DONE, MSG_ERROR = b'started', b'result', b'done', b'error'
#: default total respawn budget — one bad rowgroup killing the same worker repeatedly
#: must exhaust the budget and fail loudly, not respawn forever
DEFAULT_MAX_WORKER_RESPAWNS = 3


class WorkerTerminationError(Exception):
    pass


class ProcessPool(object):
    """Spawned-process worker pool over a ZMQ dispatcher/sink pair (reference:
    workers_pool/process_pool.py): dill-bootstrapped spawn (never fork), Arrow-IPC
    or pickle wire, orphan watchdog, exception propagation, bounded worker respawn."""

    def __init__(self, workers_count, results_queue_size=50, zmq_copy_buffers=False,
                 payload_serializer=None, max_worker_respawns=DEFAULT_MAX_WORKER_RESPAWNS):
        """``payload_serializer`` picks the wire format for worker results (reference:
        process_pool.py:251-270 pluggable serializers): default
        :class:`~petastorm_tpu.workers.serializers.ArrowIpcSerializer` (columnar
        zero-copy receive); pass :class:`PickleSerializer` to force plain pickle.
        ``zmq_copy_buffers=False`` (default) receives result frames without copying —
        deserialized arrays then alias ZMQ frame memory. ``max_worker_respawns`` is the
        pool-wide budget of worker restarts after unexpected deaths; 0 restores the
        seed's die-loudly-on-first-death behavior."""
        from petastorm_tpu.workers.serializers import ArrowIpcSerializer
        self._workers_count = workers_count
        self.workers_count = workers_count
        self._results_queue_size = results_queue_size
        self._zmq_copy = zmq_copy_buffers
        self._serializer = (payload_serializer if payload_serializer is not None
                            else ArrowIpcSerializer())
        self._max_worker_respawns = max_worker_respawns
        self._context = None
        self._ventilator = None
        self._processes = []
        self._stopped = False
        # Instance state, not a get_results local: a typical call returns after one
        # result, so a per-call throttle would still run the liveness probe (ventilator
        # lock + per-worker poll) once per result.
        self._next_liveness_check = 0.0

        # ---------------------------------------------------- dispatch bookkeeping
        # All mutated under _state_lock: ventilate() runs on the ventilator thread,
        # dispatch/ack/requeue on the consumer thread.
        self._state_lock = threading.Lock()
        self._next_token = 0
        self._items = {}                      # token -> dilled kwargs (until done-acked)
        self._pending = collections.deque()   # tokens awaiting assignment
        self._assigned = {}                   # token -> worker identity holding it
        self._ready = collections.deque()     # worker identities awaiting work
        self._identity_slot = {}              # identity -> (slot, generation)
        self._slot_generation = []            # slot -> current generation
        # Tokens whose result reached the consumer but whose 'done' has not (cleared on
        # done). Any further result for such a token is a duplicate from a
        # re-ventilated attempt — the worker contract is one result per item — and is
        # dropped, regardless of whether the first result arrived before or after the
        # producing worker died.
        self._delivered = set()
        self._workers_respawned = 0
        self._results_dropped = 0

    # ------------------------------------------------------------------ lifecycle

    def start(self, worker_class, worker_args=None, ventilator=None):
        import zmq
        self._context = zmq.Context()
        self._dispatch_socket = self._context.socket(zmq.ROUTER)
        dispatch_port = self._dispatch_socket.bind_to_random_port('tcp://127.0.0.1')
        self._control_socket = self._context.socket(zmq.PUB)
        control_port = self._control_socket.bind_to_random_port('tcp://127.0.0.1')
        self._results_socket = self._context.socket(zmq.PULL)
        self._results_socket.set_hwm(self._results_queue_size)
        results_port = self._results_socket.bind_to_random_port('tcp://127.0.0.1')

        import dill
        # Spawned interpreters must resolve petastorm_tpu itself (python -m resolves it at
        # interpreter startup) AND user modules (transform fns, predicates) exactly like
        # the parent: propagate the parent's sys.path via PYTHONPATH.
        self._child_env = dict(os.environ)
        parent_paths = [p for p in sys.path if p]
        existing = self._child_env.get('PYTHONPATH')
        self._child_env['PYTHONPATH'] = os.pathsep.join(
            parent_paths + ([existing] if existing else []))
        # Kept for the lifetime of the pool: respawns re-materialize the bootstrap file
        # (workers unlink it at startup).
        self._bootstrap_template = {
            'worker_class': dill.dumps(worker_class),
            'worker_args': dill.dumps(worker_args),
            'serializer': dill.dumps(self._serializer),
            'dispatch_addr': 'tcp://127.0.0.1:{}'.format(dispatch_port),
            'control_addr': 'tcp://127.0.0.1:{}'.format(control_port),
            'results_addr': 'tcp://127.0.0.1:{}'.format(results_port),
            'parent_pid': os.getpid(),
        }
        self._slot_generation = [0] * self._workers_count
        for worker_id in range(self._workers_count):
            self._processes.append(self._spawn_worker(worker_id, generation=0))

        # Startup handshake (reference: process_pool.py:200-213).
        deadline = time.time() + _WORKER_STARTUP_TIMEOUT_S
        started = 0
        poller = zmq.Poller()
        poller.register(self._results_socket, zmq.POLLIN)
        while started < self._workers_count:
            if time.time() > deadline:
                self.stop()
                raise WorkerTerminationError(
                    'Only {} of {} workers started within {}s'
                    .format(started, self._workers_count, _WORKER_STARTUP_TIMEOUT_S))
            if poller.poll(200):
                kind, _ = self._recv()
                if kind == MSG_STARTED:
                    started += 1

        if ventilator is not None:
            self._ventilator = ventilator
            self._ventilator.start()

    def _spawn_worker(self, slot, generation):
        bootstrap = dict(self._bootstrap_template)
        bootstrap['worker_id'] = slot
        bootstrap['generation'] = generation
        fd, path = tempfile.mkstemp(suffix='.petastorm-tpu-worker')
        with os.fdopen(fd, 'wb') as f:
            pickle.dump(bootstrap, f)
        return subprocess.Popen(
            [sys.executable, '-m', 'petastorm_tpu.workers.process_worker_main', path],
            env=self._child_env)

    # ------------------------------------------------------------------ messaging

    def _recv(self):
        parts = self._results_socket.recv_multipart(copy=self._zmq_copy)
        if not self._zmq_copy:
            parts = [p.buffer for p in parts]  # memoryviews over frame memory, no copy
        kind = bytes(memoryview(parts[0]))
        payload = parts[1:] if len(parts) > 1 else None
        return kind, payload

    def ventilate(self, **kwargs):
        if self._stopped:
            raise WorkerTerminationError('Pool is stopped')
        # dill, not pickle: ventilated items carry user callables (lambda predicates,
        # per-item transform state) that plain pickle rejects — the same reason the
        # worker bootstrap ships via dill. Items are only enqueued here; the consumer
        # thread assigns them to workers in response to 'ready' requests (pull-based
        # dispatch — see module docstring).
        import dill
        blob = dill.dumps(kwargs)
        with self._state_lock:
            token = self._next_token
            self._next_token += 1
            self._items[token] = blob
            self._pending.append(token)

    def _handle_ready(self, frames):
        """A worker announced itself idle on the dispatch ROUTER: remember its route and
        slot so pending work can be assigned to it specifically."""
        identity, slot, generation = frames[0], int(frames[2]), int(frames[3])
        with self._state_lock:
            self._identity_slot[identity] = (slot, generation)
            self._ready.append(identity)

    def _dispatch_pending(self):
        """Assign pending items to ready workers (consumer thread only — ROUTER sends
        must stay single-threaded)."""
        while True:
            with self._state_lock:
                while self._pending and self._pending[0] not in self._items:
                    # Superseded token: its original attempt completed after the token
                    # was re-ventilated (crash-after-done race) — nothing left to do.
                    self._pending.popleft()
                if not self._pending or not self._ready:
                    return
                identity = self._ready.popleft()
                slot, generation = self._identity_slot.get(identity, (None, None))
                if slot is None or self._slot_generation[slot] != generation:
                    continue  # stale 'ready' from a dead/replaced worker
                token = self._pending.popleft()
                blob = self._items[token]
                self._assigned[token] = identity
            self._dispatch_socket.send_multipart(
                [identity, b'%d' % token, blob])

    def _handle_done(self, token):
        with self._state_lock:
            if token not in self._items:
                return  # duplicate 'done' from a superseded attempt
            del self._items[token]
            self._assigned.pop(token, None)
            self._delivered.discard(token)
        if self._ventilator is not None:
            self._ventilator.processed_item()

    def _check_liveness(self):
        """Consumer-thread probe: respawn dead workers while work remains (bounded
        budget), or raise once the budget is exhausted. A death after all work finished
        must not turn a successful read into an error."""
        all_work_done = self._ventilator is not None and self._ventilator.completed()
        for slot, process in enumerate(self._processes):
            if process.poll() is None:
                continue
            if all_work_done:
                continue
            if self._workers_respawned >= self._max_worker_respawns:
                self.stop()
                raise WorkerTerminationError(
                    'Worker {} (pid {}) exited with code {} while results were still '
                    'expected, and the respawn budget ({}) is exhausted'
                    .format(slot, process.pid, process.returncode,
                            self._max_worker_respawns))
            self._respawn(slot, process)

    def _respawn(self, slot, dead_process):
        """Replace the dead worker at ``slot`` and re-ventilate every item it held:
        requeued items go to the FRONT of the pending queue (they are the oldest
        work — consumers may be blocked on exactly these rowgroups)."""
        requeued = []
        with self._state_lock:
            for token, identity in list(self._assigned.items()):
                slot_gen = self._identity_slot.get(identity)
                if slot_gen is None or slot_gen[0] != slot:
                    continue
                del self._assigned[token]
                # _delivered intentionally untouched: whether the dead worker's result
                # already reached the consumer or is still in the PULL buffer, the
                # FIRST result to be delivered marks the token and every later one is
                # dropped as a duplicate.
                self._pending.appendleft(token)
                requeued.append(token)
            self._slot_generation[slot] += 1
            generation = self._slot_generation[slot]
            self._workers_respawned += 1
        logger.warning(
            'Worker %d (pid %d) died with exit code %s mid-epoch; respawning '
            '(%d/%d respawns used) and re-ventilating %d in-flight item(s)',
            slot, dead_process.pid, dead_process.returncode, self._workers_respawned,
            self._max_worker_respawns, len(requeued))
        self._processes[slot] = self._spawn_worker(slot, generation)

    def get_results(self, timeout=None):
        import zmq
        poller = zmq.Poller()
        poller.register(self._results_socket, zmq.POLLIN)
        poller.register(self._dispatch_socket, zmq.POLLIN)
        deadline = None if timeout is None else time.time() + timeout
        while True:
            # Liveness on the hot path too — not only when results stop: with several
            # workers, survivors keep producing after one dies, but the dead worker's
            # in-flight items would otherwise silently vanish. Throttled to ~10Hz
            # (detection latency is bounded by the 100ms poller timeout anyway);
            # ventilator.completed() acquires the ventilator lock (shared with the
            # backpressure condition), so it is only evaluated inside this throttled
            # window and on poll timeout — never per-result on the hot path.
            now = time.time()
            if not self._stopped and now >= self._next_liveness_check:
                self._next_liveness_check = now + 0.1
                self._check_liveness()
            self._dispatch_pending()
            events = dict(poller.poll(100))
            if not events:
                if self._ventilator is not None and getattr(self._ventilator, 'error', None):
                    self.stop()
                    raise self._ventilator.error
                if self._ventilator is not None and self._ventilator.completed():
                    raise EmptyResultError()
                if deadline is not None and time.time() > deadline:
                    raise TimeoutWaitingForResultError()
                continue
            if self._dispatch_socket in events:
                frames = self._dispatch_socket.recv_multipart()
                if len(frames) >= 4 and bytes(frames[1]) == b'ready':
                    self._handle_ready(frames)
                self._dispatch_pending()
            if self._results_socket not in events:
                continue
            kind, payload = self._recv()
            if kind == MSG_DONE:
                self._handle_done(int(bytes(memoryview(payload[0]))))
                continue
            if kind == MSG_ERROR:
                exc, tb = pickle.loads(bytes(memoryview(payload[1])))
                logger.error('Worker failure re-raised in consumer:\n%s', tb)
                self.stop()
                raise exc
            if kind == MSG_RESULT:
                token = int(bytes(memoryview(payload[0])))
                with self._state_lock:
                    if token not in self._items or token in self._delivered:
                        # Duplicate from a re-ventilated item whose first result was
                        # already delivered (retired token, or delivered-but-not-yet-
                        # acked) — count it, never deliver it twice.
                        self._results_dropped += 1
                        continue
                    self._delivered.add(token)
                return self._serializer.deserialize(payload[1:])
            if kind == MSG_STARTED:  # respawned worker joining — expected
                continue

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        if self._ventilator is not None:
            self._ventilator.stop()
        try:
            self._control_socket.send(b'stop')
        except Exception:
            logger.warning('Failed to broadcast stop to workers; relying on the '
                           'parent-watchdog exit path', exc_info=True)

    def join(self):
        deadline = time.time() + 10
        for slot, process in enumerate(self._processes):
            while process.poll() is None:
                if time.time() >= deadline:
                    # Loud fallback + reap: a silent kill() left both an unexplained
                    # SIGKILL in the logs' absence AND a zombie (kill without wait).
                    logger.warning('Worker %d (pid %d) did not exit within 10s of '
                                   'stop(); sending SIGKILL', slot, process.pid)
                    process.kill()
                    try:
                        process.wait(timeout=5)
                    except subprocess.TimeoutExpired:
                        logger.error('Worker %d (pid %d) is unreaped after SIGKILL; '
                                     'abandoning it as a zombie', slot, process.pid)
                    break
                try:
                    process.wait(timeout=1.0)
                except subprocess.TimeoutExpired:
                    # Re-broadcast stop: a worker respawned moments before stop() may
                    # still have been starting up — its SUB socket missed the original
                    # broadcast (PUB drops messages for unjoined subscribers).
                    try:
                        self._control_socket.send(b'stop')
                    except Exception:  # noqa: BLE001 - socket may already be closed
                        pass
        if self._context is not None:
            for sock in (self._dispatch_socket, self._control_socket,
                         self._results_socket):
                sock.close(linger=0)
            self._context.term()
            self._context = None

    @property
    def diagnostics(self):
        with self._state_lock:
            return {
                'workers_alive': sum(1 for p in self._processes if p.poll() is None),
                'workers_respawned': self._workers_respawned,
                'results_dropped': self._results_dropped,
                'in_flight_items': len(self._items),
            }
