"""Process worker pool over ZeroMQ (reference: petastorm/workers_pool/process_pool.py:114-424).

Socket topology (evolved from the reference's PUSH ventilation, process_pool.py:52-74):

    main ROUTER (dispatch)  <─> worker DEALER    ('ready' requests up, work items down)
    main PUB    (control)   ──> worker SUB       ('stop' broadcast)
    main PULL   (results)   <── worker PUSH      (handshake / result / done / error)

Dispatch is **pull-based**: a worker asks for work ('ready') and the pool assigns the
next pending item to that specific worker. Unlike PUSH round-robin, nothing ever sits in
a dead worker's socket buffer, and the pool knows exactly which items each worker holds —
that attribution is what makes worker **respawn** sound: when a worker dies mid-epoch
(OOM-kill, segfault in a native decoder), the pool respawns it (bounded by
``max_worker_respawns``) and re-ventilates its un-acked in-flight items instead of
aborting the epoch (docs/robustness.md; the tf.data-service recovery model,
arXiv 2210.14826). Items are acked per-token ('done'), and a duplicate result from an
item that was re-ventilated after its first result already reached the consumer is
dropped (``results_dropped`` in diagnostics) — re-ventilation assumes the petastorm_tpu
worker contract of exactly one published result per item.

Workers are spawned (never forked — fork breaks JVM/libhdfs state, reference
exec_in_new_process.py:15-17) as fresh interpreters running
``petastorm_tpu.workers.process_worker_main`` with a dill-serialized bootstrap file.
Each worker runs a parent-watchdog thread and exits if the main process dies
(reference: process_pool.py:320-327).

**Hang watchdog** (docs/robustness.md "Hang detection & circuit breakers"): respawn
alone only fires on process *death* — a worker wedged in a native deadlock or an
NFS stall would stall the epoch forever. Two complementary consumer-side detectors
reap hung-but-alive workers through the same bounded-respawn path:

- **heartbeat staleness**: each worker's heartbeat thread stamps a monotone counter
  (shm heartbeat word when the ring is up, ``heartbeat`` results-channel messages
  otherwise); a worker holding assigned items whose stamp has not changed for
  ``hang_timeout_s`` is process-wide wedged (a GIL-releasing stall keeps stamping)
  and is SIGKILLed — the existing death path then respawns it and re-ventilates its
  items.
- **per-item deadline** (``item_deadline_s``, off by default): an assigned item with
  no result for that long marks its worker hung even though it keeps heartbeating
  (GIL-released native stall). The worker is reaped; when a hang-result factory is
  installed (``on_error='skip'``), the overdue items are *quarantined* — an empty
  stand-in batch carrying a ``QuarantineRecord(reason='hang')`` is delivered instead
  of re-dispatching a rowgroup that already demonstrated it hangs a worker.

Both checks run only while ``get_results`` is idle-polling (results drained, consumer
actually starved) — a consumer away in a long training step can neither observe
staleness nor accrue false deadlines against queued-but-unread results. Reaps count
into ``workers_hung_reaped`` and the ``watchdog_reap`` telemetry counter, and consume
the same ``max_worker_respawns`` budget as deaths: a worker that hangs repeatedly
fails loudly, exactly like one that crashes repeatedly.

**Frame integrity + the shm circuit breaker**: every shm descriptor carries a CRC-32
of its payload (``workers/shm_ring.py``) verified before deserialization. A mismatch
(torn write / bit flip that the generation stamp cannot see) drops the frame unread,
counts ``shm_crc_failures`` (+ ``shm_crc_fail`` telemetry), SIGKILLs the producing
worker — its slot memory is no longer trusted, and the proven death path re-ventilates
its in-flight items — and records a failure on the pool's shm
:class:`~petastorm_tpu.resilience.CircuitBreaker`. While that breaker is open, work
dispatches carry a ``b'0'`` transport flag telling workers to publish over plain ZMQ
frames (the temporary wire fallback); after ``recovery_timeout_s`` a half-open probe
item rides the ring again and a verified result re-closes the breaker.

**Shared-memory transport** (``shm_transport``, default auto-on): result payloads are
written into a ``workers/shm_ring.py`` slot ring owned by this pool and only a tiny
slot descriptor crosses ZMQ as a ``result_shm`` message; the consumer maps the slot
zero-copy, deserializes, then acks the slot back to the producing worker with a
``release`` on the dispatch ROUTER. Payloads that exceed the slot size (or arrive
while no slot is free past the backpressure window, or when shm is unavailable) fall
back transparently to the original ZMQ ``result`` frames — counted in
``diagnostics['shm_fallback_batches']``. Descriptors carry the producing worker's
generation, so results written by a worker that died and was respawned are dropped
(``shm_stale_drops``) instead of read while the replacement overwrites the slot; the
ring is closed AND unlinked in ``join()`` regardless of worker deaths, so no
``/dev/shm`` segment outlives the pool."""

import collections
import logging
import os
import pickle
import subprocess
import sys
import tempfile
import threading
import time

from petastorm_tpu.telemetry import tracing as _tracing
from petastorm_tpu.telemetry.registry import (BYTES_UNIT, MetricsRegistry,
                                              telemetry_enabled)
from petastorm_tpu.workers import EmptyResultError, TimeoutWaitingForResultError

logger = logging.getLogger(__name__)

_WORKER_STARTUP_TIMEOUT_S = 30
#: message kinds on the results channel; ``result_shm`` carries a shm-slot
#: descriptor instead of the payload frames, ``heartbeat`` a liveness stamp
MSG_STARTED, MSG_RESULT, MSG_DONE, MSG_ERROR = b'started', b'result', b'done', b'error'
MSG_RESULT_SHM = b'result_shm'
MSG_HEARTBEAT = b'heartbeat'
#: default total respawn budget — one bad rowgroup killing the same worker repeatedly
#: must exhaust the budget and fail loudly, not respawn forever
DEFAULT_MAX_WORKER_RESPAWNS = 3
#: watchdog defaults: stamp cadence, and how long a stamp may go unchanged (while
#: the worker holds assigned items) before the worker counts as hung. The timeout
#: is deliberately >> the interval: a worker briefly starved of the GIL by a big
#: in-Python decode must not be reaped for being slow.
DEFAULT_HEARTBEAT_INTERVAL_S = 0.5
DEFAULT_HANG_TIMEOUT_S = 30.0
#: shm breaker defaults: consecutive CRC failures before the wire fallback, and
#: the cooldown before a half-open probe rides the ring again
DEFAULT_SHM_BREAKER_THRESHOLD = 3
DEFAULT_SHM_BREAKER_RECOVERY_S = 30.0


class WorkerTerminationError(Exception):
    pass


class ProcessPool(object):
    """Spawned-process worker pool over a ZMQ dispatcher/sink pair (reference:
    workers_pool/process_pool.py): dill-bootstrapped spawn (never fork), Arrow-IPC
    or pickle wire, orphan watchdog, exception propagation, bounded worker respawn."""

    def __init__(self, workers_count, results_queue_size=50, zmq_copy_buffers=False,
                 payload_serializer=None, max_worker_respawns=DEFAULT_MAX_WORKER_RESPAWNS,
                 shm_transport=None, shm_slot_bytes=None, shm_slots_per_worker=None,
                 heartbeat_interval_s=DEFAULT_HEARTBEAT_INTERVAL_S,
                 hang_timeout_s=DEFAULT_HANG_TIMEOUT_S, item_deadline_s=None,
                 shm_checksum=True, shm_breaker=None):
        """``payload_serializer`` picks the wire format for worker results (reference:
        process_pool.py:251-270 pluggable serializers): default
        :class:`~petastorm_tpu.workers.serializers.ArrowIpcSerializer` (columnar
        zero-copy receive); pass :class:`PickleSerializer` to force plain pickle.
        ``zmq_copy_buffers=False`` (default) receives result frames without copying —
        deserialized arrays then alias ZMQ frame memory. ``max_worker_respawns`` is the
        pool-wide budget of worker restarts after unexpected deaths; 0 restores the
        seed's die-loudly-on-first-death behavior.

        ``shm_transport``: None (auto — enable when ``multiprocessing.shared_memory``
        works and the serializer receives writable copies), True (require; raises if
        unavailable), False (ZMQ frames only, the seed behavior). ``shm_slot_bytes`` /
        ``shm_slots_per_worker`` size the ring (defaults in ``workers/shm_ring.py``);
        slot count bounds the transport's in-flight payloads per worker
        (backpressure).

        Watchdog knobs (module docstring; docs/robustness.md): workers stamp
        liveness every ``heartbeat_interval_s`` (0/None disables stamping); a worker
        holding assigned items whose stamp stalls for ``hang_timeout_s`` (None
        disables the staleness reap) or whose item exceeds ``item_deadline_s``
        (None disables the per-item deadline) is SIGKILLed and respawned within
        ``max_worker_respawns``. ``shm_checksum=False`` skips CRC verification of
        shm frames (benchmark baseline; keep it on in production). ``shm_breaker``
        overrides the shm transport's :class:`~petastorm_tpu.resilience.
        CircuitBreaker` (tests inject one with a fake clock)."""
        from petastorm_tpu.resilience import CircuitBreaker
        from petastorm_tpu.workers import shm_ring
        from petastorm_tpu.workers.serializers import ArrowIpcSerializer
        self._workers_count = workers_count
        self.workers_count = workers_count
        self._results_queue_size = results_queue_size
        self._zmq_copy = zmq_copy_buffers
        self._serializer = (payload_serializer if payload_serializer is not None
                            else ArrowIpcSerializer())
        self._max_worker_respawns = max_worker_respawns
        self._shm_transport = shm_transport
        self._shm_slot_bytes = shm_slot_bytes or shm_ring.DEFAULT_SLOT_BYTES
        self._shm_slots_per_worker = (shm_slots_per_worker
                                      or shm_ring.DEFAULT_SLOTS_PER_WORKER)
        self._ring = None
        if shm_transport is not False \
                and getattr(self._serializer, 'writable', True) is False:
            # Slot memory is handed back to the worker the moment deserialize
            # returns; zero-copy receives would alias reclaimed slots.
            if shm_transport:
                raise ValueError('shm_transport requires a writable-receive '
                                 'serializer (slot memory is reclaimed after '
                                 'deserialize); use ArrowIpcSerializer(writable=True)')
            self._shm_transport = False
        self._context = None
        self._ventilator = None
        self._processes = []
        self._stopped = False
        #: consumer-side telemetry (docs/observability.md): shm_map/shm_release/
        #: pool_wait latency stages plus the per-batch wire_bytes_copied size
        #: histogram (the running-mean source for wire_bytes_copied_per_batch);
        #: merged into Reader.telemetry_snapshot()
        self.telemetry = MetricsRegistry()
        # Instance state, not a get_results local: a typical call returns after one
        # result, so a per-call throttle would still run the liveness probe (ventilator
        # lock + per-worker poll) once per result.
        self._next_liveness_check = 0.0

        # ------------------------------------------------------- hang watchdog
        self._heartbeat_interval_s = heartbeat_interval_s or 0
        self._hang_timeout_s = hang_timeout_s
        if (self._hang_timeout_s is not None and self._heartbeat_interval_s
                and self._hang_timeout_s < 4 * self._heartbeat_interval_s):
            raise ValueError('hang_timeout_s ({}) must be >= 4x '
                             'heartbeat_interval_s ({}) or staleness cannot be '
                             'told from stamp jitter'
                             .format(hang_timeout_s, heartbeat_interval_s))
        self._item_deadline_s = item_deadline_s
        #: worker slot -> [last_stamp_value, monotonic_time_of_last_change]
        self._hb_state = {}
        self._dispatch_time = {}              # token -> monotonic dispatch time
        self._hang_results = collections.deque()  # synthesized quarantine batches
        self._hang_result_factory = None
        self._workers_hung_reaped = 0
        self._next_hang_check = 0.0

        # -------------------------------------------------------- shm integrity
        self._shm_checksum = shm_checksum
        self._shm_crc_failures = 0
        # token -> current attempt number, bumped on every re-ventilation. The
        # 'done' ack echoes the attempt it was dispatched with, so an ack from a
        # SUPERSEDED attempt (e.g. the done a corrupt result's producer may or
        # may not have flushed before its SIGKILL — ZMQ gives no guarantee
        # either way) can never retire an item the redelivery attempt still
        # owes, nor double-retire one the redelivery already acked.
        self._attempt = {}

        def _count_breaker_open(name, old_state, new_state):
            if new_state == 'open' and telemetry_enabled():
                self.telemetry.inc('breaker_open')
        self._shm_breaker = shm_breaker if shm_breaker is not None else \
            CircuitBreaker('shm_transport',
                           failure_threshold=DEFAULT_SHM_BREAKER_THRESHOLD,
                           recovery_timeout_s=DEFAULT_SHM_BREAKER_RECOVERY_S)
        # injected breakers feed the breaker_open telemetry counter too;
        # observe_transitions chains after (never clobbers) any caller wiring
        self._shm_breaker.observe_transitions(_count_breaker_open)

        # ---------------------------------------------------- dispatch bookkeeping
        # All mutated under _state_lock: ventilate() runs on the ventilator thread,
        # dispatch/ack/requeue on the consumer thread.
        self._state_lock = threading.Lock()
        self._next_token = 0
        self._items = {}                      # token -> dilled kwargs (until done-acked)
        self._pending = collections.deque()   # tokens awaiting assignment
        self._assigned = {}                   # token -> worker identity holding it
        self._ready = collections.deque()     # worker identities awaiting work
        self._identity_slot = {}              # identity -> (slot, generation)
        self._slot_identity = {}              # slot -> current identity (for releases)
        self._slot_generation = []            # slot -> current generation
        # Tokens whose result reached the consumer but whose 'done' has not (cleared on
        # done). Any further result for such a token is a duplicate from a
        # re-ventilated attempt — the worker contract is one result per item — and is
        # dropped, regardless of whether the first result arrived before or after the
        # producing worker died.
        self._delivered = set()
        self._workers_respawned = 0
        self._results_dropped = 0
        # ------------------------------------------------------ wire counters
        # All consumer-thread-only except where noted; read under _state_lock in
        # diagnostics for a consistent snapshot.
        self._wire_batches = 0          # result payloads delivered or dropped
        self._shm_batches = 0           # payloads that arrived via the shm ring
        self._shm_fallback_batches = 0  # ZMQ-frame results while shm was enabled
        self._shm_stale_drops = 0       # descriptors from a pre-respawn generation
        self._shm_bytes_mapped = 0      # payload bytes served zero-copy from slots
        self._zmq_result_bytes = 0      # payload bytes copied off the ZMQ wire

    # ------------------------------------------------------------------ lifecycle

    def start(self, worker_class, worker_args=None, ventilator=None):
        import zmq
        self._context = zmq.Context()
        self._dispatch_socket = self._context.socket(zmq.ROUTER)
        dispatch_port = self._dispatch_socket.bind_to_random_port('tcp://127.0.0.1')
        self._control_socket = self._context.socket(zmq.PUB)
        control_port = self._control_socket.bind_to_random_port('tcp://127.0.0.1')
        self._results_socket = self._context.socket(zmq.PULL)
        self._results_socket.set_hwm(self._results_queue_size)
        results_port = self._results_socket.bind_to_random_port('tcp://127.0.0.1')

        if self._shm_transport is not False and self._ring is None:
            from petastorm_tpu.workers.shm_ring import ShmRing
            try:
                self._ring = ShmRing(self._workers_count,
                                     slots_per_worker=self._shm_slots_per_worker,
                                     slot_bytes=self._shm_slot_bytes)
            except Exception as exc:  # noqa: BLE001 - auto mode degrades to ZMQ
                if self._shm_transport:
                    raise
                logger.warning('shared-memory transport unavailable (%r); falling '
                               'back to ZMQ result frames', exc)
                self._ring = None

        import dill
        # Spawned interpreters must resolve petastorm_tpu itself (python -m resolves it at
        # interpreter startup) AND user modules (transform fns, predicates) exactly like
        # the parent: propagate the parent's sys.path via PYTHONPATH.
        self._child_env = dict(os.environ)
        parent_paths = [p for p in sys.path if p]
        existing = self._child_env.get('PYTHONPATH')
        self._child_env['PYTHONPATH'] = os.pathsep.join(
            parent_paths + ([existing] if existing else []))
        # Propagate the telemetry kill switch: set_telemetry_enabled(False) in
        # the parent must also silence SPAWNED workers (captured at pool start;
        # an explicit PETASTORM_TPU_TELEMETRY in the env wins).
        self._child_env.setdefault('PETASTORM_TPU_TELEMETRY',
                                   '1' if telemetry_enabled() else '0')
        # Same capture for the flight recorder: workers spawned while tracing
        # is armed record their own timeline events (trace sidecar).
        self._child_env.setdefault('PETASTORM_TPU_TRACE',
                                   '1' if _tracing.trace_enabled() else '0')
        # Kept for the lifetime of the pool: respawns re-materialize the bootstrap file
        # (workers unlink it at startup).
        self._bootstrap_template = {
            'worker_class': dill.dumps(worker_class),
            'worker_args': dill.dumps(worker_args),
            'serializer': dill.dumps(self._serializer),
            'dispatch_addr': 'tcp://127.0.0.1:{}'.format(dispatch_port),
            'control_addr': 'tcp://127.0.0.1:{}'.format(control_port),
            'results_addr': 'tcp://127.0.0.1:{}'.format(results_port),
            'parent_pid': os.getpid(),
            'shm': (dict(self._ring.worker_spec(), name=self._ring.name,
                         checksum=self._shm_checksum)
                    if self._ring is not None else None),
            'heartbeat_interval_s': self._heartbeat_interval_s,
        }
        self._slot_generation = [0] * self._workers_count
        for worker_id in range(self._workers_count):
            self._processes.append(self._spawn_worker(worker_id, generation=0))
            self._hb_state[worker_id] = [0, time.monotonic()]

        # Startup handshake (reference: process_pool.py:200-213).
        deadline = time.time() + _WORKER_STARTUP_TIMEOUT_S
        started = 0
        poller = zmq.Poller()
        poller.register(self._results_socket, zmq.POLLIN)
        while started < self._workers_count:
            if time.time() > deadline:
                self.stop()
                self._release_ring()
                raise WorkerTerminationError(
                    'Only {} of {} workers started within {}s'
                    .format(started, self._workers_count, _WORKER_STARTUP_TIMEOUT_S))
            if poller.poll(200):
                kind, _ = self._recv()
                if kind == MSG_STARTED:
                    started += 1

        if ventilator is not None:
            self._ventilator = ventilator
            self._ventilator.start()

    def _spawn_worker(self, slot, generation):
        bootstrap = dict(self._bootstrap_template)
        bootstrap['worker_id'] = slot
        bootstrap['generation'] = generation
        fd, path = tempfile.mkstemp(suffix='.petastorm-tpu-worker')
        with os.fdopen(fd, 'wb') as f:
            pickle.dump(bootstrap, f)
        return subprocess.Popen(
            [sys.executable, '-m', 'petastorm_tpu.workers.process_worker_main', path],
            env=self._child_env)

    # ------------------------------------------------------------------ messaging

    def _recv(self):
        parts = self._results_socket.recv_multipart(copy=self._zmq_copy)
        if not self._zmq_copy:
            parts = [p.buffer for p in parts]  # memoryviews over frame memory, no copy
        kind = bytes(memoryview(parts[0]))
        payload = parts[1:] if len(parts) > 1 else None
        return kind, payload

    def ventilate(self, **kwargs):
        if self._stopped:
            raise WorkerTerminationError('Pool is stopped')
        # dill, not pickle: ventilated items carry user callables (lambda predicates,
        # per-item transform state) that plain pickle rejects — the same reason the
        # worker bootstrap ships via dill. Items are only enqueued here; the consumer
        # thread assigns them to workers in response to 'ready' requests (pull-based
        # dispatch — see module docstring).
        import dill
        blob = dill.dumps(kwargs)
        with self._state_lock:
            token = self._next_token
            self._next_token += 1
            self._items[token] = blob
            self._pending.append(token)

    def _handle_ready(self, frames):
        """A worker announced itself idle on the dispatch ROUTER: remember its route and
        slot so pending work (and shm slot releases) can be routed to it
        specifically."""
        identity, slot, generation = frames[0], int(frames[2]), int(frames[3])
        with self._state_lock:
            self._identity_slot[identity] = (slot, generation)
            if self._slot_generation[slot] == generation:
                self._slot_identity[slot] = identity
            self._ready.append(identity)

    def _dispatch_pending(self):
        """Assign pending items to ready workers (consumer thread only — ROUTER sends
        must stay single-threaded). The trailing transport flag tells the worker
        whether its result may ride the shm ring — ``b'0'`` while the shm circuit
        breaker is open (the temporary ZMQ-wire fallback after repeated CRC
        failures)."""
        while True:
            with self._state_lock:
                while self._pending and self._pending[0] not in self._items:
                    # Superseded token: its original attempt completed after the token
                    # was re-ventilated (crash-after-done race) — nothing left to do.
                    self._pending.popleft()
                if not self._pending or not self._ready:
                    return
                identity = self._ready.popleft()
                slot, generation = self._identity_slot.get(identity, (None, None))
                if slot is None or self._slot_generation[slot] != generation:
                    continue  # stale 'ready' from a dead/replaced worker
                token = self._pending.popleft()
                blob = self._items[token]
                self._assigned[token] = identity
                self._dispatch_time[token] = time.monotonic()
                attempt = self._attempt.setdefault(token, 0)
            shm_flag = b'1' if (self._ring is not None
                                and self._shm_breaker.allow()) else b'0'
            self._dispatch_socket.send_multipart(
                [identity, b'work', b'%d' % token, blob, shm_flag,
                 b'%d' % attempt])

    def _release_slot(self, descriptor):
        """Ack a consumed (or duplicate-dropped) shm slot back to the worker that
        owns it, so the slot re-enters the worker's free set. Consumer thread only
        (ROUTER sends are single-threaded). A vanished identity (worker died after
        publishing) is fine: ROUTER drops unroutable messages and the replacement
        worker starts with every slot free."""
        with self._state_lock:
            identity = self._slot_identity.get(descriptor.worker_slot)
            current = self._slot_generation[descriptor.worker_slot]
        if identity is None or current != descriptor.generation:
            return
        release_start = time.perf_counter()
        self._dispatch_socket.send_multipart(
            [identity, b'release', b'%d' % descriptor.ring_slot])
        if telemetry_enabled():
            self.telemetry.observe('shm_release',
                                   time.perf_counter() - release_start)

    def _handle_done(self, token, attempt=None):
        with self._state_lock:
            if token not in self._items:
                return  # duplicate 'done' from a superseded attempt
            if attempt is not None and attempt != self._attempt.get(token, 0):
                # Ack from a superseded dispatch (e.g. the producer of a
                # CRC-failed frame flushed its done before the reaping SIGKILL
                # landed): the item was re-ventilated, and only the CURRENT
                # attempt's ack may retire it — otherwise the redelivered
                # result would be lost (retire-before-delivery).
                return
            del self._items[token]
            self._assigned.pop(token, None)
            self._dispatch_time.pop(token, None)
            self._attempt.pop(token, None)
            self._delivered.discard(token)
        if self._ventilator is not None:
            self._ventilator.processed_item()

    def _check_liveness(self):
        """Consumer-thread probe: respawn dead workers while work remains (bounded
        budget), or raise once the budget is exhausted. A death after all work finished
        must not turn a successful read into an error."""
        all_work_done = self._ventilator is not None and self._ventilator.completed()
        for slot, process in enumerate(self._processes):
            if process.poll() is None:
                continue
            if all_work_done:
                continue
            if self._workers_respawned >= self._max_worker_respawns:
                self.stop()
                raise WorkerTerminationError(
                    'Worker {} (pid {}) exited with code {} while results were still '
                    'expected, and the respawn budget ({}) is exhausted'
                    .format(slot, process.pid, process.returncode,
                            self._max_worker_respawns))
            self._respawn(slot, process)

    def _respawn(self, slot, dead_process):
        """Replace the dead worker at ``slot`` and re-ventilate every item it held:
        requeued items go to the FRONT of the pending queue (they are the oldest
        work — consumers may be blocked on exactly these rowgroups)."""
        requeued = []
        requeued_ctx = []
        with self._state_lock:
            for token, identity in list(self._assigned.items()):
                slot_gen = self._identity_slot.get(identity)
                if slot_gen is None or slot_gen[0] != slot:
                    continue
                del self._assigned[token]
                self._dispatch_time.pop(token, None)
                # New attempt number: any done the dead worker managed to flush
                # for this token is now a stale ack and cannot retire the item.
                reaped_attempt = self._attempt.get(token, 0)
                self._attempt[token] = reaped_attempt + 1
                requeued_ctx.append((token, self._items.get(token),
                                     reaped_attempt))
                # _delivered intentionally untouched: whether the dead worker's result
                # already reached the consumer or is still in the PULL buffer, the
                # FIRST result to be delivered marks the token and every later one is
                # dropped as a duplicate.
                self._pending.appendleft(token)
                requeued.append(token)
            self._slot_generation[slot] += 1
            generation = self._slot_generation[slot]
            self._workers_respawned += 1
            # fresh liveness clock for the replacement (it has not stamped yet)
            self._hb_state[slot] = [0, time.monotonic()]
        logger.warning(
            'Worker %d (pid %d) died with exit code %s mid-epoch; respawning '
            '(%d/%d respawns used) and re-ventilating %d in-flight item(s)',
            slot, dead_process.pid, dead_process.returncode, self._workers_respawned,
            self._max_worker_respawns, len(requeued))
        if _tracing.trace_enabled():
            # Timeline markers for the dead attempt: the worker took its
            # unpublished events with it, so this instant (old attempt) plus
            # the replacement's spans (attempt+1) are how one rowgroup's two
            # lives appear as distinct attempts on the merged trace.
            import dill
            for token, blob, reaped_attempt in requeued_ctx:
                ctx = None
                if blob is not None:
                    try:
                        ctx = self._kwargs_trace_ctx(dill.loads(blob),
                                                     reaped_attempt)
                    except Exception:  # noqa: BLE001 - an undecodable blob only costs the marker its context tag, never the respawn
                        ctx = None
                _tracing.trace_instant(
                    'worker_respawn', ctx=ctx,
                    args={'worker_slot': slot, 'exit_code':
                          dead_process.returncode,
                          'new_attempt': reaped_attempt + 1})
        self._processes[slot] = self._spawn_worker(slot, generation)

    def set_shm_slot_config(self, slots_per_worker=None, slot_bytes=None):
        """Bounded runtime update of the shm ring shape — a **deferred** knob
        (docs/autotuning.md): the live ring is never resized under its workers;
        the new shape applies to the NEXT ring generation (the next
        ``start()``, e.g. the next reader built from this configuration).
        Returns the ``(slots_per_worker, slot_bytes)`` now configured."""
        if slots_per_worker is not None:
            slots_per_worker = int(slots_per_worker)
            if slots_per_worker < 1:
                raise ValueError('slots_per_worker must be >= 1, got {}'
                                 .format(slots_per_worker))
            self._shm_slots_per_worker = slots_per_worker
        if slot_bytes is not None:
            slot_bytes = int(slot_bytes)
            if slot_bytes < 4096:
                raise ValueError('slot_bytes must be >= 4096, got {}'
                                 .format(slot_bytes))
            self._shm_slot_bytes = slot_bytes
        return self._shm_slots_per_worker, self._shm_slot_bytes

    # ----------------------------------------------------------- hang watchdog

    def set_hang_result_factory(self, factory):
        """Install the per-item-deadline quarantine hook: ``factory(item_kwargs,
        elapsed_s)`` must return a result object (an empty stand-in batch carrying
        a ``QuarantineRecord(reason='hang')``) delivered in place of the overdue
        item's real result. Installed by the reader under ``on_error='skip'``;
        without it, overdue items are re-ventilated on the replacement worker (and
        a rowgroup that hangs every worker exhausts the respawn budget loudly)."""
        self._hang_result_factory = factory

    def _note_heartbeat(self, payload):
        """A ``heartbeat`` message arrived on the results channel (ring-less
        transport): record the stamp for the producing worker slot."""
        slot = int(bytes(memoryview(payload[0])))
        generation = int(bytes(memoryview(payload[1])))
        seq = int(bytes(memoryview(payload[2])))
        with self._state_lock:
            if self._slot_generation[slot] != generation:
                return  # stale stamp from a reaped worker's dying breath
            state = self._hb_state.get(slot)
            if state is None or state[0] != seq:
                self._hb_state[slot] = [seq, time.monotonic()]

    def _heartbeat_stale_s(self, slot, now):
        """Seconds since worker ``slot``'s heartbeat stamp last CHANGED (0.0 right
        after a change), or None when stamping is disabled. Change detection is
        consumer-side, so worker and pool clocks are never compared."""
        if not self._heartbeat_interval_s:
            return None
        state = self._hb_state.get(slot)
        if state is None:
            state = [0, now]
            self._hb_state[slot] = state
        if self._ring is not None:
            value = self._ring.heartbeat(slot)
            if value != state[0]:
                self._hb_state[slot] = [value, now]
                return 0.0
        return now - state[1]

    def _check_hangs(self):
        """Reap hung-but-alive workers (module docstring). Runs only from the
        idle branch of ``get_results`` — every queued result/heartbeat has been
        drained, so observed staleness is real, not a consumer that was away."""
        if self._hang_timeout_s is None and self._item_deadline_s is None:
            return
        now = time.monotonic()
        if now < self._next_hang_check:
            return
        self._next_hang_check = now + 0.5
        with self._state_lock:
            assigned_by_slot = {}
            for token, identity in self._assigned.items():
                slot_gen = self._identity_slot.get(identity)
                if slot_gen is not None:
                    assigned_by_slot.setdefault(slot_gen[0], []).append(token)
            dispatch_time = dict(self._dispatch_time)
        for slot, process in enumerate(self._processes):
            if process.poll() is not None:
                continue  # already dead: _check_liveness owns that path
            tokens = assigned_by_slot.get(slot)
            if not tokens:
                # keep the change tracker fresh so idle stretches between items
                # never accrue staleness
                self._heartbeat_stale_s(slot, now)
                continue
            stale_s = self._heartbeat_stale_s(slot, now)
            heartbeat_hung = (self._hang_timeout_s is not None
                              and stale_s is not None
                              and stale_s > self._hang_timeout_s)
            overdue = []
            if self._item_deadline_s is not None:
                overdue = [token for token in tokens
                           if now - dispatch_time.get(token, now)
                           > self._item_deadline_s]
            if heartbeat_hung or overdue:
                self._reap_hung_worker(slot, process, overdue, stale_s, now,
                                       dispatch_time)

    def _reap_hung_worker(self, slot, process, overdue, stale_s, now,
                          dispatch_time):
        """SIGKILL a hung worker so the existing death path respawns it and
        re-ventilates its items. Overdue items are quarantined first (when a
        hang-result factory is installed): re-dispatching a rowgroup that just
        demonstrated it hangs a worker would burn the whole respawn budget on
        the same poison item."""
        with self._state_lock:
            self._workers_hung_reaped += 1
            reap_count = self._workers_hung_reaped
        if telemetry_enabled():
            self.telemetry.inc('watchdog_reap')
        if _tracing.trace_enabled():
            # Anomaly markers for the flight recorder, tagged with the reaped
            # attempt's context while the items are still registered — the hung
            # worker published nothing, so these instants ARE the reaped
            # attempt's footprint on the merged timeline.
            reap_args = {'worker_slot': slot, 'pid': process.pid,
                         'stale_s': round(stale_s, 3) if stale_s is not None
                         else None}
            if overdue:
                # one lock acquisition for all overdue tokens; decode and
                # emit lock-free (mirrors the _respawn requeued_ctx pattern)
                with self._state_lock:
                    pairs = [(self._attempt.get(token, 0),
                              self._items.get(token)) for token in overdue]
                import dill
                for attempt, blob in pairs:
                    ctx = None
                    if blob is not None:
                        try:
                            ctx = self._kwargs_trace_ctx(dill.loads(blob),
                                                         attempt)
                        except Exception:  # noqa: BLE001 - an undecodable blob only costs the marker its context tag, never the reap
                            ctx = None
                    _tracing.trace_instant('watchdog_reap', ctx=ctx,
                                           args=reap_args)
            else:
                _tracing.trace_instant('watchdog_reap', args=reap_args)
        logger.error(
            'Worker %d (pid %d) is hung (heartbeat stale %.1fs, %d item(s) past '
            'the %s item deadline); reaping it (hung-reap #%d — consumes the '
            'respawn budget)',
            slot, process.pid, stale_s if stale_s is not None else -1.0,
            len(overdue), self._item_deadline_s, reap_count)
        if self._hang_result_factory is not None and overdue:
            import dill
            for token in overdue:
                with self._state_lock:
                    blob = self._items.pop(token, None)
                    self._assigned.pop(token, None)
                    self._dispatch_time.pop(token, None)
                    self._attempt.pop(token, None)
                if blob is None:
                    continue  # superseded meanwhile
                elapsed = now - dispatch_time.get(token, now)
                try:
                    stand_in = self._hang_result_factory(dill.loads(blob), elapsed)
                except Exception:  # noqa: BLE001 - never lose the reap to the hook
                    logger.exception('hang-result factory failed for token %d; '
                                     're-ventilating the item instead', token)
                    with self._state_lock:
                        self._items[token] = blob
                        self._attempt[token] = self._attempt.get(token, 0) + 1
                        self._pending.appendleft(token)
                    continue
                self._hang_results.append(stand_in)
                # the item is retired exactly as a 'done' would retire it
                if self._ventilator is not None:
                    self._ventilator.processed_item()
        process.kill()
        # The next liveness pass observes the death and respawns through the
        # bounded budget; any still-assigned tokens re-ventilate there.

    def get_results(self, timeout=None):
        import zmq
        poller = zmq.Poller()
        poller.register(self._results_socket, zmq.POLLIN)
        poller.register(self._dispatch_socket, zmq.POLLIN)
        deadline = None if timeout is None else time.time() + timeout
        wait_start = time.perf_counter()
        while True:
            if self._hang_results:
                # Stand-in batch synthesized for a hang-quarantined item: deliver
                # it like any other result (the quarantine record rides it).
                return self._hang_results.popleft()
            # Liveness on the hot path too — not only when results stop: with several
            # workers, survivors keep producing after one dies, but the dead worker's
            # in-flight items would otherwise silently vanish. Throttled to ~10Hz
            # (detection latency is bounded by the 100ms poller timeout anyway);
            # ventilator.completed() acquires the ventilator lock (shared with the
            # backpressure condition), so it is only evaluated inside this throttled
            # window and on poll timeout — never per-result on the hot path.
            now = time.time()
            if not self._stopped and now >= self._next_liveness_check:
                self._next_liveness_check = now + 0.1
                self._check_liveness()
            self._dispatch_pending()
            events = dict(poller.poll(100))
            if not events:
                # Hang detection belongs exactly here: the queues are drained and
                # the consumer is genuinely starved, so heartbeat staleness and
                # item deadlines measure the workers, not a busy consumer.
                if not self._stopped:
                    self._check_hangs()
                    if self._hang_results:
                        # a reap just quarantined item(s) — deliver the stand-in
                        # BEFORE the completed() check can end the epoch
                        continue
                if self._ventilator is not None and getattr(self._ventilator, 'error', None):
                    self.stop()
                    raise self._ventilator.error
                if self._ventilator is not None and self._ventilator.completed():
                    raise EmptyResultError()
                if deadline is not None and time.time() > deadline:
                    raise TimeoutWaitingForResultError()
                continue
            if self._dispatch_socket in events:
                frames = self._dispatch_socket.recv_multipart()
                if len(frames) >= 4 and bytes(frames[1]) == b'ready':
                    self._handle_ready(frames)
                self._dispatch_pending()
            if self._results_socket not in events:
                continue
            kind, payload = self._recv()
            if kind == MSG_HEARTBEAT:
                self._note_heartbeat(payload)
                continue
            if kind == MSG_DONE:
                self._handle_done(
                    int(bytes(memoryview(payload[0]))),
                    attempt=(int(bytes(memoryview(payload[1])))
                             if len(payload) > 1 else None))
                continue
            if kind == MSG_ERROR:
                exc, tb = pickle.loads(bytes(memoryview(payload[1])))
                logger.error('Worker failure re-raised in consumer:\n%s', tb)
                self.stop()
                raise exc
            if kind == MSG_RESULT:
                token = int(bytes(memoryview(payload[0])))
                payload_bytes = sum(memoryview(frame).nbytes for frame in payload[1:])
                with self._state_lock:
                    self._wire_batches += 1
                    self._zmq_result_bytes += payload_bytes
                    shm_fallback = self._ring is not None
                    if shm_fallback:
                        self._shm_fallback_batches += 1
                    if token not in self._items or token in self._delivered:
                        # Duplicate from a re-ventilated item whose first result was
                        # already delivered (retired token, or delivered-but-not-yet-
                        # acked) — count it, never deliver it twice.
                        self._results_dropped += 1
                        continue
                    self._delivered.add(token)
                if shm_fallback and _tracing.trace_enabled():
                    # anomaly marker: this result rode the ZMQ wire although the
                    # shm ring was enabled (oversized / slot-starved / breaker)
                    _tracing.trace_instant('shm_fallback', args={'token': token})
                copy_before = self._serializer_bytes_copied()
                result = self._serializer.deserialize(payload[1:])
                if telemetry_enabled():
                    # true per-batch copied bytes: ZMQ frame bytes + the
                    # serializer's receive-side copies for THIS batch
                    self.telemetry.observe(
                        'wire_bytes_copied',
                        payload_bytes + self._serializer_bytes_copied()
                        - copy_before, unit=BYTES_UNIT)
                    self.telemetry.observe('pool_wait',
                                           time.perf_counter() - wait_start)
                return result
            if kind == MSG_RESULT_SHM:
                result = self._handle_shm_result(payload)
                if result is not None:
                    if telemetry_enabled():
                        self.telemetry.observe('pool_wait',
                                               time.perf_counter() - wait_start)
                    return result[0]
                continue
            if kind == MSG_STARTED:  # respawned worker joining — expected
                continue

    def _handle_shm_result(self, payload):
        """One ``result_shm`` message: validate the descriptor's generation, dedup the
        token, verify the payload CRC, deserialize zero-copy from the slot, ack the
        slot. Returns ``(payload_obj,)`` to deliver or None to keep polling."""
        from petastorm_tpu.workers.shm_ring import ShmSlotDescriptor
        token = int(bytes(memoryview(payload[0])))
        descriptor = ShmSlotDescriptor.from_bytes(bytes(memoryview(payload[1])))
        with self._state_lock:
            self._wire_batches += 1
            self._zmq_result_bytes += memoryview(payload[1]).nbytes
            if self._slot_generation[descriptor.worker_slot] != descriptor.generation:
                # Written by a worker that has since died and been respawned: the
                # replacement owns (and may be overwriting) the slot — never read
                # it. The item was re-ventilated, so a fresh result is coming.
                self._shm_stale_drops += 1
                return None
            duplicate = token not in self._items or token in self._delivered
        if duplicate:
            with self._state_lock:
                self._results_dropped += 1
            self._release_slot(descriptor)  # still owed: the slot holds real bytes
            return None
        if self._ring is None:  # defensive: descriptor without a ring
            self._release_slot(descriptor)
            return None
        map_start = time.perf_counter()
        copy_before = self._serializer_bytes_copied()
        views = self._ring.view(descriptor)
        if self._shm_checksum and descriptor.crc is not None:
            from petastorm_tpu.workers.integrity import payload_checksum
            if payload_checksum(views) != descriptor.crc:
                for view in views:
                    view.release()
                self._on_shm_corruption(descriptor, token)
                return None
        with self._state_lock:
            self._delivered.add(token)
            self._shm_batches += 1
            self._shm_bytes_mapped += descriptor.total_bytes
        try:
            result = self._serializer.deserialize(views)
            self._shm_breaker.record_success()
            if _tracing.trace_enabled():
                # consumer-side leg of the rowgroup's trace: the shm_map span
                # tagged with the delivered batch's (epoch, rowgroup, attempt),
                # so the exported timeline stitches worker and consumer tracks
                item_id = getattr(result, 'item_id', None)
                ctx = None
                if item_id is not None:
                    with self._state_lock:
                        attempt = self._attempt.get(token, 0)
                    ctx = (int(item_id[0]), int(item_id[1]), attempt)
                _tracing.trace_complete(
                    'shm_map', map_start, time.perf_counter() - map_start,
                    ctx=ctx)
            if telemetry_enabled():
                # shm_map: slot view + CRC verify + deserialize; copied bytes =
                # descriptor frame + the serializer's receive-side copies
                self.telemetry.observe('shm_map',
                                       time.perf_counter() - map_start)
                self.telemetry.observe(
                    'wire_bytes_copied',
                    memoryview(payload[1]).nbytes
                    + self._serializer_bytes_copied() - copy_before,
                    unit=BYTES_UNIT)
            return (result,)
        finally:
            # Frames never outlive this call (writable-receive contract enforced in
            # __init__): drop the slot views so join()'s unlink can't hit exported
            # buffers, then hand the slot back.
            for view in views:
                try:
                    view.release()
                except BufferError:  # pragma: no cover - a consumer kept a ref
                    pass
            self._release_slot(descriptor)

    def _on_shm_corruption(self, descriptor, token):
        """A shm frame failed its CRC — a torn write or bit flip the generation
        stamp cannot see. The frame is dropped unread; the producing worker is
        SIGKILLed (its slot memory is no longer trusted, and the proven death
        path re-ventilates everything it held, this token included, with the
        duplicate-drop guard intact); the shm breaker records the failure, so
        repeated corruption opens it and routes results over the ZMQ wire until
        the cooldown's half-open probe passes (docs/robustness.md)."""
        with self._state_lock:
            self._shm_crc_failures += 1
            failures = self._shm_crc_failures
            # Invalidate the producer's ack for this token RIGHT NOW: if its
            # done(attempt) was flushed before the SIGKILL below lands, it is
            # already queued behind this frame and would otherwise retire the
            # item before the respawn path can redeliver it.
            reaped_attempt = self._attempt.get(token, 0)
            self._attempt[token] = reaped_attempt + 1
        if telemetry_enabled():
            self.telemetry.inc('shm_crc_fail')
        if _tracing.trace_enabled():
            _tracing.trace_instant(
                'shm_crc_drop', ctx=self._token_trace_ctx(token, reaped_attempt),
                args={'worker_slot': descriptor.worker_slot,
                      'ring_slot': descriptor.ring_slot, 'token': token})
        self._shm_breaker.record_failure()
        logger.error(
            'shm frame from worker %d (ring slot %d, token %d) failed CRC '
            'verification (corruption #%d); dropping it unread, reaping the '
            'producing worker, and recording a shm-breaker failure (state now %r)',
            descriptor.worker_slot, descriptor.ring_slot, token, failures,
            self._shm_breaker.state)
        process = self._processes[descriptor.worker_slot]
        if process.poll() is None:
            process.kill()
        # No slot release: the replacement worker starts with its range free,
        # and the death path re-ventilates everything the worker held.

    def _token_trace_ctx(self, token, attempt):
        """Causal trace context ``(epoch, rowgroup, attempt)`` for a dispatched
        token, decoded from its ventilated kwargs blob — anomaly-path only
        (reaps, respawns, CRC drops are rare; the hot path never loads blobs)."""
        with self._state_lock:
            blob = self._items.get(token)
        if blob is None:
            return None
        import dill
        try:
            kwargs = dill.loads(blob)
        except Exception:  # noqa: BLE001 - an undecodable blob only costs the anomaly marker its context tag, never the reap/redelivery itself
            return None
        return self._kwargs_trace_ctx(kwargs, attempt)

    @staticmethod
    def _kwargs_trace_ctx(kwargs, attempt):
        piece = kwargs.get('piece_index')
        if piece is None:
            return None
        return (int(kwargs.get('epoch_index', 0)), int(piece), int(attempt))

    def _serializer_bytes_copied(self):
        """Cumulative receive-side copied bytes from the serializer's stats (0 when
        the serializer keeps none) — deltas around one deserialize give the
        per-batch copy cost for the wire_bytes_copied histogram."""
        stats = getattr(self._serializer, 'stats', None)
        return stats.get('bytes_copied', 0) if stats else 0

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        if self._ventilator is not None:
            self._ventilator.stop()
        try:
            self._control_socket.send(b'stop')
        except Exception:  # noqa: BLE001 - stop() is best-effort: a dead socket/context must not mask shutdown
            logger.warning('Failed to broadcast stop to workers; relying on the '
                           'parent-watchdog exit path', exc_info=True)

    def join(self):
        deadline = time.time() + 10
        self._drain_until_exit(deadline)
        for slot, process in enumerate(self._processes):
            if process.poll() is None:
                # Loud fallback + reap: a silent kill() left both an unexplained
                # SIGKILL in the logs' absence AND a zombie (kill without wait).
                logger.warning('Worker %d (pid %d) did not exit within 10s of '
                               'stop(); sending SIGKILL', slot, process.pid)
                process.kill()
                try:
                    process.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    logger.error('Worker %d (pid %d) is unreaped after SIGKILL; '
                                 'abandoning it as a zombie', slot, process.pid)
        if self._context is not None:
            for sock in (self._dispatch_socket, self._control_socket,
                         self._results_socket):
                sock.close(linger=0)
            self._context.term()
            self._context = None
        # After every worker is reaped: close AND unlink the ring so no /dev/shm
        # segment survives the pool, however the workers died.
        self._release_ring()

    def _drain_until_exit(self, deadline):
        """Wait (to ``deadline``) for workers to exit, DRAINING both channels in
        200ms polls. Discarding queued results/heartbeats and acking un-released
        shm descriptors is what lets a worker blocked in its slot-wait
        backpressure loop (e.g. publishing the items it held when a sibling was
        hang-reaped) finish its publish, see the stop broadcast, and exit —
        instead of riding the full slot-wait timeout into the SIGKILL fallback."""
        if self._context is None:
            while (time.time() < deadline
                    and any(p.poll() is None for p in self._processes)):
                time.sleep(0.2)
            return
        import zmq
        from petastorm_tpu.workers.shm_ring import ShmSlotDescriptor
        poller = zmq.Poller()
        poller.register(self._results_socket, zmq.POLLIN)
        poller.register(self._dispatch_socket, zmq.POLLIN)
        next_stop_broadcast = 0.0
        while any(p.poll() is None for p in self._processes):
            now = time.time()
            if now >= deadline:
                return
            if now >= next_stop_broadcast:
                # Re-broadcast stop: a worker respawned moments before stop() may
                # still have been starting up — its SUB socket missed the original
                # broadcast (PUB drops messages for unjoined subscribers).
                next_stop_broadcast = now + 1.0
                try:
                    self._control_socket.send(b'stop')
                except Exception:  # noqa: BLE001 - socket may already be closed
                    pass
            events = dict(poller.poll(200))
            if self._dispatch_socket in events:
                frames = self._dispatch_socket.recv_multipart()
                if len(frames) >= 4 and bytes(frames[1]) == b'ready':
                    self._handle_ready(frames)  # keep release routing current
            if self._results_socket in events:
                kind, payload = self._recv()
                if kind == MSG_RESULT_SHM:
                    try:
                        descriptor = ShmSlotDescriptor.from_bytes(
                            bytes(memoryview(payload[1])))
                    except Exception:  # noqa: BLE001 - shutdown drain is best-effort
                        continue
                    self._release_slot(descriptor)
                # every other kind (result/done/heartbeat/started/error) is
                # drained and dropped — the epoch is over

    def _release_ring(self):
        if self._ring is not None:
            try:
                self._ring.close_and_unlink()
            except Exception:  # noqa: BLE001 - cleanup must not mask the exit path
                logger.warning('failed to unlink the shm ring', exc_info=True)
            self._ring = None

    @property
    def diagnostics(self):
        serializer_stats = dict(getattr(self._serializer, 'stats', None) or {})
        with self._state_lock:
            wire_batches = self._wire_batches
            bytes_copied = (self._zmq_result_bytes
                            + serializer_stats.get('bytes_copied', 0))
            diag = {
                'workers_alive': sum(1 for p in self._processes if p.poll() is None),
                'workers_respawned': self._workers_respawned,
                'results_dropped': self._results_dropped,
                'in_flight_items': len(self._items),
                # --------------------------------- hang watchdog + integrity
                'workers_hung_reaped': self._workers_hung_reaped,
                'shm_crc_failures': self._shm_crc_failures,
                'shm_breaker': self._shm_breaker.as_dict(),
                # ------------------------- zero-copy data plane observability
                'shm_enabled': self._ring is not None,
                'shm_batches': self._shm_batches,
                'shm_fallback_batches': self._shm_fallback_batches,
                'shm_stale_drops': self._shm_stale_drops,
                'shm_bytes_mapped': self._shm_bytes_mapped,
                'zmq_result_bytes': self._zmq_result_bytes,
                'wire_batches': wire_batches,
                # bytes materialized into new host memory per delivered batch:
                # ZMQ-frame bytes copied off the wire + the serializer's receive-
                # side copies (unpickle payloads, writable column copies)
                'wire_bytes_copied': bytes_copied,
                'wire_bytes_copied_per_batch':
                    round(bytes_copied / wire_batches, 1) if wire_batches else 0.0,
                'sidecar_columns': serializer_stats.get('sidecar_columns', 0),
                'sidecar_column_names':
                    list(serializer_stats.get('sidecar_column_names', [])),
            }
        return diag
