"""Process worker pool over ZeroMQ (reference: petastorm/workers_pool/process_pool.py:114-424).

Socket topology (mirrors the reference's ASCII diagram, process_pool.py:52-74):

    main PUSH (ventilation) ──> worker PULL
    main PUB  (control)     ──> worker SUB      ('stop' broadcast)
    main PULL (results)     <── worker PUSH     (handshake / result / done / error)

Workers are spawned (never forked — fork breaks JVM/libhdfs state, reference
exec_in_new_process.py:15-17) as fresh interpreters running
``petastorm_tpu.workers.process_worker_main`` with a dill-serialized bootstrap file.
Each worker runs a parent-watchdog thread and exits if the main process dies
(reference: process_pool.py:320-327)."""

import logging
import os
import pickle
import subprocess
import sys
import tempfile
import time

from petastorm_tpu.workers import EmptyResultError, TimeoutWaitingForResultError

logger = logging.getLogger(__name__)

_WORKER_STARTUP_TIMEOUT_S = 30
#: message kinds on the results channel
MSG_STARTED, MSG_RESULT, MSG_DONE, MSG_ERROR = b'started', b'result', b'done', b'error'


class WorkerTerminationError(Exception):
    pass


class ProcessPool(object):
    """Spawned-process worker pool over a ZMQ ventilator/sink pair (reference:
    workers_pool/process_pool.py): dill-bootstrapped spawn (never fork), Arrow-IPC
    or pickle wire, orphan watchdog, exception propagation."""

    def __init__(self, workers_count, results_queue_size=50, zmq_copy_buffers=False,
                 payload_serializer=None):
        """``payload_serializer`` picks the wire format for worker results (reference:
        process_pool.py:251-270 pluggable serializers): default
        :class:`~petastorm_tpu.workers.serializers.ArrowIpcSerializer` (columnar
        zero-copy receive); pass :class:`PickleSerializer` to force plain pickle.
        ``zmq_copy_buffers=False`` (default) receives result frames without copying —
        deserialized arrays then alias ZMQ frame memory."""
        from petastorm_tpu.workers.serializers import ArrowIpcSerializer
        self._workers_count = workers_count
        self.workers_count = workers_count
        self._results_queue_size = results_queue_size
        self._zmq_copy = zmq_copy_buffers
        self._serializer = (payload_serializer if payload_serializer is not None
                            else ArrowIpcSerializer())
        self._context = None
        self._ventilator = None
        self._processes = []
        self._stopped = False
        self._in_flight_done = 0
        # Instance state, not a get_results local: a typical call returns after one
        # result, so a per-call throttle would still run the liveness probe (ventilator
        # lock + per-worker poll) once per result.
        self._next_liveness_check = 0.0

    def start(self, worker_class, worker_args=None, ventilator=None):
        import zmq
        self._context = zmq.Context()
        self._vent_socket = self._context.socket(zmq.PUSH)
        vent_port = self._vent_socket.bind_to_random_port('tcp://127.0.0.1')
        self._control_socket = self._context.socket(zmq.PUB)
        control_port = self._control_socket.bind_to_random_port('tcp://127.0.0.1')
        self._results_socket = self._context.socket(zmq.PULL)
        self._results_socket.set_hwm(self._results_queue_size)
        results_port = self._results_socket.bind_to_random_port('tcp://127.0.0.1')

        import dill
        # Spawned interpreters must resolve petastorm_tpu itself (python -m resolves it at
        # interpreter startup) AND user modules (transform fns, predicates) exactly like
        # the parent: propagate the parent's sys.path via PYTHONPATH.
        child_env = dict(os.environ)
        parent_paths = [p for p in sys.path if p]
        existing = child_env.get('PYTHONPATH')
        child_env['PYTHONPATH'] = os.pathsep.join(
            parent_paths + ([existing] if existing else []))
        bootstrap = {
            'worker_class': dill.dumps(worker_class),
            'worker_args': dill.dumps(worker_args),
            'serializer': dill.dumps(self._serializer),
            'vent_addr': 'tcp://127.0.0.1:{}'.format(vent_port),
            'control_addr': 'tcp://127.0.0.1:{}'.format(control_port),
            'results_addr': 'tcp://127.0.0.1:{}'.format(results_port),
            'parent_pid': os.getpid(),
        }
        for worker_id in range(self._workers_count):
            bootstrap['worker_id'] = worker_id
            fd, path = tempfile.mkstemp(suffix='.petastorm-tpu-worker')
            with os.fdopen(fd, 'wb') as f:
                pickle.dump(bootstrap, f)
            process = subprocess.Popen(
                [sys.executable, '-m', 'petastorm_tpu.workers.process_worker_main', path],
                env=child_env)
            self._processes.append(process)

        # Startup handshake (reference: process_pool.py:200-213).
        deadline = time.time() + _WORKER_STARTUP_TIMEOUT_S
        started = 0
        poller = zmq.Poller()
        poller.register(self._results_socket, zmq.POLLIN)
        while started < self._workers_count:
            if time.time() > deadline:
                self.stop()
                raise WorkerTerminationError(
                    'Only {} of {} workers started within {}s'
                    .format(started, self._workers_count, _WORKER_STARTUP_TIMEOUT_S))
            if poller.poll(200):
                kind, _ = self._recv()
                if kind == MSG_STARTED:
                    started += 1

        if ventilator is not None:
            self._ventilator = ventilator
            self._ventilator.start()

    def _recv(self):
        parts = self._results_socket.recv_multipart(copy=self._zmq_copy)
        if not self._zmq_copy:
            parts = [p.buffer for p in parts]  # memoryviews over frame memory, no copy
        kind = bytes(memoryview(parts[0]))
        payload = parts[1:] if len(parts) > 1 else None
        return kind, payload

    def ventilate(self, **kwargs):
        import zmq
        if self._stopped:
            raise WorkerTerminationError('Pool is stopped')
        # Non-blocking with retries so a dead pool raises instead of hanging
        # (reference: process_pool.py:215-224).
        deadline = time.time() + 60
        while True:
            try:
                # dill, not pickle: ventilated items carry user callables (lambda
                # predicates, per-item transform state) that plain pickle rejects —
                # the same reason the worker bootstrap ships via dill.
                import dill
                self._vent_socket.send(dill.dumps(kwargs), flags=zmq.NOBLOCK)
                return
            except zmq.Again:
                if self._stopped or time.time() > deadline:
                    raise WorkerTerminationError('Could not ventilate: workers not '
                                                 'consuming (stopped or dead)')
                if any(p.poll() is not None for p in self._processes):
                    raise WorkerTerminationError('A worker process died unexpectedly')
                time.sleep(0.05)

    def get_results(self, timeout=None):
        import zmq
        poller = zmq.Poller()
        poller.register(self._results_socket, zmq.POLLIN)
        deadline = None if timeout is None else time.time() + timeout
        while True:
            # Liveness on the hot path too — not only when results stop: with several
            # workers, survivors keep producing after one dies, but the dead worker's
            # in-flight items are gone, so continuing would silently drop rowgroups.
            # A dead worker while more results are expected is a loud failure
            # (reference failure-detection contract, SURVEY.md §5.3). Throttled to
            # ~10Hz (detection latency is bounded by the 100ms poller timeout anyway)
            # and skipped once the ventilator reports completion — a worker dying
            # AFTER all work finished must not turn a successful read into an error.
            # ventilator.completed() acquires the ventilator lock (shared with the
            # backpressure condition), so it is only evaluated inside this throttled
            # window and on poll timeout — never per-result on the hot path.
            now = time.time()
            if not self._stopped and now >= self._next_liveness_check:
                self._next_liveness_check = now + 0.1
                all_work_done = (self._ventilator is not None
                                 and self._ventilator.completed())
                if (not all_work_done
                        and any(p.poll() is not None for p in self._processes)):
                    self.stop()
                    raise WorkerTerminationError('A worker process exited while '
                                                 'results were still expected')
            if not poller.poll(100):
                if self._ventilator is not None and getattr(self._ventilator, 'error', None):
                    self.stop()
                    raise self._ventilator.error
                if self._ventilator is not None and self._ventilator.completed():
                    raise EmptyResultError()
                if deadline is not None and time.time() > deadline:
                    raise TimeoutWaitingForResultError()
                continue
            kind, payload = self._recv()
            if kind == MSG_DONE:
                if self._ventilator is not None:
                    self._ventilator.processed_item()
                continue
            if kind == MSG_ERROR:
                exc, tb = pickle.loads(bytes(memoryview(payload[0])))
                logger.error('Worker failure re-raised in consumer:\n%s', tb)
                self.stop()
                raise exc
            if kind == MSG_RESULT:
                return self._serializer.deserialize(payload)
            if kind == MSG_STARTED:  # late joiner after restart — ignore
                continue

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        if self._ventilator is not None:
            self._ventilator.stop()
        try:
            self._control_socket.send(b'stop')
        except Exception:
            pass

    def join(self):
        deadline = time.time() + 10
        for process in self._processes:
            remaining = max(0.1, deadline - time.time())
            try:
                process.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                process.kill()
        if self._context is not None:
            for sock in (self._vent_socket, self._control_socket, self._results_socket):
                sock.close(linger=0)
            self._context.term()
            self._context = None

    @property
    def diagnostics(self):
        return {'workers_alive': sum(1 for p in self._processes if p.poll() is None)}
