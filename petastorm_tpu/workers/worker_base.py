"""Pool-agnostic worker contract (reference: petastorm/workers_pool/worker_base.py:18-35)."""


class WorkerBase(object):
    """A worker instance owned by one pool slot. ``publish_func`` delivers a result object
    to the pool's results channel; ``args`` is the worker-class-specific setup tuple."""

    def __init__(self, worker_id, publish_func, args):
        self.worker_id = worker_id
        self.publish_func = publish_func
        self.args = args

    def process(self, **kwargs):
        """Process one ventilated work item. Must call ``self.publish_func`` zero or more
        times with result payloads."""
        raise NotImplementedError()

    def shutdown(self):
        """Called once when the pool stops; release per-worker resources."""
