"""The closed-loop controller: telemetry in, knob turns out (docs/autotuning.md).

Control loop (one :meth:`AutotuneController.step` per policy window):

1. **Sample** — read the cumulative goodput metric (reader rows consumed /
   service items served) and a telemetry snapshot; the per-window deltas give
   rows/s and the window's stage histograms.
2. **Interlock** — if any circuit breaker is *open*
   (:class:`~petastorm_tpu.resilience.BreakerBoard`), revert the pending
   proposal (if one is held) and **freeze**: a pipeline routing around a broken
   dependency is not a pipeline to optimize. Unfreeze only after every breaker
   closed plus a cooldown.
3. **Evaluate** — if a proposal is being held, compare the window's rate to the
   proposal's baseline: commit when the relative gain clears the policy's
   hysteresis gate, else revert and put the knob on cooldown.
4. **Propose** — otherwise run
   :func:`~petastorm_tpu.telemetry.analyze.attribute_bottleneck` on the window
   delta, map the top leaf stage to an eligible knob
   (:class:`~petastorm_tpu.autotune.knobs.KnobCatalog` stage sets), and move it
   one step in the remembered direction (hill climbing: a reverted direction is
   retried the other way; a commit keeps climbing). **One knob at a time** —
   there is never more than one uncommitted change in flight, so every measured
   delta is attributable.

Every decision (propose/commit/revert/freeze/unfreeze) is appended to a bounded
in-memory log (``report()``), emitted as an ``autotune_decision`` record through
the :class:`~petastorm_tpu.telemetry.export.JsonlEventLogger` when one is
configured, and stamped on the flight-recorder timeline as an
``autotune_decision`` trace instant — runs are auditable after the fact.

The clock is injectable and :meth:`step` is public, so the whole state machine
is unit-testable with scripted snapshots and no threads; ``start()`` wraps it
in a daemon sampling thread for production use, and ``maybe_step()`` lets a
host event loop (the service dispatcher pump) drive it without a thread.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional

from petastorm_tpu.autotune.knobs import Knob, KnobCatalog
from petastorm_tpu.autotune.policy import AutotunePolicy
from petastorm_tpu.telemetry import tracing as _tracing
from petastorm_tpu.telemetry.export import JsonlEventLogger, logger_from_env
from petastorm_tpu.telemetry.registry import SECONDS_UNIT

#: decision actions the controller can record (docs/autotuning.md JSONL schema)
DECISION_ACTIONS = ('propose', 'commit', 'revert', 'freeze', 'unfreeze')

Snapshot = Dict[str, Any]
Decision = Dict[str, Any]
ChooseFn = Callable[[Snapshot, Snapshot, float, List[Knob]], Optional[str]]


def snapshot_delta(prev: Snapshot, cur: Snapshot) -> Snapshot:
    """Per-window telemetry delta: cumulative histogram/counter snapshots in,
    the window's own increments out (gauges pass through as current values).
    The result is a valid :func:`attribute_bottleneck` input."""
    histograms: Dict[str, Any] = {}
    prev_hists = prev.get('histograms') or {}
    for name, hist in (cur.get('histograms') or {}).items():
        before = prev_hists.get(name) or {}
        count = int(hist.get('count', 0)) - int(before.get('count', 0))
        total = float(hist.get('sum', 0.0)) - float(before.get('sum', 0.0))
        if count > 0 and total > 0:
            # the unit default must match attribute_bottleneck's (a missing
            # unit means a latency stage there too)
            histograms[name] = {'unit': hist.get('unit', SECONDS_UNIT),
                                'count': count, 'sum': total,
                                'max': hist.get('max', 0.0)}
    counters: Dict[str, int] = {}
    prev_counters = prev.get('counters') or {}
    for name, value in (cur.get('counters') or {}).items():
        delta = int(value) - int(prev_counters.get(name, 0))
        if delta > 0:
            counters[name] = delta
    return {'histograms': histograms, 'counters': counters,
            'gauges': dict(cur.get('gauges') or {})}


def choose_from_bottleneck(prev: Snapshot, cur: Snapshot, rate: float,
                           eligible: List[Knob]) -> Optional[str]:
    """The default knob chooser: rank the window's leaf stages with
    :func:`~petastorm_tpu.telemetry.analyze.attribute_bottleneck` and return
    the first eligible knob claiming the highest-ranked stage (falling down
    the ranking when the top stage has no live knob)."""
    from petastorm_tpu.telemetry.analyze import attribute_bottleneck
    report = attribute_bottleneck(snapshot_delta(prev, cur))
    by_stage: Dict[str, str] = {}
    for knob in eligible:
        for stage in knob.stages:
            by_stage.setdefault(stage, knob.knob_id)
    for entry in report.get('ranked', []):
        knob_id = by_stage.get(entry['stage'])
        if knob_id is not None:
            return knob_id
    return None


def default_breaker_snapshot() -> Dict[str, Dict[str, Any]]:
    """The default safety-interlock source: the process-wide breaker board's
    tripped set (cache / filesystem / service-transport breakers)."""
    from petastorm_tpu.resilience import default_board
    return default_board().snapshot(only_tripped=True)


class _Pending(object):
    """The one in-flight proposal (one-knob-at-a-time invariant)."""

    __slots__ = ('knob_id', 'old_value', 'new_value', 'baseline_rate',
                 'hold_left', 'direction')

    def __init__(self, knob_id: str, old_value: float, new_value: float,
                 baseline_rate: float, hold_left: int, direction: int) -> None:
        self.knob_id = knob_id
        self.old_value = old_value
        self.new_value = new_value
        self.baseline_rate = baseline_rate
        self.hold_left = hold_left
        self.direction = direction


class AutotuneController(object):
    """Hill-climbing knob controller over a :class:`KnobCatalog` (module doc).

    :param catalog: the knobs this controller may turn.
    :param metric_fn: cumulative goodput counter (monotone; rows consumed /
        items served) — window deltas over the injected clock give the rate.
    :param snapshot_fn: cumulative telemetry snapshot source (e.g.
        ``Reader.telemetry_snapshot``); None = empty snapshots (a chooser that
        does not need telemetry, like the service's, still works).
    :param policy: an :class:`AutotunePolicy` (default: defaults).
    :param breaker_snapshot_fn: the safety interlock's breaker view
        (``{name: breaker_dict}``); any entry with ``state == 'open'`` freezes
        the controller. Default: the process breaker board's tripped set.
    :param choose_fn: ``(prev_snapshot, snapshot, rate, eligible_knobs) ->
        knob_id or None``; default :func:`choose_from_bottleneck`.
    :param clock: injectable monotone clock (tests drive the loop
        deterministically).
    :param event_logger: a :class:`JsonlEventLogger` for the decision stream;
        default: ``PETASTORM_TPU_TELEMETRY_JSONL`` when set.
    :param name: controller name stamped on every decision (``reader`` /
        ``service``).
    """

    def __init__(self, catalog: KnobCatalog,
                 metric_fn: Callable[[], float],
                 snapshot_fn: Optional[Callable[[], Snapshot]] = None,
                 policy: Optional[AutotunePolicy] = None,
                 breaker_snapshot_fn: Optional[
                     Callable[[], Dict[str, Dict[str, Any]]]] = None,
                 choose_fn: Optional[ChooseFn] = None,
                 clock: Callable[[], float] = time.monotonic,
                 event_logger: Optional[JsonlEventLogger] = None,
                 name: str = 'reader') -> None:
        self.catalog = catalog
        self.policy = policy if policy is not None else AutotunePolicy()
        self._metric_fn = metric_fn
        self._snapshot_fn = snapshot_fn
        self._breaker_snapshot_fn = (breaker_snapshot_fn
                                     if breaker_snapshot_fn is not None
                                     else default_breaker_snapshot)
        self._choose_fn: ChooseFn = (choose_fn if choose_fn is not None
                                     else choose_from_bottleneck)
        self._clock = clock
        self._events = (event_logger if event_logger is not None
                        else logger_from_env())
        self._name = name
        self._lock = threading.Lock()
        self._last_time: Optional[float] = None
        self._last_metric = 0.0
        self._prev_snapshot: Snapshot = {}
        self._windows = 0
        self._warmup_left = self.policy.warmup_windows
        self._pending: Optional[_Pending] = None
        self._cooldowns: Dict[str, int] = {}
        self._last_direction: Dict[str, int] = {}
        self._frozen = False
        self._freeze_left = 0
        self._decisions: Deque[Decision] = collections.deque(
            maxlen=self.policy.max_decisions)
        self._committed = 0
        self._reverted = 0
        self._freezes = 0
        self._last_rate = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop_event = threading.Event()
        self._next_step = 0.0
        # decisions made under the lock, emitted (JSONL/trace I/O) after it
        # releases — see step()
        self._pending_emits: List[Decision] = []
        # cumulative wall seconds spent inside step() (sampling, attribution,
        # knob turns, decision emission) — the controller's own cost, surfaced
        # by report() so overhead is measured, not guessed (bench guard)
        self._step_seconds = 0.0

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Run :meth:`step` every ``policy.window_s`` on a daemon thread."""
        if self._thread is not None:
            raise RuntimeError('AutotuneController already started')
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name='petastorm-tpu-autotune')
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_event.wait(self.policy.window_s):
            try:
                self.step()
            except Exception:  # noqa: BLE001 - the tuner must never kill the read it tunes
                import logging
                logging.getLogger(__name__).exception(
                    'autotune step failed; controller keeps sampling')

    def stop(self) -> None:
        """Stop the sampling thread and run every knob's ``restore`` hook
        (knobs that actuate through process-global state — the decode-threads
        env contract — undo their turns so the next reader in this process
        starts from the pre-tuning defaults). Idempotent; never blocks long."""
        self._stop_event.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
            self._thread = None
        for knob in self.catalog.knobs():
            if knob.restore is not None:
                try:
                    knob.restore()
                except Exception:  # noqa: BLE001 - teardown must never raise out of stop()
                    pass

    def warm_start(self, knob_values: Dict[str, float]) -> Dict[str, Any]:
        """Seed the catalog's knobs from a prior run's recorded values
        (``AutotunePolicy(warm_start=True)`` — the knob dict of a
        longitudinal run record, telemetry/history.py). Each known knob is
        clamped into its declared bounds and applied; unknown ids (a record
        from a differently-shaped run) are skipped. Every seed lands in the
        decision log as a ``warm_start`` action, so the report shows where
        this run's starting point came from. Returns ``{knob_id: {'from',
        'to'}}`` for the knobs that actually moved."""
        applied: Dict[str, Any] = {}
        with self._lock:
            for knob_id in sorted(knob_values):
                if knob_id not in self.catalog:
                    continue
                knob = self.catalog.knob(knob_id)
                try:
                    old = float(knob.get())
                    target = knob.clamp(float(knob_values[knob_id]))
                    if target == old:
                        continue
                    new = float(knob.apply(target))
                except Exception:  # noqa: BLE001 - a dead knob target must not kill the seeding of the rest
                    import logging
                    logging.getLogger(__name__).debug(
                        'warm start: knob %s failed to apply', knob_id,
                        exc_info=True)
                    continue
                if new == old:
                    continue  # pinned knob: apply() refused the turn
                applied[knob_id] = {'from': old, 'to': new}
                self._record('warm_start', knob_id=knob_id, from_value=old,
                             to_value=new, reason='seeded from run history')
            to_emit = self._pending_emits
            self._pending_emits = []
        for recorded in to_emit:
            self._emit(recorded)
        return applied

    def maybe_step(self) -> Optional[Decision]:
        """Window-gated :meth:`step` for host event loops (the dispatcher pump
        calls this per tick): runs at most once per ``policy.window_s``."""
        now = self._clock()
        if now < self._next_step:
            return None
        self._next_step = now + self.policy.window_s
        return self.step()

    # ------------------------------------------------------------- the loop

    def step(self) -> Optional[Decision]:
        """One control-loop window (module doc); returns the decision made in
        this window, or None (sampling/holding windows make no decision).

        Decision records are built under the controller lock but EMITTED
        (JSONL append, trace instant — blocking I/O) after it releases: a
        slow disk behind the event log must not stall ``report()`` readers
        or, on the service, the dispatch loop driving ``maybe_step()``."""
        started = time.perf_counter()
        try:
            with self._lock:
                decision = self._step_locked()
                to_emit = self._pending_emits
                self._pending_emits = []
            for recorded in to_emit:
                self._emit(recorded)
            return decision
        finally:
            # plain float add: step() is serialized by its own lock for every
            # real caller (one sampling thread / one pump), and a torn read in
            # report() would still be a valid recent value
            self._step_seconds += time.perf_counter() - started

    def _step_locked(self) -> Optional[Decision]:
        now = self._clock()
        metric = float(self._metric_fn())
        snapshot: Snapshot = self._snapshot_fn() if self._snapshot_fn else {}
        if self._last_time is None:
            self._last_time = now
            self._last_metric = metric
            self._prev_snapshot = snapshot
            return None
        dt = now - self._last_time
        if dt <= 0:
            return None
        rate = max(0.0, (metric - self._last_metric) / dt)
        self._windows += 1
        self._last_time = now
        self._last_metric = metric
        prev_snapshot = self._prev_snapshot
        self._prev_snapshot = snapshot
        self._last_rate = rate
        # a knob cooling at the START of this window stays barred for it, so a
        # cooldown of N bars exactly N windows after the revert that set it
        cooling = frozenset(self._cooldowns)
        for knob_id in list(self._cooldowns):
            self._cooldowns[knob_id] -= 1
            if self._cooldowns[knob_id] <= 0:
                del self._cooldowns[knob_id]
        open_breakers = sorted(
            name for name, state in (self._breaker_snapshot_fn() or {}).items()
            if state.get('state') == 'open')
        if open_breakers:
            return self._interlock(open_breakers, rate)
        if self._frozen:
            self._freeze_left -= 1
            if self._freeze_left > 0:
                return None
            self._frozen = False
            return self._record('unfreeze', rate=rate,
                                reason='all breakers closed')
        if self._warmup_left > 0:
            self._warmup_left -= 1
            return None
        if self._pending is not None:
            return self._evaluate_pending(rate)
        return self._propose(prev_snapshot, snapshot, rate, cooling)

    def _interlock(self, open_breakers: List[str],
                   rate: float) -> Optional[Decision]:
        """Breaker safety interlock: revert any held change, freeze until the
        board is healthy again (plus the policy's re-entry cooldown)."""
        decision: Optional[Decision] = None
        if self._pending is not None:
            decision = self._revert_pending(
                rate, reason='breaker open: {}'.format(','.join(open_breakers)))
        if not self._frozen:
            self._frozen = True
            self._freezes += 1
            decision = self._record(
                'freeze', rate=rate,
                reason='open breaker(s): {}'.format(','.join(open_breakers)))
        self._freeze_left = max(self.policy.freeze_cooldown_windows, 1)
        return decision

    def _evaluate_pending(self, rate: float) -> Optional[Decision]:
        pending = self._pending
        assert pending is not None
        if pending.hold_left > 0:
            pending.hold_left -= 1
            return None
        gate = pending.baseline_rate * (1.0 + self.policy.min_improvement)
        # rate > 0 guards the degenerate gate: a 0 rows/s baseline (consumer
        # paused mid-window) makes gate 0.0, and committing a change judged
        # against a window that measured no progress would teach the climb a
        # direction nothing validated. 0 -> positive still commits (a change
        # that unstuck a stalled pipeline is the realest improvement there is).
        if rate > 0 and rate >= gate:
            self._pending = None
            self._last_direction[pending.knob_id] = pending.direction
            self._committed += 1
            return self._record(
                'commit', knob_id=pending.knob_id,
                from_value=pending.old_value, to_value=pending.new_value,
                rate=rate, baseline=pending.baseline_rate,
                reason='rate {:.1f} cleared gate {:.1f}'.format(rate, gate))
        return self._revert_pending(
            rate, reason='rate {:.1f} below gate {:.1f}'.format(rate, gate))

    def _revert_pending(self, rate: float, reason: str) -> Decision:
        pending = self._pending
        assert pending is not None
        self._pending = None
        restored = True
        try:
            pending_knob = self.catalog.knob(pending.knob_id)
            pending_knob.apply(pending.old_value)
        except Exception:  # noqa: BLE001 - a dead target must not wedge the loop; the decision records the attempt
            restored = False
        self._cooldowns[pending.knob_id] = self.policy.cooldown_windows
        # hill climbing: a failed direction flips the next try for this knob
        self._last_direction[pending.knob_id] = -pending.direction
        self._reverted += 1
        # the audit must state the LIVE value: a failed restore leaves the
        # knob at the proposed value, and a decision claiming otherwise would
        # send an operator reading the JSONL stream after the wrong state
        return self._record(
            'revert', knob_id=pending.knob_id,
            from_value=pending.new_value,
            to_value=pending.old_value if restored else pending.new_value,
            rate=rate, baseline=pending.baseline_rate,
            reason=reason if restored else
            reason + ' (restore FAILED: knob target dead; live value unchanged)')

    def _propose(self, prev_snapshot: Snapshot, snapshot: Snapshot,
                 rate: float,
                 cooling: frozenset = frozenset()) -> Optional[Decision]:
        allowed = self.policy.knob_ids
        eligible = [
            knob for knob in self.catalog.knobs()
            if knob.cost != 'deferred'
            and knob.knob_id not in cooling
            and knob.knob_id not in self._cooldowns
            and (allowed is None or knob.knob_id in allowed)]
        if not eligible:
            return None
        knob_id = self._choose_fn(prev_snapshot, snapshot, rate, eligible)
        if knob_id is None or not any(k.knob_id == knob_id for k in eligible):
            return None
        knob = self.catalog.knob(knob_id)
        old = float(knob.get())
        direction = self._last_direction.get(knob_id, 1)
        target = knob.clamp(old + direction * knob.step)
        if target == old:
            direction = -direction
            target = knob.clamp(old + direction * knob.step)
        if target == old:
            # pinned at both bounds (min == max): nothing to turn
            self._cooldowns[knob_id] = self.policy.cooldown_windows
            return None
        applied = float(knob.apply(target))
        if applied == old:
            # the mutator refused the move (stopped pool, clamped away)
            self._cooldowns[knob_id] = self.policy.cooldown_windows
            return None
        self._pending = _Pending(knob_id, old, applied, rate,
                                 self.policy.hold_windows, direction)
        return self._record(
            'propose', knob_id=knob_id, from_value=old, to_value=applied,
            rate=rate,
            reason='bottleneck stage maps to {} (direction {:+d})'
            .format(knob_id, direction))

    # ------------------------------------------------------------- reporting

    def _record(self, action: str, knob_id: Optional[str] = None,
                from_value: Optional[float] = None,
                to_value: Optional[float] = None,
                rate: float = 0.0, baseline: Optional[float] = None,
                reason: str = '') -> Decision:
        decision: Decision = {
            'window': self._windows, 'controller': self._name,
            'action': action, 'knob': knob_id,
            'from': from_value, 'to': to_value,
            'rate_rows_per_sec': round(rate, 3), 'reason': reason}
        if baseline is not None:
            decision['baseline_rows_per_sec'] = round(baseline, 3)
        self._decisions.append(decision)
        self._pending_emits.append(decision)
        return decision

    def _emit(self, decision: Decision) -> None:
        """Emit one recorded decision to the JSONL log and the flight
        recorder. Called lock-free from step() (both sinks are independently
        thread-safe); an interlock window can emit two (revert + freeze)."""
        if self._events is not None:
            self._events.emit({}, event='autotune_decision', **decision)
        if _tracing.trace_enabled():
            _tracing.trace_instant('autotune_decision',
                                   args={k: v for k, v in decision.items()
                                         if v is not None})

    @property
    def frozen(self) -> bool:
        """True while the breaker interlock holds the controller frozen."""
        with self._lock:
            return self._frozen

    def report(self) -> Dict[str, Any]:
        """JSON-safe controller state: window/decision counts, the
        frozen-by-breaker flag, current knob values/bounds, and the bounded
        decision log (``Reader.autotune_report()`` / doctor surface this)."""
        with self._lock:
            pending = self._pending
            return {
                'enabled': True,
                'controller': self._name,
                'windows': self._windows,
                'frozen_by_breaker': self._frozen,
                'committed': self._committed,
                'reverted': self._reverted,
                'freezes': self._freezes,
                'pending_knob': pending.knob_id if pending else None,
                'last_rate_rows_per_sec': round(self._last_rate, 3),
                'controller_step_seconds': round(self._step_seconds, 6),
                'knobs': self.catalog.as_dicts(),
                'decisions': list(self._decisions),
            }


def setup_reader_autotune(reader: Any,
                          policy: AutotunePolicy) -> AutotuneController:
    """Build (without starting) the reader-side controller: live knobs from
    :func:`~petastorm_tpu.autotune.knobs.build_reader_knobs`, goodput from the
    reader's delivered-row counter, telemetry from
    ``Reader.telemetry_snapshot``, and a breaker interlock spanning the
    process board plus the pool's shm breaker."""
    from petastorm_tpu.autotune.knobs import build_reader_knobs
    catalog = KnobCatalog(build_reader_knobs(reader))

    def breakers() -> Dict[str, Dict[str, Any]]:
        tripped = dict(default_breaker_snapshot())
        shm_breaker = getattr(getattr(reader, '_pool', None),
                              '_shm_breaker', None)
        if shm_breaker is not None:
            state = shm_breaker.as_dict()
            if state.get('state') != 'closed' or state.get('failures'):
                tripped['shm_transport'] = state
        return tripped

    return AutotuneController(
        catalog,
        metric_fn=lambda: float(reader.rows_consumed),
        snapshot_fn=reader.telemetry_snapshot,
        policy=policy,
        breaker_snapshot_fn=breakers,
        name='reader')
