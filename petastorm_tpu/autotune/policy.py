"""Autotune policy: the constants of the closed control loop (docs/autotuning.md).

One frozen dataclass holds every pacing/hysteresis parameter the
:class:`~petastorm_tpu.autotune.controller.AutotuneController` consults, so a
policy can be passed through ``make_reader(autotune=AutotunePolicy(...))``,
logged verbatim into the decision stream, and compared across runs. The
defaults are deliberately conservative — the controller must never oscillate a
healthy pipeline: a 2s sampling window, one hold window per proposal, a 2%
relative-improvement hysteresis gate before any commit, and a multi-window
cooldown after every revert (the tf.data AUTOTUNE stance of changing one thing
at a time and measuring, arXiv 2101.12127).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple, Union


@dataclass(frozen=True)
class AutotunePolicy:
    """Pacing and hysteresis of the closed-loop autotuner (docs/autotuning.md).

    :param window_s: telemetry sampling window — the controller wakes, samples
        rows/s and the stage histograms, and takes at most one action per
        window.
    :param warmup_windows: windows ignored after start (cold caches, pool
        spin-up) before the first proposal may fire.
    :param hold_windows: windows a proposed knob change is held before its
        rows/s effect is measured (lets in-flight work drain through the new
        setting).
    :param min_improvement: hysteresis gate — the relative rows/s gain a held
        proposal must show to be committed; anything less reverts. Prevents
        noise-chasing oscillation.
    :param cooldown_windows: windows a knob is barred from new proposals after
        a revert (or a bound pin) — the anti-oscillation half of hysteresis.
    :param freeze_cooldown_windows: windows the controller stays frozen after
        every circuit breaker has closed again (the safety interlock's
        re-entry delay).
    :param max_decisions: bound of the in-memory decision log surfaced by
        ``Reader.autotune_report()`` (every decision also goes to the JSONL
        event log when one is configured).
    :param knob_ids: explicit allowlist of knob ids the controller may turn;
        ``None`` = every live knob in the catalog. An empty tuple yields a
        measure-only controller (samples and reports, never actuates) — what
        the bench overhead guard runs.
    :param warm_start: seed the knobs from the newest same-dataset,
        same-platform run record in the longitudinal history store before
        the first window, so a retuned run starts from last run's converged
        values instead of re-climbing from the defaults
        (docs/observability.md "Longitudinal observatory"). Requires
        ``history`` to be armed on the owner; gated off silently when the
        store holds no comparable record.
    """

    window_s: float = 2.0
    warmup_windows: int = 2
    hold_windows: int = 1
    min_improvement: float = 0.02
    cooldown_windows: int = 3
    freeze_cooldown_windows: int = 2
    max_decisions: int = 64
    knob_ids: Optional[Tuple[str, ...]] = None
    warm_start: bool = False

    def __post_init__(self) -> None:
        if self.window_s <= 0:
            raise ValueError('window_s must be > 0, got {!r}'.format(self.window_s))
        if self.warmup_windows < 0 or self.hold_windows < 0:
            raise ValueError('warmup_windows/hold_windows must be >= 0')
        if self.min_improvement < 0:
            raise ValueError('min_improvement must be >= 0, got {!r}'
                             .format(self.min_improvement))
        if self.cooldown_windows < 1 or self.freeze_cooldown_windows < 0:
            raise ValueError('cooldown_windows must be >= 1 and '
                             'freeze_cooldown_windows >= 0')
        if self.max_decisions < 1:
            raise ValueError('max_decisions must be >= 1')


def resolve_policy(
        autotune: Union[bool, None, AutotunePolicy]) -> Optional[AutotunePolicy]:
    """The ONE normalization of the ``autotune`` reader argument: ``None``/
    ``False`` mean off (no controller object is ever built — the disabled path
    stays byte-identical to the seed), ``True`` means the default policy, and
    an :class:`AutotunePolicy` passes through."""
    if autotune is None or autotune is False:
        return None
    if autotune is True:
        return AutotunePolicy()
    if isinstance(autotune, AutotunePolicy):
        return autotune
    raise ValueError('autotune must be True/False/None or an AutotunePolicy, '
                     'got {!r}'.format(autotune))
