"""Closed-loop autotuner: telemetry-driven online retuning of pipeline knobs.

The subsystem that closes the loop PR 3 opened: ``attribute_bottleneck``
already names the knob that moves the dominant stage — this package turns it,
live, mid-epoch (docs/autotuning.md; the tf.data AUTOTUNE model,
arXiv 2101.12127):

- :mod:`~petastorm_tpu.autotune.knobs` — the typed knob actuation layer
  (:class:`Knob`/:class:`KnobCatalog`, the declared ``KNOB_IDS`` catalog, and
  builders that wire knobs into live readers/loaders/service schedulers);
- :mod:`~petastorm_tpu.autotune.policy` — :class:`AutotunePolicy`, the pacing
  and hysteresis constants;
- :mod:`~petastorm_tpu.autotune.controller` — the hill-climbing
  :class:`AutotuneController` (propose -> hold -> measure -> commit/revert,
  breaker-board safety interlock, JSONL + flight-recorder decision audit).

Enable per reader with ``make_reader(..., autotune=True)`` (or an
:class:`AutotunePolicy`); inspect with ``Reader.autotune_report()`` /
``diagnostics['autotune']``. The service dispatcher reuses the same controller
core for its admission windows (``Dispatcher(autotune=...)``). Off by default:
with ``autotune`` unset no controller is built and no knob is ever touched.
"""

from petastorm_tpu.autotune.controller import (AutotuneController,
                                               choose_from_bottleneck,
                                               setup_reader_autotune,
                                               snapshot_delta)
from petastorm_tpu.autotune.knobs import (KNOB_IDS, Knob, KnobCatalog,
                                          build_loader_knobs,
                                          build_reader_knobs,
                                          build_service_knobs)
from petastorm_tpu.autotune.policy import AutotunePolicy, resolve_policy

__all__ = ['AutotuneController', 'AutotunePolicy', 'KNOB_IDS', 'Knob',
           'KnobCatalog', 'build_loader_knobs', 'build_reader_knobs',
           'build_service_knobs', 'choose_from_bottleneck', 'resolve_policy',
           'setup_reader_autotune', 'snapshot_delta']
