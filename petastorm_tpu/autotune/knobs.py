"""The knob actuation layer: typed runtime-adjustable pipeline knobs.

A :class:`Knob` names one runtime-adjustable throughput parameter — bounds,
step, actuation cost, the telemetry stages it moves — and wires ``get``/
``apply`` callables into the LIVE pipeline objects (ventilator in-flight
window, thread-pool worker count, decode thread pool, shm ring shape, cache
mode, loader shuffle-buffer fill threshold, service admission windows). The
:class:`KnobCatalog` is the typed registry the
:class:`~petastorm_tpu.autotune.controller.AutotuneController` hill-climbs
over, and ``KNOB_IDS`` is the declared id catalog pipecheck's telemetry-names
rule checks knob references against (docs/static-analysis.md) — a typo'd knob
id fails the tier-1 self-check instead of silently naming a knob nobody turns.

Builders (``build_reader_knobs`` / ``build_loader_knobs`` /
``build_service_knobs``) introspect live objects by duck-typing the ``set_*``
mutators grown for this subsystem, so a pool or cache without the mutator
simply contributes no knob (docs/autotuning.md has the full knob table).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

#: declared knob ids — the catalog every ``Knob(...)``/``catalog.knob(...)``
#: literal must draw from (pipecheck telemetry-names rule,
#: docs/static-analysis.md). Keep in sync with the docs/autotuning.md table.
KNOB_IDS: Tuple[str, ...] = (
    'ventilator_max_in_flight',   # reader: bounded in-flight rowgroup window
    'pool_workers',               # thread pool: elastic grow/park worker count
    'decode_threads',             # codec decode fan-out (PETASTORM_TPU_DECODE_THREADS)
    'shm_slots_per_worker',       # process pool: ring slots (next generation)
    'shm_slot_bytes',             # process pool: ring slot size (next generation)
    'cache_writable_hits',        # arrow-ipc cache: writable vs zero-copy hits
    'cache_bypass',               # disk cache: direct-fill bypass mode
    'loader_min_after_retrieve',  # loader shuffle-buffer fill threshold
    'loader_prefetch',            # loader: host-batch prefetch queue depth
    'loader_device_buffer',       # loader: device decode-tail ring depth
    'service_admission_window',   # dispatcher: per-client admission cap
    'service_client_window',      # dispatcher: live per-client in-flight depth
    'schedule_interleave',        # cost-aware heavy/light ventilation interleave
    'storage_fetch_window',       # storage engine: parallel range-GET window
                                  # (PETASTORM_TPU_STORAGE_FETCH_WINDOW)
)

#: actuation costs: ``cheap`` knobs act instantly, ``moderate`` knobs take a
#: little while to show (spawned threads, env-driven pools), ``deferred``
#: knobs only take effect on the next generation of their object (shm ring) —
#: the controller never hill-climbs a deferred knob (it could not measure it)
KNOB_COSTS: Tuple[str, ...] = ('cheap', 'moderate', 'deferred')


@dataclass
class Knob:
    """One runtime-adjustable pipeline knob (docs/autotuning.md knob table).

    ``get``/``apply`` thread into the live object: ``apply`` receives the
    proposed value and returns the value actually applied (mutators clamp), so
    the controller can detect a pinned knob by ``apply(v) == get-before``.
    ``stages`` names the telemetry stages this knob moves — the bottleneck
    report's top stage selects the knob through this mapping. ``restore``
    (optional) is run by ``AutotuneController.stop()``: a knob that actuates
    through process-global state (the decode-threads env contract) declares
    there how to undo its turns when the tuned reader goes away."""

    knob_id: str
    description: str
    minimum: float
    maximum: float
    step: float
    cost: str
    stages: Tuple[str, ...]
    get: Callable[[], float]
    apply: Callable[[float], float]
    unit: str = ''
    restore: Optional[Callable[[], None]] = None

    def __post_init__(self) -> None:
        if self.knob_id not in KNOB_IDS:
            raise ValueError('unknown knob id {!r} (declared: {})'
                             .format(self.knob_id, KNOB_IDS))
        if self.cost not in KNOB_COSTS:
            raise ValueError('unknown knob cost {!r} (declared: {})'
                             .format(self.cost, KNOB_COSTS))
        if self.minimum > self.maximum:
            raise ValueError('knob {}: minimum {} > maximum {}'
                             .format(self.knob_id, self.minimum, self.maximum))
        if self.step <= 0:
            raise ValueError('knob {}: step must be > 0'.format(self.knob_id))

    def clamp(self, value: float) -> float:
        """Clamp ``value`` into the knob's declared bounds."""
        return max(self.minimum, min(self.maximum, value))

    def as_dict(self) -> Dict[str, Any]:
        """JSON-safe view (current value + static shape) for reports."""
        try:
            value: Optional[float] = float(self.get())
        except Exception:  # noqa: BLE001 - a dead target must not kill the report
            value = None
        return {'value': value, 'min': self.minimum, 'max': self.maximum,
                'step': self.step, 'cost': self.cost, 'unit': self.unit,
                'stages': list(self.stages),
                'description': self.description}


class KnobCatalog:
    """Thread-safe registry of :class:`Knob` instances, keyed by knob id.

    The controller iterates it to find the knob a bottleneck stage maps to;
    loaders/adapters may :meth:`add` further knobs after the controller is
    already running (the JaxDataLoader registers its shuffle-buffer knob this
    way)."""

    def __init__(self, knobs: Optional[List[Knob]] = None) -> None:
        self._lock = threading.Lock()
        self._knobs: Dict[str, Knob] = {}
        for knob in knobs or []:
            self._knobs[knob.knob_id] = knob

    def add(self, knob: Knob) -> None:
        """Register ``knob``; re-adding an id replaces the previous entry."""
        with self._lock:
            self._knobs[knob.knob_id] = knob

    def knob(self, knob_id: str) -> Knob:
        """The registered knob for ``knob_id`` (KeyError when absent)."""
        with self._lock:
            return self._knobs[knob_id]

    def __contains__(self, knob_id: str) -> bool:
        with self._lock:
            return knob_id in self._knobs

    def __len__(self) -> int:
        with self._lock:
            return len(self._knobs)

    def ids(self) -> List[str]:
        """Registered knob ids, in registration order."""
        with self._lock:
            return list(self._knobs)

    def knobs(self) -> List[Knob]:
        """Snapshot of the registered knobs (safe to iterate lock-free)."""
        with self._lock:
            return list(self._knobs.values())

    def knobs_for_stage(self, stage: str) -> List[Knob]:
        """Knobs claiming ``stage`` in their declared stage set."""
        return [knob for knob in self.knobs() if stage in knob.stages]

    def as_dicts(self) -> Dict[str, Dict[str, Any]]:
        """JSON-safe ``{knob_id: knob.as_dict()}`` for reports/diagnostics."""
        return {knob.knob_id: knob.as_dict() for knob in self.knobs()}


# ---------------------------------------------------------------------------
# builders: live-object introspection -> knobs
# ---------------------------------------------------------------------------


#: the process's pre-autotune decode-threads env, captured when this module
#: first loads (any autotuner touch necessarily postdates this import). Every
#: restore returns to THIS value: capturing per reader would leak reader A's
#: tuned width through reader B's restore when their lifetimes overlap.
_PRISTINE_DECODE_THREADS_ENV: Optional[str] = os.environ.get(
    'PETASTORM_TPU_DECODE_THREADS')

#: same pristine-capture contract for the storage engine's fetch window
#: (restore returns the process to the value it imported with)
_PRISTINE_FETCH_WINDOW_ENV: Optional[str] = os.environ.get(
    'PETASTORM_TPU_STORAGE_FETCH_WINDOW')


def _set_decode_threads(value: float) -> float:
    """Apply the decode-threads knob through its env contract
    (``PETASTORM_TPU_DECODE_THREADS`` — the process-local decode pool rebuilds
    on next use; spawned process-pool workers capture the env at spawn)."""
    threads = max(1, int(value))
    os.environ['PETASTORM_TPU_DECODE_THREADS'] = str(threads)
    return float(threads)


def build_reader_knobs(reader: Any) -> List[Knob]:
    """Knobs for a live :class:`~petastorm_tpu.reader.Reader`: ventilation
    depth, pool workers (thread pool), decode threads (decoding readers), shm
    ring shape (process pool — deferred), and cache mode. Each knob is added
    only when its target object exposes the matching ``set_*`` mutator."""
    knobs: List[Knob] = []
    ventilator = getattr(reader, '_ventilator', None)
    if ventilator is not None and hasattr(ventilator, 'set_max_in_flight'):
        current = float(ventilator.max_in_flight)
        knobs.append(Knob(
            'ventilator_max_in_flight',
            'bounded in-flight rowgroup window fed to the pool',
            minimum=1.0, maximum=max(64.0, current * 8), step=2.0,
            cost='cheap', stages=('pool_wait', 'shuffle_wait'), unit='items',
            get=lambda: float(ventilator.max_in_flight),
            apply=lambda v: float(ventilator.set_max_in_flight(int(v)))))
    pool = getattr(reader, '_pool', None)
    if pool is not None and hasattr(pool, 'set_workers_count'):
        maximum = float(getattr(pool, '_max_workers_count',
                                4 * pool.workers_count))
        knobs.append(Knob(
            'pool_workers',
            'elastic thread-pool worker count (grow spawns, shrink parks)',
            minimum=1.0, maximum=maximum, step=1.0,
            cost='moderate', unit='workers',
            stages=('pool_wait', 'shuffle_wait', 'rowgroup_read', 'decode'),
            get=lambda: float(pool.workers_count),
            apply=lambda v: float(pool.set_workers_count(int(v)))))
    # Process-local knobs (decode threads, cache modes) only exist where the
    # work runs in THIS process (thread/dummy pools): process-pool workers
    # captured the env and hold their own unpickled cache copies from spawn,
    # and service decode runs on the fleet — turning a consumer-side knob
    # there would burn propose/revert cycles on a knob that moves nothing.
    from petastorm_tpu.workers.dummy_pool import DummyPool
    from petastorm_tpu.workers.thread_pool import ThreadPool
    in_process_work = isinstance(pool, (ThreadPool, DummyPool))
    if (not getattr(reader, 'is_batched_reader', False)
            and in_process_work):
        from petastorm_tpu.codecs import decode_thread_count
        # env actuation is process-global: hand the controller a restore hook
        # returning to the module-pristine value so a stopped reader cannot
        # leak its tuned width into every later reader in this process
        touched: List[bool] = []

        def _apply_decode_threads(value: float) -> float:
            touched.append(True)
            return _set_decode_threads(value)

        def _restore_decode_threads() -> None:
            if not touched:
                return
            if _PRISTINE_DECODE_THREADS_ENV is None:
                os.environ.pop('PETASTORM_TPU_DECODE_THREADS', None)
            else:
                os.environ['PETASTORM_TPU_DECODE_THREADS'] = \
                    _PRISTINE_DECODE_THREADS_ENV

        knobs.append(Knob(
            'decode_threads',
            'codec decode fan-out width (PETASTORM_TPU_DECODE_THREADS)',
            minimum=1.0, maximum=float(max(8, 2 * (os.cpu_count() or 1))),
            step=1.0, cost='moderate', stages=('decode',), unit='threads',
            get=lambda: float(decode_thread_count()),
            apply=_apply_decode_threads,
            restore=_restore_decode_threads))
    storage_policy = getattr(reader, '_storage_policy', None)
    if storage_policy is not None and in_process_work:
        # the fetch window actuates through the same env contract as decode
        # threads: storage/fetcher.py re-reads it per fetch, so a turn takes
        # effect on the next planned rowgroup (docs/performance.md
        # "Object-store ingest engine")
        from petastorm_tpu.storage.fetcher import fetch_window
        storage_touched: List[bool] = []

        def _apply_fetch_window(value: float) -> float:
            storage_touched.append(True)
            window = min(max(int(value), 1), 128)
            os.environ['PETASTORM_TPU_STORAGE_FETCH_WINDOW'] = str(window)
            return float(window)

        def _restore_fetch_window() -> None:
            if not storage_touched:
                return
            if _PRISTINE_FETCH_WINDOW_ENV is None:
                os.environ.pop('PETASTORM_TPU_STORAGE_FETCH_WINDOW', None)
            else:
                os.environ['PETASTORM_TPU_STORAGE_FETCH_WINDOW'] = \
                    _PRISTINE_FETCH_WINDOW_ENV

        knobs.append(Knob(
            'storage_fetch_window',
            'parallel range-GET window of the storage ingest engine '
            '(PETASTORM_TPU_STORAGE_FETCH_WINDOW)',
            minimum=1.0, maximum=128.0, step=2.0, cost='moderate',
            stages=('range_fetch',), unit='requests',
            get=lambda: float(fetch_window(storage_policy)),
            apply=_apply_fetch_window,
            restore=_restore_fetch_window))
    if pool is not None and hasattr(pool, 'set_shm_slot_config'):
        knobs.append(Knob(
            'shm_slots_per_worker',
            'shm ring slots per worker — applies on the next ring generation',
            minimum=1.0, maximum=32.0, step=1.0, cost='deferred',
            stages=('shm_slot_wait', 'shm_release'), unit='slots',
            get=lambda: float(pool._shm_slots_per_worker),
            apply=lambda v: float(
                pool.set_shm_slot_config(slots_per_worker=int(v))[0])))
        knobs.append(Knob(
            'shm_slot_bytes',
            'shm ring slot size — applies on the next ring generation',
            minimum=65536.0, maximum=float(256 * 1024 * 1024),
            step=float(4 * 1024 * 1024), cost='deferred',
            stages=('shm_slot_wait',), unit='bytes',
            get=lambda: float(pool._shm_slot_bytes),
            apply=lambda v: float(
                pool.set_shm_slot_config(slot_bytes=int(v))[1])))
    cache = getattr(reader, '_cache', None) if in_process_work else None
    if cache is not None and hasattr(cache, 'set_bypass'):
        # stages deliberately EXCLUDE cache_store: first-epoch store cost is
        # an investment in warm epochs, and a bypass committed on it would be
        # a one-way door (with bypass on, no cache stage ever accumulates
        # again to propose turning it back). Only hit-serving cost — the case
        # where bypass can genuinely win — may select this knob.
        knobs.append(Knob(
            'cache_bypass',
            'serve direct fills instead of cache hits (0=serve, 1=bypass)',
            minimum=0.0, maximum=1.0, step=1.0, cost='cheap',
            stages=('cache_hit',), unit='flag',
            get=lambda: float(bool(cache.bypass)),
            apply=lambda v: float(cache.set_bypass(v >= 0.5))))
    scheduler = getattr(reader, '_cost_scheduler', None)
    if (scheduler is not None and hasattr(scheduler, 'set_interleave')
            and getattr(scheduler, 'live_reorder', False)
            and getattr(reader, '_lineage', None) is None):
        # With the lineage audit armed the knob is PINNED: the manifest
        # header froze this run's schedule plan, and a mid-run interleave
        # flip would make `lineage verify` diagnose divergence on an order
        # the controller legitimately produced (docs/observability.md
        # "Sample lineage & determinism audit"). Reproducibility-audited
        # runs trade this one knob away by construction.
        # the cost-aware interleave half is a live toggle (next epoch
        # reorder); splits are frozen at construction — they shaped the
        # work-item list — so only the interleave is hill-climbable, and
        # only on readers that actually reorder each epoch (live_reorder:
        # a static-order reader never reads the toggle again, and the
        # controller must not hill-climb a dead knob). The breaker board
        # interlocks this knob like every other (docs/autotuning.md).
        knobs.append(Knob(
            'schedule_interleave',
            'cost-balanced heavy/light ventilation interleave '
            '(0=plain order, 1=interleaved)',
            minimum=0.0, maximum=1.0, step=1.0, cost='cheap',
            stages=('pool_wait', 'shuffle_wait'), unit='flag',
            get=lambda: float(bool(scheduler.interleave)),
            apply=lambda v: float(scheduler.set_interleave(v >= 0.5))))
    if (cache is not None and hasattr(cache, 'set_writable_hits')
            and getattr(reader, '_transform_spec', None) is None
            and not getattr(cache, 'writable_hits_pinned', False)):
        # A transform_spec may mutate hit columns in place — writable hits are
        # then a correctness requirement, not a knob; only transform-free
        # readers may trade the copy away. An explicit
        # cache_extra_settings={'writable_hits': ...} pins the mode too: the
        # user said what their consumer needs, the tuner must not unsay it.
        knobs.append(Knob(
            'cache_writable_hits',
            'decode cache hits writable (1) vs zero-copy read-only views (0)',
            minimum=0.0, maximum=1.0, step=1.0, cost='cheap',
            stages=('cache_hit',), unit='flag',
            get=lambda: float(bool(cache.writable_hits)),
            apply=lambda v: float(cache.set_writable_hits(v >= 0.5))))
    return knobs


def build_loader_knobs(loader: Any) -> List[Knob]:
    """Knobs for a live :class:`~petastorm_tpu.parallel.loader.JaxDataLoader`:
    the prefetch queue depth and (when the reader ships raw fields) the device
    decode tail's ring depth — both gated off when ``device_put=False``, where
    batches never leave the host and neither queue hides device latency — plus
    the shuffle-buffer fill threshold (``min_after_retrieve``) when a
    shuffling buffer is configured."""
    knobs: List[Knob] = []
    if getattr(loader, '_device_put', False):
        current_prefetch = float(getattr(loader, 'prefetch', 2))
        knobs.append(Knob(
            'loader_prefetch',
            'host-batch prefetch queue depth (batches in flight ahead of the '
            'training loop)',
            minimum=1.0, maximum=max(16.0, current_prefetch * 8), step=1.0,
            cost='cheap', stages=('shuffle_wait', 'h2d'), unit='batches',
            get=lambda: float(loader.prefetch),
            apply=lambda v: float(loader.set_prefetch(int(v)))))
        if getattr(loader, '_device_stage', None) is not None:
            knobs.append(Knob(
                'loader_device_buffer',
                'device decode-tail ring depth (decode programs dispatched '
                'ahead of the train step)',
                minimum=1.0, maximum=16.0, step=1.0, cost='cheap',
                stages=('d2d_wait', 'h2d'), unit='batches',
                get=lambda: float(loader.device_buffer_depth),
                apply=lambda v: float(loader.set_device_buffer_depth(int(v)))))
    capacity = int(getattr(loader, '_shuffling_queue_capacity', 0) or 0)
    if capacity <= 0:
        return knobs

    def current() -> float:
        value = getattr(loader, '_min_after_retrieve', None)
        return float(capacity // 2 if value is None else value)

    def apply(value: float) -> float:
        applied = max(0, min(int(value), capacity))
        loader._min_after_retrieve = applied
        buffer = getattr(loader, '_active_buffer', None)
        if buffer is not None and hasattr(buffer, 'set_min_after_retrieve'):
            applied = buffer.set_min_after_retrieve(applied)
        return float(applied)

    knobs.append(Knob(
        'loader_min_after_retrieve',
        'shuffle-buffer decorrelation floor (fill threshold before retrieve)',
        minimum=0.0, maximum=float(capacity),
        step=float(max(1, capacity // 8)), cost='cheap',
        stages=('shuffle_wait',), unit='rows', get=current, apply=apply))
    return knobs


def build_service_knobs(scheduler: Any) -> List[Knob]:
    """Knobs for a live service :class:`~petastorm_tpu.service.dispatcher.
    FairShareScheduler`: the admission-window cap and the live per-client
    in-flight depth (both via the scheduler's bounded setters)."""
    knobs: List[Knob] = []
    if hasattr(scheduler, 'set_admission_window'):
        initial = float(scheduler.admission_window)
        knobs.append(Knob(
            'service_admission_window',
            'per-client admission cap (queued + assigned) before busy',
            minimum=1.0, maximum=max(64.0, initial * 4),
            step=max(1.0, initial / 4), cost='cheap', stages=(),
            unit='items',
            get=lambda: float(scheduler.admission_window),
            apply=lambda v: float(scheduler.set_admission_window(int(v)))))
    if hasattr(scheduler, 'set_client_windows'):
        initial = float(scheduler.admission_window)
        knobs.append(Knob(
            'service_client_window',
            'live per-client in-flight depth (clamped by the admission cap)',
            minimum=1.0, maximum=max(64.0, initial * 4),
            step=max(1.0, initial / 4), cost='cheap', stages=(),
            unit='items',
            get=lambda: float(scheduler.effective_client_window()),
            apply=lambda v: float(scheduler.set_client_windows(int(v)))))
    return knobs
