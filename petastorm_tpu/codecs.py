"""Field codecs: how a logical tensor/scalar field is stored inside a Parquet column.

Capability parity with petastorm/codecs.py:36-294 (ScalarCodec, NdarrayCodec,
CompressedNdarrayCodec, CompressedImageCodec), re-designed for a TPU-first stack:

- codecs render to **Arrow types** (the storage substrate) instead of Spark SQL types;
- every codec is **JSON-serializable** (``to_config``/``codec_from_config``) so schemas are
  persisted as versioned JSON rather than pickled class instances — the reference documents
  pickling as its own fragility (petastorm/codecs.py:20-21, etl/dataset_metadata.py:216-218);
- decode returns C-contiguous numpy suitable for zero-copy ``jax.device_put``.
"""

import os
import threading
import zlib
from io import BytesIO

import numpy as np
import pyarrow as pa


def decode_thread_count():
    """Decode fan-out width for GIL-releasing batched kernels (``cv2.imdecode``,
    zlib inflate): ``PETASTORM_TPU_DECODE_THREADS`` when set, else
    ``min(4, cpu_count)`` — 1 disables the pool (docs/performance.md
    "Vectorized decode engine")."""
    env = os.environ.get('PETASTORM_TPU_DECODE_THREADS')
    if env is not None:
        return max(1, int(env))
    return max(1, min(4, os.cpu_count() or 1))


#: below this many cells a thread fan-out costs more than it hides
_MIN_PARALLEL_CELLS = 16

_decode_pool_state = {'pool': None, 'threads': 0, 'pid': 0}
_decode_pool_lock = threading.Lock()


def _decode_pool(threads):
    """Process-local decode thread pool, rebuilt under a lock if the width knob
    or the pid changed (a pool of threads never survives a fork); a superseded
    pool is shut down so its idle threads don't linger."""
    from concurrent.futures import ThreadPoolExecutor
    state = _decode_pool_state
    with _decode_pool_lock:
        if (state['pool'] is None or state['threads'] != threads
                or state['pid'] != os.getpid()):
            if state['pool'] is not None and state['pid'] == os.getpid():
                state['pool'].shutdown(wait=False)
            state['pool'] = ThreadPoolExecutor(
                max_workers=threads, thread_name_prefix='ptpu-decode')
            state['threads'] = threads
            state['pid'] = os.getpid()
        return state['pool']


def _binary_chunk_blobs(chunk):
    """Zero-copy per-row ``uint8`` views into a binary chunk's data buffer
    (sliced/offset chunks included), or None when the chunk is not binary-typed
    or contains nulls — callers then fall back to ``to_pylist``."""
    if chunk.null_count or len(chunk) == 0:
        return None
    if pa.types.is_large_binary(chunk.type) or pa.types.is_large_string(chunk.type):
        off_dtype = np.dtype(np.int64)
    elif pa.types.is_binary(chunk.type) or pa.types.is_string(chunk.type):
        off_dtype = np.dtype(np.int32)
    else:
        return None
    buffers = chunk.buffers()
    if buffers[1] is None or buffers[2] is None:
        return None
    offsets = np.frombuffer(buffers[1], dtype=off_dtype, count=len(chunk) + 1,
                            offset=chunk.offset * off_dtype.itemsize)
    data = np.frombuffer(buffers[2], dtype=np.uint8)
    bounds = offsets.tolist()
    return [data[lo:hi] for lo, hi in zip(bounds, bounds[1:])]


def _column_blobs(arrow_col):
    """Flatten a (Chunked)Array of binary blobs into one list of zero-copy views
    (``to_pylist`` bytes for null-bearing or exotic chunks)."""
    chunks = arrow_col.chunks if isinstance(arrow_col, pa.ChunkedArray) else [arrow_col]
    blobs = []
    for chunk in chunks:
        views = _binary_chunk_blobs(chunk)
        blobs.extend(chunk.to_pylist() if views is None else views)
    return blobs


def _is_compliant_shape(data_shape, field_shape):
    """True when ``data_shape`` matches ``field_shape``, treating None dims as wildcards
    (reference: petastorm/codecs.py:274-294)."""
    if len(data_shape) != len(field_shape):
        return False
    for data_dim, field_dim in zip(data_shape, field_shape):
        if field_dim is not None and data_dim != field_dim:
            return False
    return True


class FieldCodec(object):
    """Abstract codec: encodes one logical field value into its stored Parquet representation
    and back (reference ABC: petastorm/codecs.py:36-55)."""

    #: registry name used in JSON schema serialization
    codec_name = None

    def encode(self, unischema_field, value):
        raise NotImplementedError()

    def decode(self, unischema_field, value):
        raise NotImplementedError()

    def decode_column(self, unischema_field, values):
        """Decode a whole column of encoded cells; codecs override this when a vectorized
        path exists (None cells pass through)."""
        return [None if v is None else self.decode(unischema_field, v) for v in values]

    def decode_arrow_column(self, unischema_field, arrow_col):
        """Decode straight from the Arrow column. Returns either a fully-stacked ndarray
        of shape ``(n,) + field.shape`` (fast path) or a per-cell list like
        :meth:`decode_column`. Codecs override this to avoid the Arrow->Python-object
        round-trip on the hot read path."""
        return self.decode_column(unischema_field, arrow_col.to_pylist())

    def arrow_type(self, unischema_field):
        """Arrow storage type of the encoded column."""
        raise NotImplementedError()

    def to_config(self):
        """JSON-safe dict describing this codec; inverse of :func:`codec_from_config`."""
        return {'codec': self.codec_name}

    def __str__(self):
        return '{}()'.format(type(self).__name__)

    def __eq__(self, other):
        return isinstance(other, FieldCodec) and self.to_config() == other.to_config()

    def __ne__(self, other):
        return not self == other

    def __hash__(self):
        return hash(tuple(sorted(self.to_config().items(), key=lambda kv: kv[0])))


def _parse_npy_header(blob):
    """Parse a ``.npy`` blob's header. Returns (header_len, shape, fortran_order, dtype),
    or None for unknown format versions / malformed headers."""
    f = BytesIO(blob)
    try:
        version = np.lib.format.read_magic(f)
        if version == (1, 0):
            shape, fortran, dtype = np.lib.format.read_array_header_1_0(f)
        elif version == (2, 0):
            shape, fortran, dtype = np.lib.format.read_array_header_2_0(f)
        else:
            return None
    except Exception:  # noqa: BLE001 - malformed header falls back to np.load
        return None
    return f.tell(), shape, fortran, dtype


_NUMPY_TO_ARROW = {
    np.dtype('bool'): pa.bool_(),
    np.dtype('int8'): pa.int8(),
    np.dtype('uint8'): pa.uint8(),
    np.dtype('int16'): pa.int16(),
    np.dtype('uint16'): pa.uint16(),
    np.dtype('int32'): pa.int32(),
    np.dtype('uint32'): pa.uint32(),
    np.dtype('int64'): pa.int64(),
    np.dtype('uint64'): pa.uint64(),
    np.dtype('float16'): pa.float16(),
    np.dtype('float32'): pa.float32(),
    np.dtype('float64'): pa.float64(),
}


def arrow_type_for_numpy(numpy_dtype):
    """Best-effort Arrow type for a numpy dtype, including strings and datetimes."""
    dtype = np.dtype(numpy_dtype) if not isinstance(numpy_dtype, np.dtype) else numpy_dtype
    if dtype in _NUMPY_TO_ARROW:
        return _NUMPY_TO_ARROW[dtype]
    if dtype.kind == 'S':
        # bytes dtype must store as Arrow binary, or decode hands back str
        return pa.binary()
    if dtype.kind == 'U' or dtype == np.dtype(object):
        return pa.string()
    if dtype.kind == 'M':
        return pa.timestamp('ns')
    raise ValueError('No Arrow mapping for numpy dtype {}'.format(dtype))


class ScalarCodec(FieldCodec):
    """Stores a scalar field as a native Parquet column of ``arrow_dtype`` (reference:
    petastorm/codecs.py:215-271, which took a Spark SQL type instead).

    ``arrow_dtype`` may be a ``pyarrow.DataType`` or anything ``np.dtype`` accepts; defaults
    to the field's own numpy dtype.
    """

    codec_name = 'scalar'

    def __init__(self, arrow_dtype=None):
        if arrow_dtype is None or isinstance(arrow_dtype, pa.DataType):
            self._arrow_dtype = arrow_dtype
        else:
            self._arrow_dtype = arrow_type_for_numpy(arrow_dtype)
        if self._arrow_dtype is not None:
            # Fail at construction (write time), not at dataset load time: the JSON schema
            # store round-trips the type through str().
            try:
                _parse_arrow_type(str(self._arrow_dtype))
            except ValueError:
                raise ValueError(
                    'ScalarCodec does not support Arrow type {!r}: it would not survive '
                    'schema serialization. Supported: {}'.format(
                        self._arrow_dtype,
                        sorted(_PARSEABLE_ARROW_TYPES) + ['decimal128(p,s)']))

    def encode(self, unischema_field, value):
        if isinstance(value, np.ndarray) and value.ndim > 0:
            raise TypeError('Expected a scalar value for field {}, got array of shape {}'
                            .format(unischema_field.name, value.shape))
        # Unwrap numpy scalars to native python for Parquet writers.
        if isinstance(value, np.generic):
            return value.item()
        return value

    def decode(self, unischema_field, value):
        dtype = unischema_field.numpy_dtype
        if np.dtype(dtype).kind in ('U', 'S', 'O'):
            return value
        return np.dtype(dtype).type(value)

    def decode_arrow_column(self, unischema_field, arrow_col):
        """Vectorized scalar decode: numeric/bool/datetime columns convert through Arrow's
        native ``to_numpy`` in one shot instead of per-cell ``np.dtype.type`` calls."""
        dtype = np.dtype(unischema_field.numpy_dtype)
        if dtype.kind in ('U', 'S', 'O', 'M') or arrow_col.null_count:
            return self.decode_column(unischema_field, arrow_col.to_pylist())
        return arrow_col.to_numpy(zero_copy_only=False).astype(dtype, copy=False)

    def arrow_type(self, unischema_field):
        if self._arrow_dtype is not None:
            return self._arrow_dtype
        return arrow_type_for_numpy(unischema_field.numpy_dtype)

    def to_config(self):
        config = {'codec': self.codec_name}
        if self._arrow_dtype is not None:
            config['arrow_dtype'] = str(self._arrow_dtype)
        return config

    @classmethod
    def from_config(cls, config):
        arrow_dtype = config.get('arrow_dtype')
        if arrow_dtype is not None:
            arrow_dtype = _parse_arrow_type(arrow_dtype)
        return cls(arrow_dtype)


_PARSEABLE_ARROW_TYPES = {
    'bool': pa.bool_(), 'int8': pa.int8(), 'uint8': pa.uint8(), 'int16': pa.int16(),
    'uint16': pa.uint16(), 'int32': pa.int32(), 'uint32': pa.uint32(),
    'int64': pa.int64(), 'uint64': pa.uint64(), 'halffloat': pa.float16(),
    'float': pa.float32(), 'double': pa.float64(), 'string': pa.string(),
    'binary': pa.binary(), 'large_string': pa.large_string(),
    'timestamp[ns]': pa.timestamp('ns'), 'timestamp[us]': pa.timestamp('us'),
    'date32[day]': pa.date32(),
}


def _parse_arrow_type(type_str):
    """Parse ``str(pa.DataType)`` back into a DataType for the types ScalarCodec emits."""
    if type_str in _PARSEABLE_ARROW_TYPES:
        return _PARSEABLE_ARROW_TYPES[type_str]
    if type_str.startswith('decimal128'):
        inner = type_str[type_str.index('(') + 1:type_str.index(')')]
        precision, scale = (int(x) for x in inner.split(','))
        return pa.decimal128(precision, scale)
    raise ValueError('Cannot parse Arrow type {!r}'.format(type_str))


def _ndarray_to_npy_bytes(value):
    memfile = BytesIO()
    np.save(memfile, value)
    return memfile.getvalue()


def _npy_bytes_to_ndarray(blob):
    return np.ascontiguousarray(np.load(BytesIO(blob), allow_pickle=False))


class NdarrayCodec(FieldCodec):
    """Stores a numpy tensor as an uncompressed ``.npy`` byte blob (reference:
    petastorm/codecs.py:133-171)."""

    codec_name = 'ndarray'

    def encode(self, unischema_field, value):
        expected = np.dtype(unischema_field.numpy_dtype)
        if value.dtype != expected:
            raise ValueError('Unexpected dtype {} for field {} (expected {})'
                             .format(value.dtype, unischema_field.name, expected))
        if not _is_compliant_shape(value.shape, unischema_field.shape):
            raise ValueError('Unexpected shape {} for field {} (expected {})'
                             .format(value.shape, unischema_field.name, unischema_field.shape))
        return _ndarray_to_npy_bytes(value)

    def decode(self, unischema_field, value):
        return _npy_bytes_to_ndarray(value)

    def decode_arrow_column(self, unischema_field, arrow_col):
        """Whole-column decode straight from Arrow buffers: when every ``.npy`` blob in a
        chunk has the same length and header (the common fixed-shape-field case), the
        chunk's data buffer is reinterpreted as an ``(n, blob_len)`` byte matrix and the
        payload region becomes the stacked output in ONE copy — no per-row Python at all.
        Ragged/mixed chunks fall back to the per-cell path."""
        chunks = arrow_col.chunks if isinstance(arrow_col, pa.ChunkedArray) else [arrow_col]
        pieces = []
        all_stacked = True
        for chunk in chunks:
            fast = self._decode_chunk_matrix(chunk)
            if fast is None:
                pieces.append(self.decode_column(unischema_field, chunk.to_pylist()))
                all_stacked = False
            else:
                pieces.append(fast)
        if len(pieces) == 1:
            return pieces[0]
        if all_stacked and len({p.shape[1:] for p in pieces}) == 1:
            return np.concatenate(pieces, axis=0)
        out = []
        for piece in pieces:
            out.extend(list(piece))
        return out

    @staticmethod
    def _decode_chunk_matrix(chunk):
        if len(chunk) == 0 or chunk.null_count:
            return None
        if pa.types.is_large_binary(chunk.type):
            off_dtype = np.dtype(np.int64)
        elif pa.types.is_binary(chunk.type):
            off_dtype = np.dtype(np.int32)
        else:
            return None
        buffers = chunk.buffers()
        offsets = np.frombuffer(buffers[1], dtype=off_dtype, count=len(chunk) + 1,
                                offset=chunk.offset * off_dtype.itemsize)
        lengths = np.diff(offsets)
        blob_len = int(lengths[0]) if len(lengths) else 0
        if blob_len == 0 or not (lengths == blob_len).all():
            return None
        data = np.frombuffer(buffers[2], dtype=np.uint8)
        matrix = data[int(offsets[0]):int(offsets[0]) + len(chunk) * blob_len] \
            .reshape(len(chunk), blob_len)
        parsed = _parse_npy_header(matrix[0].tobytes())
        if parsed is None:
            return None
        header_len, shape, fortran, dtype = parsed
        if fortran or dtype.hasobject or not dtype.isnative:
            return None
        if header_len + int(np.prod(shape, dtype=np.int64)) * dtype.itemsize != blob_len:
            return None
        header = matrix[0, :header_len]
        if not (matrix[:, :header_len] == header).all():
            return None
        payload = np.ascontiguousarray(matrix[:, header_len:])
        return payload.view(dtype).reshape((len(chunk),) + shape)

    #: distinct-header cache cap: ragged columns with per-row shapes must not grow it
    _HEADER_CACHE_MAX = 1024

    def decode_column(self, unischema_field, values):
        """Vectorized decode: ``.npy`` blobs of the same dtype/shape share an identical
        header prefix, so the header is parsed ONCE and the rest decode via zero-parse
        ``np.frombuffer`` — ~5x faster than per-cell ``np.load`` (whose
        ast.literal_eval header parsing dominates the reference-style per-row decode).

        The npy header is 64-byte aligned, so ``blob[:64]`` lies entirely within it and
        serves as an O(1) dict key; full-prefix equality is confirmed within the bucket.
        """
        header_cache = {}

        def lookup(blob):
            probe = bytes(blob[:64])
            for prefix, meta in header_cache.get(probe, ()):
                if blob[:len(prefix)] == prefix:
                    return meta
            parsed = _parse_npy_header(blob)
            if parsed is None:
                return None
            offset, shape, fortran, dtype = parsed
            meta = (shape, fortran, dtype, offset)
            if len(header_cache) < self._HEADER_CACHE_MAX:
                header_cache.setdefault(probe, []).append((bytes(blob[:offset]), meta))
            return meta

        out = []
        for blob in values:
            if blob is None:
                out.append(None)
                continue
            meta = lookup(blob)
            if meta is None:
                out.append(self.decode(unischema_field, blob))
                continue
            shape, fortran, dtype, offset = meta
            if fortran or dtype.hasobject:
                out.append(self.decode(unischema_field, blob))
                continue
            # .copy() keeps decode()'s writable-array contract (frombuffer views of a
            # bytes blob are read-only).
            out.append(np.frombuffer(blob, dtype=dtype, offset=offset)
                       .reshape(shape).copy())
        return out

    def arrow_type(self, unischema_field):
        return pa.binary()


def _npz_raw_member(blob):
    """Parse the single-member zip container of a ``np.savez_compressed`` blob
    WITHOUT inflating: returns ``(method, body)`` where ``method`` is the zip
    compression method (8 = deflate: ``body`` is the raw-deflate stream; 0 =
    stored: ``body`` is the member's ``.npy`` bytes) — the ship-raw form the
    device-resident decode tail uploads (docs/performance.md). None for any
    unexpected container layout — callers must then keep the host decode path."""
    head = bytes(memoryview(blob)[:30])
    if len(head) < 30 or head[:4] != b'PK\x03\x04':
        return None
    flags = int.from_bytes(head[6:8], 'little')
    method = int.from_bytes(head[8:10], 'little')
    name_len = int.from_bytes(head[26:28], 'little')
    extra_len = int.from_bytes(head[28:30], 'little')
    body = memoryview(blob)[30 + name_len + extra_len:]
    if method == 8:
        if flags & 0x08:
            # sizes only in the trailing data descriptor: the deflate stream's
            # end is self-delimiting, but the body view would include the
            # descriptor + central directory — the raw-deflate consumer stops
            # at BFINAL, so the trailing bytes are harmless; still slice off
            # nothing here (length unknown without inflating).
            return 8, body
        size = int.from_bytes(head[18:22], 'little')
        return 8, body[:size]
    if method == 0 and not flags & 0x08:
        size = int.from_bytes(head[18:22], 'little')
        return 0, body[:size]
    return None


def _npz_npy_payload(blob):
    """Extract the raw ``.npy`` member bytes out of a ``np.savez_compressed``
    container WITHOUT ``BytesIO``/``ZipFile`` machinery: the single member's
    zip local-file header parses through :func:`_npz_raw_member` (the one
    parser both the host decode and ship-raw paths share) and deflate bodies
    inflate in one raw ``zlib`` call. Returns None for any unexpected layout —
    callers fall back to ``np.load``."""
    parsed = _npz_raw_member(blob)
    if parsed is None:
        return None
    method, body = parsed
    if method == 8:
        try:
            return zlib.decompressobj(-15).decompress(body)
        except zlib.error:
            return None
    return bytes(body)


def _cached_npy_meta(payload, cache):
    """``(shape, fortran, dtype, offset)`` of an npy blob, memoized by header
    prefix: the npy header is 64-byte aligned so ``payload[:64]`` is an O(1)
    dict key, with full-prefix equality confirmed inside the bucket. None for
    unparseable headers."""
    probe = bytes(payload[:64])
    for prefix, meta in cache.get(probe, ()):
        if payload[:len(prefix)] == prefix:
            return meta
    parsed = _parse_npy_header(bytes(payload))
    if parsed is None:
        return None
    offset, shape, fortran, dtype = parsed
    meta = (shape, fortran, dtype, offset)
    if len(cache) < 1024:
        cache.setdefault(probe, []).append((bytes(payload[:offset]), meta))
    return meta


class CompressedNdarrayCodec(FieldCodec):
    """Stores a numpy tensor zlib-compressed via ``np.savez_compressed`` (reference:
    petastorm/codecs.py:174-212)."""

    codec_name = 'compressed_ndarray'

    def encode(self, unischema_field, value):
        expected = np.dtype(unischema_field.numpy_dtype)
        if value.dtype != expected:
            raise ValueError('Unexpected dtype {} for field {} (expected {})'
                             .format(value.dtype, unischema_field.name, expected))
        if not _is_compliant_shape(value.shape, unischema_field.shape):
            raise ValueError('Unexpected shape {} for field {} (expected {})'
                             .format(value.shape, unischema_field.name, unischema_field.shape))
        memfile = BytesIO()
        np.savez_compressed(memfile, arr=value)
        return memfile.getvalue()

    def decode(self, unischema_field, value):
        memfile = BytesIO(value)
        with np.load(memfile, allow_pickle=False) as data:
            return np.ascontiguousarray(data['arr'])

    @staticmethod
    def _cell_payload_meta(blob, header_cache):
        """One cell's (payload, meta): raw-deflate inflate + memoized npy header
        parse. meta is None when the fast path cannot represent the cell (the
        caller np.load-falls-back)."""
        payload = _npz_npy_payload(blob)
        if payload is None:
            return None, None
        meta = _cached_npy_meta(payload, header_cache)
        if meta is None:
            return payload, None
        shape, fortran, dtype, offset = meta
        if fortran or dtype.hasobject:
            return payload, None
        nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if len(payload) - offset != nbytes:
            return payload, None
        return payload, meta

    def _cell_fallback(self, unischema_field, blob, payload):
        """Slow-path single cell: np.load on the inflated member when available
        (container already validated), else the full zip decode."""
        if payload is not None:
            return np.ascontiguousarray(
                np.load(BytesIO(bytes(payload)), allow_pickle=False))
        return self.decode(unischema_field, bytes(memoryview(blob)))

    def decode_column(self, unischema_field, values):
        """Vectorized decode: every cell inflates through ONE raw zlib call (no
        per-cell ``BytesIO``/``ZipFile`` re-parse) and npy headers are parsed
        once per distinct header — the same shared-header trick as
        :meth:`NdarrayCodec.decode_column`. Unknown containers fall back to
        per-cell :meth:`decode`."""
        header_cache = {}
        out = []
        for blob in values:
            if blob is None:
                out.append(None)
                continue
            payload, meta = self._cell_payload_meta(blob, header_cache)
            if meta is None:
                out.append(self._cell_fallback(unischema_field, blob, payload))
                continue
            shape, _, dtype, offset = meta
            count = int(np.prod(shape, dtype=np.int64))
            # .copy() keeps decode()'s writable-array contract
            out.append(np.frombuffer(payload, dtype=dtype, count=count,
                                     offset=offset).reshape(shape).copy())
        return out

    def decode_arrow_column(self, unischema_field, arrow_col):
        """Whole-column decode with a preallocated output: blobs stream straight
        out of the Arrow data buffer as zero-copy views, inflate via raw zlib,
        and land in ONE ``(n,) + shape`` array when every cell shares one npy
        header (the uniform-shape case); ragged/null/mixed columns demote to the
        per-cell list contract."""
        blobs = _column_blobs(arrow_col)
        n = len(blobs)
        if n == 0:
            return []
        header_cache = {}
        out = None
        cells = None
        for i, blob in enumerate(blobs):
            arr = None
            cell = None
            if blob is not None:
                payload, meta = self._cell_payload_meta(blob, header_cache)
                if meta is None:
                    cell = self._cell_fallback(unischema_field, blob, payload)
                else:
                    shape, _, dtype, offset = meta
                    count = int(np.prod(shape, dtype=np.int64))
                    arr = np.frombuffer(payload, dtype=dtype, count=count,
                                        offset=offset).reshape(shape)
            if cells is None:
                if arr is not None:
                    if out is None and i == 0:
                        out = np.empty((n,) + arr.shape, dtype=arr.dtype)
                    if out is not None and arr.shape == out.shape[1:] \
                            and arr.dtype == out.dtype:
                        out[i] = arr
                        continue
                # first non-uniform cell: demote the filled prefix to a list
                cells = [out[j] for j in range(i)] if out is not None else []
            cells.append(cell if arr is None else arr.copy())
        return out if cells is None else cells

    def arrow_type(self, unischema_field):
        return pa.binary()


class CompressedImageCodec(FieldCodec):
    """png/jpeg image compression via OpenCV, with the RGB<->BGR swap for 3-channel images
    (reference: petastorm/codecs.py:58-130)."""

    codec_name = 'compressed_image'

    def __init__(self, image_codec='png', quality=80):
        if image_codec not in ('png', 'jpeg'):
            raise ValueError('image_codec must be "png" or "jpeg", got {!r}'
                             .format(image_codec))
        self._image_codec = '.' + image_codec
        self._quality = int(quality)

    @property
    def image_codec(self):
        return self._image_codec[1:]

    @property
    def quality(self):
        return self._quality

    def encode(self, unischema_field, value):
        import cv2
        expected = np.dtype(unischema_field.numpy_dtype)
        if value.dtype != expected:
            raise ValueError('Unexpected dtype {} for field {} (expected {})'
                             .format(value.dtype, unischema_field.name, expected))
        if not _is_compliant_shape(value.shape, unischema_field.shape):
            raise ValueError('Unexpected shape {} for field {} (expected {})'
                             .format(value.shape, unischema_field.name, unischema_field.shape))
        if self._image_codec == '.jpeg' and value.dtype != np.uint8:
            raise ValueError('jpeg compression supports only uint8 images '
                             '(field {})'.format(unischema_field.name))
        image_bgr = value
        if value.ndim == 3 and value.shape[2] == 3:
            # Stored in OpenCV's BGR channel order, same convention the reference documents
            # (petastorm/codecs.py:92-95) so image blobs round-trip bit-compatibly.
            image_bgr = cv2.cvtColor(value, cv2.COLOR_RGB2BGR)
        if self._image_codec == '.jpeg':
            params = [cv2.IMWRITE_JPEG_QUALITY, self._quality]
        else:
            params = []
        success, buf = cv2.imencode(self._image_codec, image_bgr, params)
        if not success:
            raise RuntimeError('cv2.imencode failed for field {}'.format(unischema_field.name))
        return buf.tobytes()

    def decode(self, unischema_field, value):
        import cv2
        image_bgr = cv2.imdecode(np.frombuffer(value, dtype=np.uint8), cv2.IMREAD_UNCHANGED)
        if image_bgr is None:
            raise ValueError('cv2.imdecode failed for field {}'.format(unischema_field.name))
        if image_bgr.ndim == 3 and image_bgr.shape[2] == 3:
            image_bgr = cv2.cvtColor(image_bgr, cv2.COLOR_BGR2RGB)
        return np.ascontiguousarray(image_bgr.astype(unischema_field.numpy_dtype, copy=False))

    #: decode_arrow_column slab marker: "this cell was written into the
    #: preallocated output", distinct from a None (null) cell value
    _IN_SLAB = object()

    def decode_arrow_column(self, unischema_field, arrow_col):
        """Batched whole-column image decode: per-row zero-copy blob views (no
        ``to_pylist`` byte materialization), one ``cv2.imdecode`` per image
        fanned across GIL-released decode threads
        (``PETASTORM_TPU_DECODE_THREADS``), and the BGR->RGB conversion written
        straight into a preallocated ``(n, h, w, c)`` output when the field
        declares a fully-concrete shape. Ragged columns demote to the per-cell
        list contract."""
        import cv2
        blobs = _column_blobs(arrow_col)
        n = len(blobs)
        if n == 0:
            return []
        dtype = np.dtype(unischema_field.numpy_dtype)
        shape = tuple(unischema_field.shape)
        uniform = bool(shape) and all(d is not None for d in shape)
        out = np.empty((n,) + shape, dtype=dtype) if uniform else None
        in_slab = self._IN_SLAB

        def decode_one(i):
            blob = blobs[i]
            if blob is None:
                return None
            buf = blob if isinstance(blob, np.ndarray) \
                else np.frombuffer(blob, dtype=np.uint8)
            image_bgr = cv2.imdecode(buf, cv2.IMREAD_UNCHANGED)
            if image_bgr is None:
                raise ValueError('cv2.imdecode failed for field {}'
                                 .format(unischema_field.name))
            if out is not None and image_bgr.shape == shape \
                    and image_bgr.dtype == dtype:
                if image_bgr.ndim == 3 and image_bgr.shape[2] == 3:
                    cv2.cvtColor(image_bgr, cv2.COLOR_BGR2RGB, dst=out[i])
                else:
                    out[i] = image_bgr
                return in_slab
            if image_bgr.ndim == 3 and image_bgr.shape[2] == 3:
                image_bgr = cv2.cvtColor(image_bgr, cv2.COLOR_BGR2RGB)
            return np.ascontiguousarray(image_bgr.astype(dtype, copy=False))

        threads = decode_thread_count()
        if threads > 1 and n >= _MIN_PARALLEL_CELLS:
            results = list(_decode_pool(threads).map(decode_one, range(n)))
        else:
            results = [decode_one(i) for i in range(n)]
        if out is not None and all(r is in_slab for r in results):
            return out
        return [out[i] if r is in_slab else r for i, r in enumerate(results)]

    def arrow_type(self, unischema_field):
        return pa.binary()

    def to_config(self):
        return {'codec': self.codec_name,
                'image_codec': self.image_codec,
                'quality': self._quality}

    @classmethod
    def from_config(cls, config):
        return cls(image_codec=config['image_codec'], quality=config['quality'])

    def __str__(self):
        return 'CompressedImageCodec({!r}, quality={})'.format(self.image_codec, self._quality)


class DctImageCodec(FieldCodec):
    """JPEG-style DCT-domain image storage with an on-chip decode option (SURVEY.md
    §7.3's decode-as-jax-op variant; no reference analog).

    Images are stored as quantized 8x8 DCT coefficient blocks (int16) with a tiny
    header carrying the pre-padding height/width; Parquet page compression over the
    mostly-zero coefficients replaces JPEG's entropy coder, so the stored size is
    JPEG-like. ``decode`` runs the exact host mirror (numpy IDCT) — full parity with
    every reader path. For on-chip decode, read the SAME stored field through
    :class:`DctCoefficientsCodec` (``make_reader(..., field_overrides=...)``): workers
    then ship raw int16 coefficients and ``ops.image_decode.dct_decode_images_jax``
    does dequant + IDCT + color conversion on the MXU inside your jitted step."""

    codec_name = 'dct_image'
    _MAGIC = b'DCT1'

    def __init__(self, quality=75):
        self._quality = int(quality)

    @property
    def quality(self):
        return self._quality

    def encode(self, unischema_field, value):
        import struct
        from petastorm_tpu.ops.image_decode import dct_encode_image
        expected = np.dtype(unischema_field.numpy_dtype)
        if value.dtype != expected or expected != np.uint8:
            raise ValueError('DctImageCodec requires uint8 images (field {}, got {})'
                             .format(unischema_field.name, value.dtype))
        if not _is_compliant_shape(value.shape, unischema_field.shape):
            raise ValueError('Unexpected shape {} for field {} (expected {})'
                             .format(value.shape, unischema_field.name,
                                     unischema_field.shape))
        coeffs = dct_encode_image(value, quality=self._quality)
        header = self._MAGIC + struct.pack('<HH', value.shape[0], value.shape[1])
        return header + _ndarray_to_npy_bytes(coeffs)

    def _split(self, unischema_field, value):
        import struct
        value = bytes(value)
        if value[:4] != self._MAGIC:
            raise ValueError('Field {} is not DCT-coded data'.format(unischema_field.name))
        h, w = struct.unpack('<HH', value[4:8])
        return (h, w), value[8:]

    def decode(self, unischema_field, value):
        from petastorm_tpu.ops.image_decode import dct_decode_image
        (h, w), npy = self._split(unischema_field, value)
        coeffs = _npy_bytes_to_ndarray(npy)
        return dct_decode_image(coeffs, quality=self._quality, orig_hw=(h, w))

    def arrow_type(self, unischema_field):
        return pa.binary()

    def to_config(self):
        return {'codec': self.codec_name, 'quality': self._quality}

    @classmethod
    def from_config(cls, config):
        return cls(quality=config['quality'])

    def __str__(self):
        return 'DctImageCodec(quality={})'.format(self._quality)


class DctCoefficientsCodec(DctImageCodec):
    """Read-side reinterpretation of a :class:`DctImageCodec` field: decodes only to the
    raw int16 coefficient blocks ``[H/8, W/8, 8, 8, C]`` (no host IDCT) so the device
    does the transform. Use via ``make_reader(..., field_overrides=[UnischemaField(name,
    np.int16, (None, None, 8, 8, C), DctCoefficientsCodec(quality), False)])``.
    Images whose dimensions are multiples of 8 reconstruct exactly like the host path;
    otherwise the on-chip image keeps the edge padding (crop with the stored sizes)."""

    codec_name = 'dct_coefficients'

    def decode(self, unischema_field, value):
        _, npy = self._split(unischema_field, value)
        return _npy_bytes_to_ndarray(npy)


_CODEC_REGISTRY = {
    ScalarCodec.codec_name: ScalarCodec,
    NdarrayCodec.codec_name: NdarrayCodec,
    CompressedNdarrayCodec.codec_name: CompressedNdarrayCodec,
    CompressedImageCodec.codec_name: CompressedImageCodec,
    DctImageCodec.codec_name: DctImageCodec,
    DctCoefficientsCodec.codec_name: DctCoefficientsCodec,
}


def codec_from_config(config):
    """Reconstruct a codec from its ``to_config()`` dict (the JSON schema store)."""
    name = config['codec']
    if name not in _CODEC_REGISTRY:
        raise ValueError('Unknown codec {!r}'.format(name))
    cls = _CODEC_REGISTRY[name]
    if hasattr(cls, 'from_config'):
        return cls.from_config(config)
    return cls()
