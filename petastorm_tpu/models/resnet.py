"""ResNet-50 in flax (consumer model for examples/imagenet parity — reference:
examples/imagenet feeds torchvision's ResNet; re-designed MXU-first: NHWC, bfloat16
compute with float32 batch-norm statistics, no python-loop over data)."""

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name='conv_proj')(residual)
            residual = self.norm(name='norm_proj')(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: type = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train, momentum=0.9,
                       epsilon=1e-5, dtype=jnp.float32)
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name='conv_init')(x)
        x = norm(name='bn_init')(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding='SAME')
        for stage, block_count in enumerate(self.stage_sizes):
            for block in range(block_count):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                x = BottleneckBlock(self.num_filters * 2 ** stage, conv=conv, norm=norm,
                                    act=nn.relu, strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])
