"""TransformerLM: the decoder-only consumer model family (MXU-first flax).

The reference feeds torch/TF models; this repo's flagship consumers are JAX-native
(models/mnist.py, models/resnet.py for vision). TransformerLM completes the family for
the long-context story (SURVEY.md §5.7): bf16 compute with float32 logits, pre-norm
blocks, and a pluggable ``attention_fn`` so the SAME module runs

- dense attention on one chip (default),
- ``ops.flash_attention`` (Pallas MXU kernel) via ``attention_fn=flash_attention``,
- ``ops.ring_attention`` sequence-parallel over a mesh axis by injecting a
  ``shard_map``-wrapped callable (see examples/long_context) — the model stays free of
  mesh concerns; sharding is the caller's injection.
"""

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def dense_causal_attention(q, k, v):
    """[B, T, H, D] -> [B, T, H, D] exact causal attention — delegates to the ops
    reference implementation so every backend (dense default, flash fallback, ring)
    shares ONE numerical definition (fp32 scores)."""
    from petastorm_tpu.ops.ring_attention import dense_attention
    return dense_attention(q, k, v, causal=True)


def attention_sublayer(x, heads, attention_fn, dtype):
    """Pre-norm attention sublayer with residual: shared by the dense :class:`Block`
    and the MoE block (models/moe.py) so the attention path has ONE definition. Must
    be called from inside a parent module's ``@nn.compact`` ``__call__``."""
    embed = x.shape[-1]
    head_dim = embed // heads
    h = nn.LayerNorm(dtype=jnp.float32)(x).astype(dtype)
    qkv = nn.Dense(3 * embed, use_bias=False, dtype=dtype)(h)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    shape = (x.shape[0], x.shape[1], heads, head_dim)
    attn = attention_fn(q.reshape(shape), k.reshape(shape), v.reshape(shape))
    attn = attn.reshape(x.shape[0], x.shape[1], embed)
    return x + nn.Dense(embed, use_bias=False, dtype=dtype)(attn)


class Block(nn.Module):
    heads: int
    attention_fn: Callable
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        embed = x.shape[-1]
        x = attention_sublayer(x, self.heads, self.attention_fn, self.dtype)
        h = nn.LayerNorm(dtype=jnp.float32)(x).astype(self.dtype)
        h = nn.Dense(4 * embed, dtype=self.dtype)(h)
        h = nn.gelu(h)
        return x + nn.Dense(embed, dtype=self.dtype)(h)


class TransformerLM(nn.Module):
    """Decoder-only LM: tokens [B, T] int -> logits [B, T, vocab] float32."""

    vocab: int = 256
    embed: int = 64
    heads: int = 4
    layers: int = 2
    max_len: int = 8192
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    remat: bool = False

    @nn.compact
    def __call__(self, tokens, positions=None):
        """``positions`` (optional [B, T] int): explicit per-token position ids.
        Packed batches pass the packer's ``*_positions`` column here so each packed
        document restarts from position 0 instead of inheriting the bin-global
        arange (ADVICE r3); default None keeps the plain contiguous-sequence
        behavior."""
        if self.embed % self.heads != 0:
            raise ValueError('embed={} must be divisible by heads={}'
                             .format(self.embed, self.heads))
        if tokens.shape[1] > self.max_len:
            # jit-time (shapes are static): gather would silently clamp positions
            # past the table instead of failing.
            raise ValueError('sequence length {} exceeds max_len={}; raise max_len'
                             .format(tokens.shape[1], self.max_len))
        attention_fn = self.attention_fn or dense_causal_attention
        # remat trades FLOPs for HBM: block activations are recomputed in the
        # backward instead of stored — the standard long-context/deep-stack lever
        # (pairs with flash/ring attention, which bound the attention memory).
        block_cls = nn.remat(Block) if self.remat else Block
        x = nn.Embed(self.vocab, self.embed, dtype=self.dtype)(tokens)
        pos_table = nn.Embed(self.max_len, self.embed, dtype=self.dtype)
        if positions is None:
            x = x + pos_table(jnp.arange(tokens.shape[1]))[None]
        else:
            x = x + pos_table(positions)
        for i in range(self.layers):
            # Explicit names keep the param tree identical with and without remat
            # (nn.remat would otherwise rename the scope), so checkpoints and
            # sharding specs transfer between the two configurations.
            x = block_cls(heads=self.heads, attention_fn=attention_fn,
                          dtype=self.dtype, name='Block_{}'.format(i))(x)
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        return nn.Dense(self.vocab, dtype=jnp.float32)(x)


def next_token_loss(logits, tokens):
    """Causal LM loss: predict token t+1 from positions <= t. Requires T >= 2."""
    if tokens.shape[1] < 2:
        raise ValueError('next_token_loss needs sequences of length >= 2 (got {}): '
                         'the mean over zero predicted positions would be NaN'
                         .format(tokens.shape[1]))
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    targets = tokens[:, 1:]
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)
