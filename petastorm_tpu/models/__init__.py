"""Reference consumer models for the input pipeline's examples/benchmarks (the analog of
the reference's examples/mnist and examples/imagenet model code, re-done in flax)."""

from petastorm_tpu.models.mnist import MnistCNN  # noqa: F401
from petastorm_tpu.models.resnet import ResNet50  # noqa: F401
from petastorm_tpu.models.transformer import TransformerLM, next_token_loss  # noqa: F401
from petastorm_tpu.models.moe import (MoEMlp, MoEBlock, MoETransformerLM,  # noqa: F401
                                      expert_partition_specs, moe_aux_total)
