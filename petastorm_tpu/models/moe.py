"""Mixture-of-Experts layers — expert parallelism (ep) for the device mesh.

The reference has no MoE; this completes the parallelism families the TPU framework
serves (dp/sp/tp in ``__graft_entry__``/examples, pp in ``parallel/pipeline.py``, ep
here). Design is TPU-first, not a torch translation:

- **Static capacity dispatch.** Top-k routing with a fixed per-expert capacity
  ``C = ceil(capacity_factor * k * tokens / num_experts)`` so every shape is known at
  trace time — no ragged gathers, no data-dependent shapes that would break XLA tiling.
  Dispatch and combine are one-hot einsum masks, which land on the MXU.
- **Sharding by annotation.** Expert weights carry a leading experts axis; shard them
  ``PartitionSpec('expert', ...)`` (see :func:`expert_partition_specs`) and jit under a
  mesh with an ``'expert'`` axis — XLA places the all-to-all that moves token slots to
  their expert's device on ICI (the scaling-book recipe: annotate, let the compiler
  insert collectives). The module itself stays mesh-free; an optional
  ``expert_axis`` adds a ``with_sharding_constraint`` hint on the dispatched blocks.
- **Residual overflow.** Tokens past capacity contribute zero from the MoE branch and
  ride the block's residual connection (Switch Transformer semantics).

The router runs in float32 (softmax stability); expert FFNs run in ``dtype``
(bfloat16 by default, MXU-native). The load-balance auxiliary loss is sown into the
``'losses'`` collection — collect with :func:`moe_aux_total`.
"""

import math
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


def _capacity(num_tokens, num_experts, num_selected, capacity_factor):
    cap = int(math.ceil(capacity_factor * num_selected * num_tokens / num_experts))
    return max(1, cap)


def _ambient_mesh_axes():
    """Axis names of the mesh context the caller is tracing under, or None when no
    mesh context is active (plain single-chip execution)."""
    try:
        from jax.sharding import get_abstract_mesh
        mesh = get_abstract_mesh()
        if mesh is not None and mesh.axis_names:
            return set(mesh.axis_names)
    except (ImportError, AttributeError):
        pass
    try:
        # Private-API fallback for older jax: a rename that keeps the module but
        # moves an attribute must degrade to the no-mesh path, not raise from
        # inside every forward pass (ADVICE r3).
        from jax._src.mesh import thread_resources
        mesh = thread_resources.env.physical_mesh
        if mesh.axis_names:
            return set(mesh.axis_names)
    except (ImportError, AttributeError):
        pass
    return None


def _sharding_hint(x, spec_axes):
    """with_sharding_constraint when a mesh context is active. A mesh that exists but
    lacks the named axis raises — silently skipping the constraint would disable
    expert parallelism with no signal. With no ambient mesh at all (single-chip runs,
    or jit driven purely by in_shardings without a mesh context) the hint cannot be
    applied as a bare PartitionSpec; that case warns instead of raising so a model
    configured with ``expert_axis`` still runs unsharded (the default warnings filter
    dedups repeats per call site — no hand-rolled once flag, which would also
    suppress the signal for later, genuinely misconfigured models)."""
    import warnings

    from jax.sharding import PartitionSpec
    axes = _ambient_mesh_axes()
    if axes is None:
        warnings.warn(
            'MoE expert_axis={!r} set but no mesh context is active; the expert '
            'sharding hint was skipped. Trace under `with mesh:` (or jax.set_mesh)'
            ' for expert parallelism.'.format(spec_axes[0]), stacklevel=2)
        return x
    wanted = {a for a in spec_axes if a is not None}
    if not wanted <= axes:
        raise ValueError('expert_axis {} not in ambient mesh axes {}; fix the mesh '
                         'or the MoE expert_axis argument'
                         .format(sorted(wanted - axes), sorted(axes)))
    return lax.with_sharding_constraint(x, PartitionSpec(*spec_axes))


def switch_routing(probs, capacity, num_selected):
    """Top-k routing with static capacity: ``probs [S, X]`` (row-softmax) ->
    ``(dispatch [S, X, C], combine [S, X, C], aux, drop_fraction)``.

    Pure function shared by :class:`MoEMlp` (annotation-based expert parallelism)
    and ``ops.sharded_moe`` (explicit all-to-all under shard_map) so the two
    execution paths can never route differently. Slot-major priority: all
    first-choice assignments win capacity before any second choice (Switch/GShard);
    positions use an int32 cumsum (exact past 2^24 token-slots)."""
    n_tokens, n_exp = probs.shape
    k = num_selected
    gate, expert_idx = lax.top_k(probs, k)                              # [S, k]
    if k > 1:
        gate = gate / jnp.sum(gate, axis=-1, keepdims=True)

    onehot_i = jax.nn.one_hot(expert_idx, n_exp, dtype=jnp.int32)       # [S, k, X]
    flat_i = onehot_i.transpose(1, 0, 2).reshape(k * n_tokens, n_exp)   # slot-major
    flat = flat_i.astype(jnp.float32)
    pos_in_expert = jnp.cumsum(flat_i, axis=0) - flat_i                 # [kS, X]
    position = jnp.sum(pos_in_expert * flat_i, axis=-1)                 # [kS] int32
    assigned = jnp.sum(flat, axis=-1)
    keep = assigned * (position < capacity).astype(jnp.float32)         # [kS]

    pos_onehot = jax.nn.one_hot(position, capacity, dtype=jnp.float32)  # [kS, C]
    dispatch_flat = (flat[:, :, None] * pos_onehot[:, None, :]
                     * keep[:, None, None])                             # [kS, X, C]
    gate_flat = gate.transpose(1, 0).reshape(k * n_tokens)
    combine_flat = dispatch_flat * gate_flat[:, None, None]
    dispatch = dispatch_flat.reshape(k, n_tokens, n_exp, capacity).sum(0)
    combine = combine_flat.reshape(k, n_tokens, n_exp, capacity).sum(0)

    # Switch load-balance loss: X * sum_x f_x * P_x, minimized (=1) when uniform.
    frac_tokens = jnp.mean(onehot_i[:, 0, :].astype(jnp.float32), axis=0)
    mean_probs = jnp.mean(probs, axis=0)
    aux = n_exp * jnp.sum(frac_tokens * mean_probs)
    drop_fraction = 1.0 - jnp.sum(keep) / float(k * n_tokens)
    return dispatch, combine, aux, drop_fraction


class MoEMlp(nn.Module):
    """Top-k routed expert MLP: ``[B, T, D] -> [B, T, D]``.

    Shard ``w1``/``w2`` over their leading experts axis (``expert_partition_specs``)
    for expert parallelism. ``expert_axis`` (optional) names the mesh axis for
    sharding hints on the dispatched activations; leave ``None`` when running
    unsharded (single chip or replicated).
    """

    num_experts: int
    capacity_factor: float = 1.25
    num_selected: int = 1
    hidden_mult: int = 4
    dtype: Any = jnp.bfloat16
    expert_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        batch, seqlen, d = x.shape
        n_tokens = batch * seqlen
        n_exp = self.num_experts
        k = self.num_selected
        if k > n_exp:
            raise ValueError('num_selected={} exceeds num_experts={}'.format(k, n_exp))
        cap = _capacity(n_tokens, n_exp, k, self.capacity_factor)
        hidden = self.hidden_mult * d

        tokens = x.reshape(n_tokens, d)
        # Router in float32: softmax over experts must not run in bf16.
        logits = nn.Dense(n_exp, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32, name='router')(
                              tokens.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)                      # [S, X]
        dispatch, combine, aux, drop_fraction = switch_routing(probs, cap, k)

        w1 = self.param('w1', nn.initializers.lecun_normal(batch_axis=(0,)),
                        (n_exp, d, hidden), jnp.float32)
        w2 = self.param('w2', nn.initializers.lecun_normal(batch_axis=(0,)),
                        (n_exp, hidden, d), jnp.float32)

        compute_dtype = self.dtype
        # init() traces outside any mesh; the hint (and its no-mesh warning) only
        # matters on real forward/backward traces.
        want_hint = self.expert_axis is not None and not self.is_initializing()
        expert_in = jnp.einsum('sd,sxc->xcd', tokens.astype(compute_dtype),
                               dispatch.astype(compute_dtype))          # [X, C, D]
        if want_hint:
            expert_in = _sharding_hint(expert_in, (self.expert_axis, None, None))
        h = jnp.einsum('xcd,xdf->xcf', expert_in, w1.astype(compute_dtype))
        h = nn.gelu(h)
        expert_out = jnp.einsum('xcf,xfd->xcd', h, w2.astype(compute_dtype))
        if want_hint:
            expert_out = _sharding_hint(expert_out, (self.expert_axis, None, None))
        y = jnp.einsum('xcd,sxc->sd', expert_out.astype(jnp.float32),
                       combine.astype(jnp.float32))

        self.sow('losses', 'moe_aux', aux)
        # Diagnostics: fraction of (token, slot) assignments dropped by capacity.
        self.sow('losses', 'moe_drop_fraction', drop_fraction)

        return y.reshape(batch, seqlen, d).astype(x.dtype)


def expert_partition_specs(params, expert_axis='expert'):
    """PartitionSpecs for a pytree of params: MoE expert weights (leading experts
    axis, i.e. param names ``w1``/``w2`` under an ``MoEMlp``) sharded over
    ``expert_axis``, everything else replicated. Feed to ``NamedSharding``/jit."""
    from jax.sharding import PartitionSpec as P

    # Scopes holding a 'router' child: MoEMlp always carries its router Dense beside
    # w1/w2, so a router sibling — not path depth — is the signal that a top-level
    # w1/w2 belongs to a root-module MoEMlp. A non-MoE root module with 3-D params
    # that happen to be named w1/w2 has no router and stays replicated (ADVICE r3).
    router_scopes = set()
    for path, _ in jax.tree_util.tree_flatten_with_path(params)[0]:
        names = tuple(str(getattr(p, 'key', getattr(p, 'name', ''))) for p in path)
        if 'router' in names:
            router_scopes.add(names[:names.index('router')])

    def spec(path, leaf):
        names = [str(getattr(p, 'key', getattr(p, 'name', ''))) for p in path]
        # Expert weights are the 3-D [experts, in, out] leaves named w1/w2 — under a
        # nested MoEMlp_* scope, or beside a router Dense when MoEMlp is the root
        # module. Both the scope and ndim conditions are required: a bare top-level
        # w1/w2 (e.g. stack_stage_params output) must not be captured, and an MoE
        # leaf with extra leading axes (nn.scan / stacked pipeline stages) must fail
        # loudly, not shard the wrong axis.
        in_moe_scope = (any('MoEMlp' in n for n in names)
                        or tuple(names[:-1]) in router_scopes)
        if names and names[-1] in ('w1', 'w2') and in_moe_scope:
            if leaf.ndim == 3:
                return P(expert_axis, *([None] * (leaf.ndim - 1)))
            if any('MoEMlp' in n for n in names):
                raise ValueError(
                    'MoE expert weight {} has ndim {} (expected 3): scanned/stacked '
                    'MoE params need hand-written specs'.format(
                        '/'.join(names), leaf.ndim))
        return P(*([None] * leaf.ndim))

    return jax.tree_util.tree_map_with_path(spec, params)


def collect_sown(mutables, sown_key):
    """Latest sown value of ``sown_key`` from every MoE layer in a ``'losses'``
    collection (as returned by ``model.apply(..., mutable='losses')``) — one entry
    per layer, traced-safe. ``sow`` appends one value per apply, so only each
    tuple's LAST entry belongs to the current step; taking the whole tuple would
    double-count when the collection was threaded through from a previous apply
    (e.g. from ``init``)."""
    losses = mutables.get('losses', mutables)
    leaves = []

    def visit(tree, under_key=False):
        if isinstance(tree, dict):
            for key, sub in tree.items():
                visit(sub, under_key or key == sown_key)
        elif isinstance(tree, (tuple, list)):
            if under_key and tree:
                visit(tree[-1], under_key)
            elif not under_key:
                for sub in tree:
                    visit(sub, under_key)
        elif under_key:
            leaves.append(tree)

    visit(losses)
    return leaves


def moe_aux_total(mutables, weight=1.0):
    """Sum of every MoE layer's latest Switch load-balance loss, scaled by
    ``weight``. Train on ``variables['params']`` only; never feed the init-time
    ``'losses'`` collection to the optimizer."""
    leaves = collect_sown(mutables, 'moe_aux')
    if not leaves:
        return jnp.float32(0)
    return weight * sum(leaves)


def moe_drop_fractions(mutables):
    """Every MoE layer's latest capacity drop fraction (list of scalars; empty when
    the model has no MoE layers)."""
    return collect_sown(mutables, 'moe_drop_fraction')


class MoEBlock(nn.Module):
    """Pre-norm transformer block whose MLP is a routed expert MLP."""

    heads: int
    num_experts: int
    attention_fn: Callable
    capacity_factor: float = 1.25
    num_selected: int = 1
    dtype: Any = jnp.bfloat16
    expert_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        from petastorm_tpu.models.transformer import attention_sublayer
        x = attention_sublayer(x, self.heads, self.attention_fn, self.dtype)
        h = nn.LayerNorm(dtype=jnp.float32)(x).astype(self.dtype)
        return x + MoEMlp(num_experts=self.num_experts,
                          capacity_factor=self.capacity_factor,
                          num_selected=self.num_selected,
                          dtype=self.dtype,
                          expert_axis=self.expert_axis)(h)


class MoETransformerLM(nn.Module):
    """Decoder-only LM with routed-expert MLP blocks: tokens ``[B, T]`` -> logits
    ``[B, T, vocab]`` float32. Every ``moe_every``-th block is MoE (1 = all)."""

    vocab: int = 256
    embed: int = 64
    heads: int = 4
    layers: int = 2
    num_experts: int = 4
    capacity_factor: float = 1.25
    num_selected: int = 1
    moe_every: int = 1
    max_len: int = 8192
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    expert_axis: Optional[str] = None
    remat: bool = False

    @nn.compact
    def __call__(self, tokens, positions=None):
        """``positions`` mirrors TransformerLM: optional [B, T] per-token position
        ids so packed batches restart each document at position 0."""
        from petastorm_tpu.models.transformer import Block, dense_causal_attention
        if self.embed % self.heads != 0:
            raise ValueError('embed={} must be divisible by heads={}'
                             .format(self.embed, self.heads))
        if tokens.shape[1] > self.max_len:
            raise ValueError('sequence length {} exceeds max_len={}'
                             .format(tokens.shape[1], self.max_len))
        attention_fn = self.attention_fn or dense_causal_attention
        # Same remat/naming treatment as TransformerLM: recompute block activations
        # in the backward, with explicit per-class names reproducing the auto scheme
        # so the param tree is identical with and without remat (the sown 'losses'
        # collection passes through nn.remat unchanged).
        dense_cls = nn.remat(Block) if self.remat else Block
        moe_cls = nn.remat(MoEBlock) if self.remat else MoEBlock
        x = nn.Embed(self.vocab, self.embed, dtype=self.dtype)(tokens)
        pos_table = nn.Embed(self.max_len, self.embed, dtype=self.dtype)
        if positions is None:
            x = x + pos_table(jnp.arange(tokens.shape[1]))[None]
        else:
            x = x + pos_table(positions)
        n_moe = n_dense = 0
        for i in range(self.layers):
            if (i + 1) % self.moe_every == 0:
                x = moe_cls(heads=self.heads, num_experts=self.num_experts,
                            capacity_factor=self.capacity_factor,
                            num_selected=self.num_selected,
                            attention_fn=attention_fn, dtype=self.dtype,
                            expert_axis=self.expert_axis,
                            name='MoEBlock_{}'.format(n_moe))(x)
                n_moe += 1
            else:
                x = dense_cls(heads=self.heads, attention_fn=attention_fn,
                              dtype=self.dtype,
                              name='Block_{}'.format(n_dense))(x)
                n_dense += 1
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        return nn.Dense(self.vocab, dtype=jnp.float32)(x)
