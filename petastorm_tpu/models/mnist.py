"""MNIST CNN in flax (consumer model for examples/mnist parity — reference:
examples/mnist/pytorch_example.py:34-54's two-conv net, re-designed for the MXU: NHWC
layout, bfloat16-friendly convs, no data-dependent control flow)."""

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    """Two conv blocks + two dense layers, NHWC."""

    num_classes: int = 10
    dtype: type = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x
