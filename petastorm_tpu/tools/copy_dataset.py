"""Dataset copy tool: column-subset / not-null filter / repartition + re-materialize with
metadata (reference: petastorm/tools/copy_dataset.py:35-153 — Spark job there; a pure
Arrow streaming copy here). Usable as a CLI:
``python -m petastorm_tpu.tools.copy_dataset <source_url> <target_url> [options]``.
"""

import argparse
import logging
import sys

import pyarrow.compute as pc
import pyarrow.dataset as pads
import pyarrow.fs as pa_fs


from petastorm_tpu.etl import dataset_metadata
from petastorm_tpu.unischema import match_unischema_fields

logger = logging.getLogger(__name__)


def copy_dataset(source_url, target_url, field_regex=None, not_null_fields=None,
                 rowgroup_size_mb=32, rows_per_file=None, storage_options=None,
                 overwrite=False):
    """Copy a (petastorm_tpu or petastorm) dataset, optionally selecting a column subset
    and dropping rows with nulls in ``not_null_fields``; the target gets fresh
    metadata. A non-empty target is refused unless ``overwrite=True`` (then deleted
    first) — writing into an existing store would leave stale part files mixed with
    the copy (reference: tools/copy_dataset.py --overwrite-output)."""
    source = dataset_metadata.open_dataset(source_url, storage_options=storage_options)
    schema = dataset_metadata.infer_or_load_unischema(source)
    if field_regex:
        fields = match_unischema_fields(schema, field_regex)
        if not fields:
            raise ValueError('field_regex {} matched no fields of {}'
                             .format(field_regex, list(schema.fields)))
        schema = schema.create_schema_view(fields)
    column_names = list(schema.fields)

    filter_expr = None
    for field_name in (not_null_fields or []):
        expr = ~pc.field(field_name).is_null()
        filter_expr = expr if filter_expr is None else (filter_expr & expr)

    from petastorm_tpu.fs_utils import (delete_path, get_filesystem_and_path_or_paths,
                                        path_exists)
    target_fs, target_path = get_filesystem_and_path_or_paths(
        target_url, storage_options=storage_options)
    if path_exists(target_fs, target_path):
        infos = target_fs.get_file_info(pa_fs.FileSelector(target_path,
                                                           allow_not_found=True))
        if infos and not overwrite:
            raise ValueError('Target {} exists and is not empty; pass '
                             'overwrite=True (--overwrite) to replace it'
                             .format(target_url))
        if infos:
            delete_path(target_fs, target_path)

    with dataset_metadata.materialize_dataset(target_url, schema,
                                              rowgroup_size_mb=rowgroup_size_mb,
                                              storage_options=storage_options):
        target_fs.create_dir(target_path, recursive=True)
        scanner = pads.Scanner.from_dataset(source.arrow_dataset, columns=column_names,
                                            filter=filter_expr)
        # Stream batches -> files: the whole source is never resident in memory.
        total_rows = dataset_metadata.write_table_files(
            target_fs, target_path, scanner.projected_schema, scanner.to_batches(),
            rowgroup_size_mb=rowgroup_size_mb, rows_per_file=rows_per_file)
    logger.info('Copied %d rows to %s', total_rows, target_url)
    return total_rows


def main(argv=None):
    """``petastorm-tpu-copy-dataset`` console entry: re-materialize a store subset
    (field regexes / not-null filter) to a new location (reference:
    tools/copy_dataset.py)."""
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('source_url')
    parser.add_argument('target_url')
    parser.add_argument('--field-regex', nargs='+')
    parser.add_argument('--not-null-fields', nargs='+')
    parser.add_argument('--rowgroup-size-mb', type=int, default=32)
    parser.add_argument('--rows-per-file', type=int)
    parser.add_argument('--overwrite', action='store_true',
                        help='replace a non-empty target instead of refusing')
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    copy_dataset(args.source_url, args.target_url, field_regex=args.field_regex,
                 not_null_fields=args.not_null_fields,
                 rowgroup_size_mb=args.rowgroup_size_mb,
                 rows_per_file=args.rows_per_file, overwrite=args.overwrite)
    return 0


if __name__ == '__main__':
    sys.exit(main())
