"""Operator tools (reference: petastorm/tools/)."""
