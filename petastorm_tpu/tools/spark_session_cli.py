"""argparse plumbing for tools that optionally run against a Spark cluster
(reference: petastorm/tools/spark_session_cli.py — ``--master`` /
``--spark-session-config k=v`` flags feeding a SparkSession builder).

petastorm_tpu's own tools are Arrow-native and do not need Spark, but users
migrating Spark-driven ETL jobs can reuse this helper to keep their CLI
contracts. Importing this module is safe without pyspark; only
:func:`configure_spark` requires it.
"""

import argparse


def add_configure_spark_arguments(parser):
    """Add ``--master`` and ``--spark-session-config`` arguments to ``parser``."""
    group = parser.add_argument_group('spark')
    group.add_argument('--master', type=str, default=None,
                       help='Spark master URL (e.g. local[4]). Default: whatever '
                            'the environment provides.')
    group.add_argument('--spark-session-config', type=str, nargs='+', default=[],
                       metavar='KEY=VALUE',
                       help='Extra SparkSession config entries, each KEY=VALUE.')
    return parser


def _parse_config_pairs(pairs):
    config = {}
    for pair in pairs:
        key, sep, value = pair.partition('=')
        if not sep or not key:
            raise argparse.ArgumentTypeError(
                'spark-session-config entries must be KEY=VALUE, got {!r}'.format(pair))
        config[key] = value
    return config


def configure_spark(builder_or_args, args=None):
    """Apply parsed CLI args to a ``SparkSession.Builder`` and return it.

    Can be called either as ``configure_spark(args)`` (a builder is created) or
    ``configure_spark(builder, args)`` (reference signature shape). Requires
    pyspark.
    """
    if args is None:
        args = builder_or_args
        try:
            from pyspark.sql import SparkSession
        except ImportError:
            raise ImportError('configure_spark requires pyspark, which is not '
                              'installed; pip install pyspark')
        builder = SparkSession.builder
    else:
        builder = builder_or_args
    if getattr(args, 'master', None):
        builder = builder.master(args.master)
    for key, value in _parse_config_pairs(getattr(args, 'spark_session_config', [])).items():
        builder = builder.config(key, value)
    return builder
