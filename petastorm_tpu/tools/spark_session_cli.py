"""argparse plumbing for tools that optionally run against a Spark cluster
(reference: petastorm/tools/spark_session_cli.py — ``--master`` /
``--spark-session-config k=v`` flags feeding a SparkSession builder).

petastorm_tpu's own tools are Arrow-native and do not need Spark, but users
migrating Spark-driven ETL jobs can reuse this helper to keep their CLI
contracts. Importing this module is safe without pyspark; only
:func:`configure_spark` requires it.
"""

import argparse


def _config_pair(pair):
    key, sep, value = pair.partition('=')
    if not sep or not key:
        raise argparse.ArgumentTypeError(
            'spark-session-config entries must be KEY=VALUE, got {!r}'.format(pair))
    return key, value


def add_configure_spark_arguments(parser):
    """Add ``--master`` and ``--spark-session-config`` arguments to ``parser``."""
    group = parser.add_argument_group('spark')
    group.add_argument('--master', type=str, default=None,
                       help='Spark master URL (e.g. local[4]). Default: whatever '
                            'the environment provides.')
    group.add_argument('--spark-session-config', type=_config_pair, nargs='+', default=[],
                       metavar='KEY=VALUE',
                       help='Extra SparkSession config entries, each KEY=VALUE.')
    return parser


def _parse_config_pairs(pairs):
    return dict(_config_pair(p) if isinstance(p, str) else p for p in pairs)


def configure_spark(builder_or_args, args=None):
    """Apply parsed CLI args to a ``SparkSession.Builder`` and return it.

    Can be called either as ``configure_spark(args)`` (a builder is created) or
    ``configure_spark(builder, args)`` (reference signature shape). Requires
    pyspark for the one-argument form.
    """
    if args is None:
        args = builder_or_args
        if hasattr(args, 'config') and hasattr(args, 'getOrCreate'):
            raise TypeError('configure_spark(builder) needs the parsed CLI args too: '
                            'call configure_spark(builder, args)')
        try:
            from pyspark.sql import SparkSession
        except ImportError:
            raise ImportError('configure_spark requires pyspark, which is not '
                              'installed; pip install pyspark')
        builder = SparkSession.builder
    else:
        builder = builder_or_args
    master = getattr(args, 'master', None)
    if isinstance(master, str) and master:
        builder = builder.master(master)
    for key, value in _parse_config_pairs(getattr(args, 'spark_session_config', [])).items():
        builder = builder.config(key, value)
    return builder
