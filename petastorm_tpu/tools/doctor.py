"""Environment doctor: one command that answers "is this install healthy and
what will it be fast at?".

``petastorm-tpu-doctor`` (or ``python -m petastorm_tpu.tools.doctor``) checks,
in order:

1. **Versions** — python / jax / pyarrow / numpy (flax, optax, orbax if present).
2. **Accelerator backend** — probed in a SUBPROCESS with a hard timeout: on
   tunneled deployments backend init can *hang* rather than fail (the axon
   plugin ignores ``JAX_PLATFORMS`` and probes its tunnel at import), and a
   doctor that wedges on the exact condition it exists to diagnose is useless.
3. **Link characterization** — dispatch RTT + H2D/D2H bandwidth
   (:mod:`petastorm_tpu.benchmark.linkprobe`) when a device is up, plus the
   implied per-batch streaming ceiling for a reference 1 KiB row — this is the
   number that says whether streaming or HBM-resident (``scan_epochs``)
   configurations fit today's link.
4. **Store roundtrip** — write a small dataset to a temp dir through the real
   codec/metadata path, read it back with ``make_reader`` across the thread
   pool, verify row integrity, report rows/s.
5. **Pipecheck** — the static data-plane invariant analysis
   (:mod:`petastorm_tpu.analysis`, docs/static-analysis.md) over the
   installed package; findings print as a WARNING (``report['pipecheck']``).
6. **Input service** — when ``--service-url`` (or the
   ``PETASTORM_TPU_SERVICE_URL`` env var) names a disaggregated input
   service (docs/service.md), probe its dispatcher: reachable? workers
   registered? queue depth? An unreachable configured service prints a
   WARNING (``report['service']``) — readers pointed at it will fail.
7. **Topology** — when ``--topology-journal`` (or the
   ``PETASTORM_TPU_TOPOLOGY_JOURNAL`` env var) names an elastic-sharding
   membership journal (docs/robustness.md "Elastic pod-scale sharding"),
   replay it: generation, members, stale leases (WARNING — a host crashed
   without a leave record), torn frames dropped by CRC (WARNING).

Prints a human-readable report; with ``--json``, one machine-readable JSON
line (the same dict :func:`collect_report` returns). Exit code 0 iff the
store roundtrip passed — that is the install-health criterion. Backend DOWN
and link-probe failures are reported as warnings, not failures: they describe
the attached environment (CPU development installs are healthy installs; a
flaky tunnel is the environment's fault, and diagnosing it is this tool's
job, not a reason for it to fail).

The reference ships per-task CLIs (generate-metadata, copy-dataset,
throughput); the doctor composes this repo's equivalents into the first
command to run on a new box.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

# The child honors JAX_PLATFORMS=cpu explicitly: the axon plugin pins the
# platform at import and ignores the env var (same gotcha bench.py handles).
PROBE_CODE = (
    "import os, jax\n"
    "if os.environ.get('JAX_PLATFORMS') == 'cpu':\n"
    "    jax.config.update('jax_platforms', 'cpu')\n"
    "ds = jax.devices()\n"
    "print(ds[0].platform, len(ds))\n")

# Link probe child (r4 advisor, medium): a tunnel that wedges AFTER the backend
# probe subprocess succeeded — or degrades mid-run — used to hang the doctor
# in-process on exactly the condition it exists to diagnose. Same
# subprocess+timeout pattern as check_backend; the tagged last line survives
# plugin banner noise on stdout.
LINK_PROBE_CODE = (
    "import json, os, jax\n"
    "if os.environ.get('JAX_PLATFORMS') == 'cpu':\n"
    "    jax.config.update('jax_platforms', 'cpu')\n"
    "from petastorm_tpu.benchmark.linkprobe import (\n"
    "    probe_link, streaming_ceiling_rows_per_sec)\n"
    "link = probe_link(sizes_mb=(1, 4), dispatch_iters=10, transfer_iters=3)\n"
    "link['streaming_ceiling_rows_per_sec_at_1kib'] = round(\n"
    "    streaming_ceiling_rows_per_sec(link, {row_bytes}, {batch}), 1)\n"
    "print('LINKPROBE_JSON ' + json.dumps(link))\n")


def check_versions():
    """Importable-library report; missing optional libraries are reported, not
    fatal."""
    import numpy
    import pyarrow
    report = {'python': sys.version.split()[0],
              'numpy': numpy.__version__,
              'pyarrow': pyarrow.__version__}
    import importlib
    for name in ('jax', 'flax', 'optax', 'orbax.checkpoint', 'torch',
                 'tensorflow'):
        try:
            # import_module resolves the dotted submodule (orbax.checkpoint's
            # version lives there; the bare orbax namespace package has none)
            mod = importlib.import_module(name)
            report[name.split('.')[0]] = getattr(mod, '__version__', 'present')
        except Exception:  # noqa: BLE001 - absence is information, not error
            report[name.split('.')[0]] = None
    from petastorm_tpu import __version__ as pt_version
    report['petastorm_tpu'] = pt_version
    return report


def _probe_subprocess(code, timeout_s, timeout_detail, env=None):
    """Run probe ``code`` in a subprocess with a hard timeout.

    Returns ``(completed_process, None)`` on a clean exit, else
    ``(None, error_dict)`` with ``status`` 'timeout'/'down' and a ``detail``
    drawn from the child's stderr tail — the shared scaffolding for every
    doctor check that must survive a wedged tunnel."""
    try:
        out = subprocess.run([sys.executable, '-c', code], env=env,
                             capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None, {'status': 'timeout',
                      'detail': timeout_detail.format(timeout_s)}
    if out.returncode != 0:
        return None, {'status': 'down',
                      'detail': out.stderr.strip().splitlines()[-1][:200]
                      if out.stderr.strip() else 'unknown'}
    return out, None


def check_backend(timeout_s=60):
    """Probe ``jax.devices()`` in a subprocess with a hard timeout.

    Returns ``{'status': 'up'|'down'|'timeout', 'platform': ..., 'devices': N}``.
    """
    out, error = _probe_subprocess(
        PROBE_CODE, timeout_s,
        'backend init exceeded {}s — tunneled device unreachable?')
    if error is not None:
        error.update(platform=None, devices=0)
        return error
    # parse the LAST line only: accelerator plugins/libtpu may write banner
    # text to the child's stdout before the probe's own print
    try:
        platform, n = out.stdout.strip().splitlines()[-1].split()
        return {'status': 'up', 'platform': platform, 'devices': int(n)}
    except (IndexError, ValueError):
        return {'status': 'down', 'platform': None, 'devices': 0,
                'detail': 'unparseable probe output: {!r}'.format(
                    out.stdout.strip()[-200:])}


def check_link(reference_row_bytes=1024, reference_batch=1024, timeout_s=180):
    """Link probe + the per-batch streaming ceiling it implies, run in a
    subprocess with a hard timeout (only call when the backend is up).

    A hang — the tunnel's documented failure mode, which can start *between*
    the backend probe and this measurement — is reported as
    ``{'status': 'timeout', ...}``, a link failure, instead of wedging the
    doctor."""
    code = LINK_PROBE_CODE.format(row_bytes=int(reference_row_bytes),
                                  batch=int(reference_batch))
    env = dict(os.environ)
    # the child must find petastorm_tpu even when the doctor runs from a
    # source checkout that was put on sys.path by hand
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    existing = env.get('PYTHONPATH', '')
    # no trailing separator when PYTHONPATH was unset: an empty entry means
    # cwd, where a stray jax.py/json.py would shadow the real module
    env['PYTHONPATH'] = (pkg_root + os.pathsep + existing if existing
                         else pkg_root)
    out, error = _probe_subprocess(
        code, timeout_s,
        'link probe exceeded {}s — tunnel wedged after backend probe?',
        env=env)
    if error is not None:
        if error['status'] == 'down':
            error['status'] = 'fail'  # backend was up; this is a link failure
        return error
    for line in reversed(out.stdout.strip().splitlines()):
        if line.startswith('LINKPROBE_JSON '):
            try:
                return json.loads(line[len('LINKPROBE_JSON '):])
            except ValueError:
                break
    return {'status': 'fail',
            'detail': 'unparseable link probe output: {!r}'.format(
                out.stdout.strip()[-200:])}


def check_store_roundtrip(rows=200, workers=2):
    """Write a real store (scalar + ndarray codecs) to a temp dir, read it back
    through ``make_reader``, verify integrity, report rows/s."""
    import numpy as np
    import pyarrow as pa

    from petastorm_tpu import make_reader
    from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_rows
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('DoctorSchema', [
        UnischemaField('idx', np.int64, (), ScalarCodec(pa.int64()), False),
        UnischemaField('vec', np.float32, (8,), NdarrayCodec(), False),
    ])
    # Flight recorder armed for the roundtrip (docs/observability.md "Flight
    # recorder"): the doctor's trace summary is the per-rowgroup view of the
    # same read the telemetry block aggregates — restored (and the ring
    # cleared) afterwards so the doctor leaves no armed recorder behind.
    from petastorm_tpu.telemetry import tracing
    trace_was_enabled = tracing.trace_enabled()
    try:
        # armed INSIDE the restoring try: a tempdir/write failure must not
        # leave the recorder running process-wide. When the doctor itself arms
        # the recorder, it also clears it first so the summary covers ONLY
        # this roundtrip (a user-armed capture — PETASTORM_TPU_TRACE=1 — is
        # left intact and the summary then spans their whole recording).
        if not trace_was_enabled:
            tracing.reset_tracing()
        tracing.set_trace_enabled(True)
        with tempfile.TemporaryDirectory(prefix='petastorm_tpu_doctor_') as tmp:
            url = 'file://' + tmp
            write_rows(url, schema,
                       ({'idx': i, 'vec': np.full(8, i, np.float32)}
                        for i in range(rows)),
                       rowgroup_size_mb=1)
            start = time.perf_counter()
            seen = []
            # on_error='retry': the roundtrip doubles as a probe of the resilience
            # path — a flaky local disk shows up as a non-zero retry count in the
            # report rather than an opaque failure (docs/robustness.md).
            # autotune armed with a long window (docs/autotuning.md): the
            # roundtrip is far shorter than one control window, so no knob is
            # ever turned — the block proves the controller wires up (knob
            # catalog, breaker interlock state) without perturbing the probe.
            from petastorm_tpu.autotune import AutotunePolicy
            # lineage armed manifest-less (docs/observability.md "Sample
            # lineage"): the block proves the audit plane folds a clean
            # digest with zero divergence on this install, without leaving
            # a manifest file in the temp store.
            from petastorm_tpu.telemetry.lineage import LineagePolicy
            # history armed into a temp store (docs/observability.md
            # "Longitudinal observatory"): the block proves the run
            # historian's append + CRC replay on this install without
            # leaving a store behind.
            hist_path = os.path.join(tmp, 'run_history.bin')
            with make_reader(url, workers_count=workers, num_epochs=1,
                             on_error='retry',
                             lineage=LineagePolicy(manifest=False),
                             history=hist_path,
                             autotune=AutotunePolicy(window_s=3600.0)) as reader:
                for row in reader:
                    seen.append(int(row.idx))
                    if row.vec[0] != row.idx:
                        return {'status': 'fail',
                                'detail': 'row {} decoded wrong vec'.format(row.idx)}
                diag = reader.diagnostics
                telemetry = reader.telemetry_snapshot()
                trace = reader.trace_summary()
                autotune = reader.autotune_report()
                slo = reader.efficiency_report()
                lineage = diag.get('lineage')
                sentinel = diag.get('sentinel')
            elapsed = time.perf_counter() - start
            history = check_history(hist_path, sentinel)
    finally:
        tracing.set_trace_enabled(trace_was_enabled)
        if not trace_was_enabled:
            tracing.reset_tracing()
    if sorted(seen) != list(range(rows)):
        return {'status': 'fail',
                'detail': 'expected {} distinct rows, got {}'.format(
                    rows, len(set(seen)))}
    return {'status': 'ok', 'rows': rows,
            'rows_per_sec': round(rows / elapsed, 1),
            'io_retries': diag.get('io_retries', 0),
            'rowgroups_quarantined': diag.get('rowgroups_quarantined', 0),
            'quarantine': diag.get('quarantine', []),
            'telemetry': telemetry,
            # lifted to report['trace'] by collect_report — the flight-recorder
            # summary of docs/observability.md "Flight recorder"
            'trace': trace,
            # lifted to report['autotune'] by collect_report — the closed-loop
            # controller's state (docs/autotuning.md)
            'autotune': autotune,
            # lifted to report['slo'] by collect_report — the input-efficiency
            # SLO evaluation of docs/observability.md "Efficiency SLOs"
            'slo': slo,
            # lifted to report['lineage'] by collect_report — the sample-
            # lineage audit of docs/observability.md "Sample lineage"
            'lineage': lineage,
            # lifted to report['history'] by collect_report — the run
            # historian + regression sentinel of docs/observability.md
            # "Longitudinal observatory"
            'history': history,
            # lifted to report['resilience'] by collect_report — the hang/
            # integrity/breaker view of docs/robustness.md
            'resilience': {
                'breakers': diag.get('breakers', {}),
                'workers_hung_reaped': diag.get('workers_hung_reaped', 0),
                'shm_crc_failures': diag.get('shm_crc_failures', 0),
                'cache_corrupt_entries':
                    diag.get('cache', {}).get('corrupt_entries', 0),
                'rowgroups_quarantined': diag.get('rowgroups_quarantined', 0),
            }}


def check_storage(rows=64, workers=1):
    """Force-arm the object-store ingest engine (docs/performance.md
    "Object-store ingest engine") over a tiny local store and report its
    counters: footer-cache hits/misses, ranges coalesced away, hedges
    fired/won. On a healthy local disk hedges should essentially never
    fire — the human report WARNS when the hedge-win rate exceeds 50%,
    because storage that tail-heavy means every other fetch is racing a
    straggler and the hedge deadline is doing the store's job."""
    import numpy as np
    import pyarrow as pa

    from petastorm_tpu import make_reader
    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_rows
    from petastorm_tpu.storage import (reset_storage_metrics,
                                       storage_metrics_snapshot)
    from petastorm_tpu.telemetry.registry import (set_telemetry_enabled,
                                                  telemetry_enabled)
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('DoctorStorageSchema', [
        UnischemaField('idx', np.int64, (), ScalarCodec(pa.int64()), False),
        UnischemaField('val', np.float64, (), ScalarCodec(pa.float64()), False),
    ])
    was_enabled = telemetry_enabled()
    set_telemetry_enabled(True)   # counters are gated on the kill switch
    reset_storage_metrics()       # this probe's reads only
    try:
        with tempfile.TemporaryDirectory(prefix='petastorm_tpu_doctor_') as tmp:
            url = 'file://' + tmp
            write_rows(url, schema,
                       ({'idx': i, 'val': float(i)} for i in range(rows)),
                       rowgroup_size_mb=1)
            seen = []
            # storage_policy=True force-arms the engine on the local store
            # (auto-engage is non-local-schemes only); two epochs so the
            # second one exercises the footer cache's hit path.
            with make_reader(url, workers_count=workers, num_epochs=2,
                             storage_policy=True) as reader:
                for row in reader:
                    seen.append(int(row.idx))
        counters = storage_metrics_snapshot().get('counters', {})
    finally:
        set_telemetry_enabled(was_enabled)
        reset_storage_metrics()   # don't leak probe counts into real reads
    if sorted(set(seen)) != list(range(rows)):
        return {'status': 'fail',
                'detail': 'engine-armed read returned {} distinct rows, '
                          'expected {}'.format(len(set(seen)), rows)}
    fired = int(counters.get('storage_hedge_fired', 0))
    won = int(counters.get('storage_hedge_won', 0))
    return {'status': 'ok',
            'footer_cache_hits': int(counters.get('storage_footer_cache_hit', 0)),
            'footer_cache_misses': int(counters.get('storage_footer_cache_miss', 0)),
            'ranges_coalesced': int(counters.get('storage_ranges_coalesced', 0)),
            'hedges_fired': fired,
            'hedges_won': won,
            'hedge_win_rate': round(won / fired, 3) if fired else 0.0}


def check_service(service_url=None, timeout_s=2.0):
    """Probe the disaggregated input service (docs/service.md) when one is
    configured — ``service_url`` argument or the ``PETASTORM_TPU_SERVICE_URL``
    env var. Returns ``{'status': 'unconfigured'}`` when no URL is set,
    ``{'status': 'ok', 'workers': N, 'clients': N, 'queue_depth': N, ...}``
    when the dispatcher answers a state request, or ``{'status':
    'unreachable', 'detail': ...}`` — which the human report prints as a
    WARNING: a reader pointed at that URL will fail its hello."""
    url = service_url or os.environ.get('PETASTORM_TPU_SERVICE_URL')
    if not url:
        return {'status': 'unconfigured'}
    # tripped client-transport breakers registered by any ServicePool this
    # process created (they live on the default board so they surface here
    # and in Reader.diagnostics through one mechanism)
    from petastorm_tpu.resilience import default_board
    breakers = {name: state for name, state
                in default_board().snapshot(only_tripped=True).items()
                if name.startswith('service:')}
    try:
        from petastorm_tpu.service.service_client import fetch_service_state
        state = fetch_service_state(url, timeout_s=timeout_s)
    except Exception as exc:  # noqa: BLE001 - unreachability is the finding, not a doctor failure
        return {'status': 'unreachable', 'service_url': url,
                'detail': repr(exc), 'breakers': breakers}
    workers = state.get('workers') or []
    return {'status': 'ok', 'service_url': url,
            'workers': len(workers),
            'clients': len(state.get('clients') or []),
            'queue_depth': state.get('queue_depth', 0),
            'in_flight': state.get('in_flight', 0),
            'busy_rejections': state.get('busy_rejections', 0),
            'items_requeued': state.get('items_requeued', 0),
            'workers_departed': state.get('workers_departed', 0),
            'breakers': breakers,
            'state': state}


def check_pipecheck():
    """Run the pipecheck static analysis over the installed package
    (docs/static-analysis.md) and summarize: ``{'status': 'ok'|'findings',
    'findings': N, 'suppressed': M, 'files': F, 'by_rule': {...}}``.

    Static findings mean the *installed code* has drifted from its own
    data-plane invariants (protocol kinds, telemetry names, the mypy
    ratchet) — a WARNING in the human report, not an install-health failure:
    reads still work, but the next refactor is flying blind."""
    from petastorm_tpu.analysis import run_pipecheck
    report = run_pipecheck()
    return {'status': 'ok' if report.clean else 'findings',
            'findings': len(report.findings),
            'suppressed': report.suppressed,
            'files': report.files,
            'callgraph_functions': report.callgraph_functions,
            'by_rule': report.by_rule(),
            'first': report.findings[0].format() if report.findings else None}


def collect_report(probe_timeout_s=60, link=True, link_timeout_s=180,
                   service_url=None, topology_journal=None):
    """Run every check; returns the full report dict (no printing)."""
    report = {'versions': check_versions()}
    report['backend'] = check_backend(timeout_s=probe_timeout_s)
    if link and report['backend']['status'] == 'up':
        try:
            report['link'] = check_link(timeout_s=link_timeout_s)
        except Exception as exc:  # noqa: BLE001 - link probe is best-effort
            report['link'] = {'status': 'fail', 'detail': repr(exc)}
    try:
        report['store_roundtrip'] = check_store_roundtrip()
    except Exception as exc:  # noqa: BLE001 - the report must always complete
        report['store_roundtrip'] = {'status': 'fail', 'detail': repr(exc)}
    # Pipeline telemetry (docs/observability.md): the roundtrip reader's
    # cross-process stage snapshot + the bottleneck attribution it implies —
    # the doctor's answer to "what will this install's input pipeline be slow
    # at". Lifted to report level so --json consumers find one stable key.
    snapshot = report['store_roundtrip'].pop('telemetry', None)
    if snapshot is not None:
        from petastorm_tpu.telemetry.analyze import attribute_bottleneck
        report['telemetry'] = {'snapshot': snapshot,
                               'bottleneck': attribute_bottleneck(snapshot)}
    # Flight-recorder block (docs/observability.md "Flight recorder"): event
    # counts, dropped-event count, anomaly instants and the top-5 longest
    # rowgroup traces of the roundtrip read. Always present so --json
    # consumers find one stable key.
    trace = report['store_roundtrip'].pop('trace', None)
    if trace is None:
        # one stable schema either way: the empty summary IS the summarizer's
        # own empty-snapshot output, so the two paths cannot drift apart
        from petastorm_tpu.telemetry.trace_export import summarize_trace
        trace = summarize_trace({})
    report['trace'] = trace
    # Resilience block (docs/robustness.md): breaker states + hung-reap/corrupt
    # counts, lifted to report level so --json consumers find one stable key.
    # Always present — dashboards alert on it without key-existence checks.
    resilience = report['store_roundtrip'].pop('resilience', None)
    report['resilience'] = resilience if resilience is not None else {
        'breakers': {}, 'workers_hung_reaped': 0, 'shm_crc_failures': 0,
        'cache_corrupt_entries': 0, 'rowgroups_quarantined': 0}
    # Autotune block (docs/autotuning.md): the roundtrip controller's state —
    # knob catalog, decision log, frozen-by-breaker flag. Always present so
    # --json consumers find one stable key.
    autotune = report['store_roundtrip'].pop('autotune', None)
    report['autotune'] = autotune if autotune is not None else {
        'enabled': False}
    # Input-efficiency SLO block (docs/observability.md "Efficiency SLOs"):
    # the roundtrip reader's efficiency-vs-target evaluation. Always present
    # so --json consumers find one stable key.
    slo = report['store_roundtrip'].pop('slo', None)
    report['slo'] = slo if slo is not None else {'evaluated': False}
    # Sample-lineage block (docs/observability.md "Sample lineage &
    # determinism audit"): the roundtrip reader's order digest + divergence
    # count. Always present so --json consumers find one stable key.
    lineage = report['store_roundtrip'].pop('lineage', None)
    report['lineage'] = lineage if lineage is not None else {
        'enabled': False}
    # Longitudinal-observatory block (docs/observability.md "Longitudinal
    # observatory"): the roundtrip's run-history store replayed — record
    # landed, zero CRC drops, sentinel armed. Always present so --json
    # consumers find one stable key.
    history = report['store_roundtrip'].pop('history', None)
    report['history'] = history if history is not None else {
        'status': 'unprobed', 'records': 0, 'frames_dropped': 0,
        'sentinel_armed': False}
    # Static-analysis block (docs/static-analysis.md): does the installed
    # package still satisfy its own data-plane invariants? Always present so
    # --json consumers find one stable key; failures of the analyzer itself
    # are reported, never fatal to the doctor.
    try:
        report['pipecheck'] = check_pipecheck()
    except Exception as exc:  # noqa: BLE001 - the report must always complete
        report['pipecheck'] = {'status': 'fail', 'detail': repr(exc)}
    # Input-service block (docs/service.md): when PETASTORM_TPU_SERVICE_URL
    # (or --service-url) names a dispatcher, is it reachable and how does its
    # fleet look? Always present so --json consumers find one stable key;
    # an unconfigured service is a healthy install.
    try:
        report['service'] = check_service(service_url)
    except Exception as exc:  # noqa: BLE001 - the report must always complete
        report['service'] = {'status': 'fail', 'detail': repr(exc)}
    # Durable-ledger block (docs/service.md "Failure modes"): when the
    # probed dispatcher journals its token lifecycle, how did its last
    # restart go — journal present, last replay result, frames dropped by
    # CRC? Always present so --json consumers find one stable key.
    try:
        report['ledger'] = check_ledger(report.get('service'))
    except Exception as exc:  # noqa: BLE001 - the report must always complete
        report['ledger'] = {'status': 'fail', 'detail': repr(exc)}
    # Topology block (docs/robustness.md "Elastic pod-scale sharding"): when
    # PETASTORM_TPU_TOPOLOGY_JOURNAL (or --topology-journal) names a
    # membership journal, the replayed pod view — generation, members, stale
    # leases, CRC drops. Always present so --json consumers find one stable
    # key; an unarmed topology is a healthy install.
    try:
        report['topology'] = check_topology(topology_journal)
    except Exception as exc:  # noqa: BLE001 - the report must always complete
        report['topology'] = {'status': 'fail', 'detail': repr(exc)}
    # Incident-bundle block (docs/observability.md "Incident autopsy
    # plane"): retained black-box bundles in the default incident home (or
    # PETASTORM_TPU_INCIDENT_HOME) — each one is a captured failure edge
    # awaiting `petastorm-tpu-throughput autopsy`. Always present so --json
    # consumers find one stable key.
    try:
        report['incidents'] = check_incidents()
    except Exception as exc:  # noqa: BLE001 - the report must always complete
        report['incidents'] = {'status': 'fail', 'detail': repr(exc)}
    # Object-store ingest block (docs/performance.md "Object-store ingest
    # engine"): a force-armed engine read over a local store — footer-cache
    # hit/miss, ranges coalesced, hedges fired/won. Always present so --json
    # consumers find one stable key.
    try:
        report['storage'] = check_storage()
    except Exception as exc:  # noqa: BLE001 - the report must always complete
        report['storage'] = {'status': 'fail', 'detail': repr(exc)}
    report['healthy'] = report['store_roundtrip'].get('status') == 'ok'
    return report


def check_ledger(service_report=None):
    """The probed dispatcher's durable-ledger health (docs/service.md
    "Failure modes"), derived from the ``check_service`` state snapshot:
    ``{'status': 'unarmed'}`` when no service is configured or the
    dispatcher runs without a ledger, else journal path, ledger epoch,
    the last replay result (``ok`` / ``corrupt`` / ``absent`` /
    ``discarded``) and the CRC-dropped frame count — a nonzero drop count
    means a past restart degraded to replay-from-clients."""
    state = ((service_report or {}).get('state') or {}).get('ledger') or {}
    if not state.get('armed'):
        return {'status': 'unarmed'}
    return {'status': 'ok',
            'path': state.get('path'),
            'epoch': state.get('epoch'),
            'last_replay': state.get('last_replay'),
            'frames_dropped': state.get('frames_dropped', 0),
            'records_replayed': state.get('records_replayed', 0)}


def check_history(path, sentinel=None):
    """Replay the roundtrip's run-history store (docs/observability.md
    "Longitudinal observatory"): record count, CRC-dropped frames, the
    newest record's headline rows/s, and the sentinel's armed state — a
    nonzero drop count means a past append was torn and the store healed
    around it."""
    from petastorm_tpu.telemetry.history import load_records
    records, dropped = load_records(path)
    block = {'status': 'ok' if records and not dropped else 'degraded',
             'records': len(records), 'frames_dropped': dropped,
             'sentinel_armed': bool(sentinel)}
    if records:
        newest = records[-1]
        block['rows_per_sec'] = newest.get('rows_per_sec')
        block['platform'] = newest.get('platform')
    return block


def check_topology(journal_path=None):
    """Replay the elastic-sharding membership journal (docs/robustness.md
    "Elastic pod-scale sharding") when one is named — ``journal_path``
    argument or the ``PETASTORM_TPU_TOPOLOGY_JOURNAL`` env var. Returns
    ``{'status': 'unarmed'}`` when no journal is configured,
    ``{'status': 'absent', ...}`` when the path does not exist yet, else
    the replayed membership view: generation, live members, stale leases
    (hosts whose lease expired without a leave — reshard candidates) and
    the CRC-dropped frame count."""
    path = journal_path or os.environ.get('PETASTORM_TPU_TOPOLOGY_JOURNAL')
    if not path:
        return {'status': 'unarmed'}
    from petastorm_tpu.parallel.topology import replay_topology_journal
    replay = replay_topology_journal(path)
    if replay.result == 'absent':
        return {'status': 'absent', 'path': path}
    stale = replay.stale_leases(time.time())
    return {'status': replay.result, 'path': path,
            'generation': replay.generation,
            'members': sorted(replay.members),
            'stale_leases': stale,
            'delivered': len(replay.delivered),
            'resharded': replay.resharded,
            'frames_dropped': replay.frames_dropped,
            'records': replay.records}


def check_incidents(home=None):
    """Scan the incident home for retained bundles (newest first): the
    doctor's view of the incident autopsy plane — bundle names, trigger
    kinds and ranked causes, without opening the heavyweight evidence."""
    from petastorm_tpu.telemetry.incident import (default_incident_home,
                                                  scan_bundles)
    home = home or default_incident_home(None)
    bundles = scan_bundles(home)
    return {'status': 'ok', 'home': home, 'retained': len(bundles),
            'bundles': bundles[:8]}


def _print_human(report):
    v = report['versions']
    print('petastorm-tpu doctor')
    print('  versions: petastorm_tpu {} / python {} / jax {} / pyarrow {}'
          .format(v['petastorm_tpu'], v['python'], v['jax'], v['pyarrow']))
    optional = ', '.join('{} {}'.format(k, v[k]) for k in
                         ('flax', 'optax', 'orbax', 'torch', 'tensorflow')
                         if v.get(k))
    if optional:
        print('  optional: ' + optional)
    b = report['backend']
    if b['status'] == 'up':
        print('  backend: UP — {} x{}'.format(b['platform'], b['devices']))
    else:
        print('  backend: {} ({}) — CPU development still works; streaming '
              'benchmarks need the device'.format(
                  b['status'].upper(), b.get('detail', '')))
    link = report.get('link')
    if link and 'dispatch_rtt_ms' in link:
        print('  link: RTT {} ms, H2D {} MB/s, D2H {} MB/s -> streaming '
              'ceiling ~{} rows/s at 1 KiB rows'.format(
                  link['dispatch_rtt_ms'], link['h2d_mbytes_per_sec'],
                  link['d2h_mbytes_per_sec'],
                  link['streaming_ceiling_rows_per_sec_at_1kib']))
    elif link:
        print('  link: FAIL ({}) — device up but unmeasurable; expect '
              'streaming anomalies'.format(link.get('detail', 'unknown')))
    s = report['store_roundtrip']
    if s.get('status') == 'ok':
        print('  store roundtrip: OK — {} rows at {} rows/s'.format(
            s['rows'], s['rows_per_sec']))
        if s.get('io_retries') or s.get('rowgroups_quarantined'):
            print('  resilience: {} transient-IO retries, {} rowgroups quarantined '
                  '— local reads should never need these; check the disk'.format(
                      s.get('io_retries', 0), s.get('rowgroups_quarantined', 0)))
    else:
        print('  store roundtrip: FAIL — {}'.format(s.get('detail')))
    telemetry = report.get('telemetry')
    if telemetry and telemetry['bottleneck'].get('top_stage'):
        b = telemetry['bottleneck']
        print('  telemetry: top stage {} ({:.0%} of {:.3f}s stage time) -> {}'
              .format(b['top_stage'], b['top_share'],
                      b.get('total_stage_seconds', 0.0), b['recommendation']))
    slo = report.get('slo') or {}
    if slo.get('evaluated'):
        print('  input efficiency: {:.1%} (target {:.0%}; consumer waited '
              '{:.3f}s of {:.3f}s)'.format(
                  slo.get('efficiency', 0.0),
                  slo.get('target_efficiency', 0.0),
                  slo.get('wait_seconds', 0.0), slo.get('elapsed_s', 0.0)))
        if slo.get('breached'):
            print('  WARNING: input efficiency is BELOW the SLO target — '
                  'the consumer sat starved {:.0%} of the time; see the '
                  'telemetry bottleneck line for the knob to turn '
                  '(docs/observability.md "Efficiency SLOs")'.format(
                      slo.get('starvation_fraction', 0.0)))
    lineage = report.get('lineage') or {}
    if lineage.get('enabled'):
        print('  lineage: digest {}… over {} item(s), {} pending, '
              '{} divergence event(s)'.format(
                  (lineage.get('order_digest') or '')[:12],
                  lineage.get('items_folded', 0),
                  lineage.get('pending_items', 0),
                  lineage.get('divergence', 0)))
        if lineage.get('divergence'):
            last = lineage.get('last_divergence') or {}
            print('  WARNING: sample-lineage verification FAILED {} time(s) '
                  '(last: {} — {}) — the delivered stream broke its expected '
                  'order; reproducibility is not provable for this run '
                  '(docs/observability.md "Sample lineage")'.format(
                      lineage.get('divergence'), last.get('reason'),
                      last.get('detail')))
    history = report.get('history') or {}
    if history.get('status') != 'unprobed':
        print('  history: {} run record(s) replayed ({} CRC-dropped '
              'frame(s)), sentinel {}'.format(
                  history.get('records', 0),
                  history.get('frames_dropped', 0),
                  'armed' if history.get('sentinel_armed') else 'unarmed'))
        if history.get('frames_dropped'):
            print('  WARNING: the run-history store dropped torn frame(s) '
                  'on replay — a past append was interrupted; the store '
                  'heals on the next append (docs/observability.md '
                  '"Longitudinal observatory")')
    trace = report.get('trace') or {}
    if trace.get('events'):
        anomalies = trace.get('anomaly_instants') or []
        slowest = (trace.get('top_rowgroup_traces') or [{}])[0]
        print('  trace: {} event(s) across {} process(es), {} rowgroup '
              'trace(s), {} dropped; {} anomaly instant(s){}'.format(
                  trace.get('events', 0), len(trace.get('processes', [])),
                  trace.get('rowgroups_traced', 0),
                  trace.get('dropped_events', 0), len(anomalies),
                  '; slowest rowgroup {} at {} ms'.format(
                      slowest.get('rowgroup'), slowest.get('duration_ms'))
                  if slowest else ''))
    resilience = report.get('resilience') or {}
    open_breakers = sorted(
        name for name, state in (resilience.get('breakers') or {}).items()
        if state.get('state') != 'closed')
    if open_breakers:
        print('  WARNING: circuit breaker(s) not closed: {} — a dependency is '
              'being routed around; reads are degraded, not broken '
              '(docs/robustness.md)'.format(', '.join(open_breakers)))
    degraded = {key: resilience.get(key, 0)
                for key in ('workers_hung_reaped', 'shm_crc_failures',
                            'cache_corrupt_entries')
                if resilience.get(key, 0)}
    if degraded:
        print('  resilience: {} — the roundtrip needed hang/corruption '
              'recovery on a local disk; check the hardware'.format(
                  ', '.join('{}={}'.format(k, v) for k, v in sorted(degraded.items()))))
    autotune = report.get('autotune') or {}
    if autotune.get('enabled'):
        decisions = autotune.get('decisions') or []
        line = '  autotune: {} knob(s) catalogued, {} window(s), {} decision(s)' \
            .format(len(autotune.get('knobs') or {}),
                    autotune.get('windows', 0), len(decisions))
        if decisions:
            last = decisions[-1]
            line += '; last: {} {}'.format(last.get('action'),
                                           last.get('knob') or '')
        print(line)
        if autotune.get('frozen_by_breaker'):
            print('  WARNING: autotune is FROZEN by an open circuit breaker — '
                  'the controller reverted its last change and will not retune '
                  'until the board is healthy (docs/autotuning.md)')
    service = report.get('service') or {}
    if service.get('status') == 'ok':
        print('  service: {} — {} worker(s), {} client(s), queue depth {} '
              '(docs/service.md)'.format(
                  service.get('service_url'), service.get('workers', 0),
                  service.get('clients', 0), service.get('queue_depth', 0)))
        if service.get('workers', 0) == 0:
            print('  WARNING: input service at {} has NO registered decode '
                  'workers — readers pointed at it will stall until workers '
                  'join'.format(service.get('service_url')))
    elif service.get('status') == 'unreachable':
        print('  WARNING: input service at {} is UNREACHABLE ({}) — readers '
              'with this service_url will fail their hello; is the '
              'dispatcher running? (docs/service.md)'.format(
                  service.get('service_url'), service.get('detail', '')))
    ledger = report.get('ledger') or {}
    if ledger.get('status') == 'ok':
        print('  ledger: armed at {} — epoch {}, last replay {} ({} '
              'record(s), {} frame(s) CRC-dropped) (docs/service.md '
              '"Failure modes")'.format(
                  ledger.get('path'), ledger.get('epoch'),
                  ledger.get('last_replay'),
                  ledger.get('records_replayed', 0),
                  ledger.get('frames_dropped', 0)))
        if ledger.get('frames_dropped'):
            print('  WARNING: the dispatcher ledger dropped {} journal '
                  'frame(s) on its last replay — a restart degraded to '
                  'replay-from-clients; inspect the journal and any '
                  'ledger_corrupt incident bundle'.format(
                      ledger.get('frames_dropped')))
    topology = report.get('topology') or {}
    if topology.get('status') in ('ok', 'corrupt'):
        print('  topology: journal {} — generation {}, {} member(s), {} '
              'item(s) journaled delivered, {} reshard(s) '
              '(docs/robustness.md "Elastic pod-scale sharding")'.format(
                  topology.get('path'), topology.get('generation'),
                  len(topology.get('members') or []),
                  topology.get('delivered', 0),
                  topology.get('resharded', 0)))
        if topology.get('stale_leases'):
            print('  WARNING: topology member(s) with EXPIRED leases and no '
                  'leave record: {} — they look crashed or partitioned; '
                  'survivors should reshard their undelivered remainder '
                  '(`petastorm-tpu-throughput chaos --hosts N --kill-host` '
                  'rehearses exactly this)'.format(
                      ', '.join(sorted(topology.get('stale_leases')))))
        if topology.get('frames_dropped'):
            print('  WARNING: the membership journal dropped {} torn '
                  'frame(s) on replay — a past append was interrupted; '
                  'membership resumed from the intact prefix '
                  '(docs/robustness.md)'.format(
                      topology.get('frames_dropped')))
    elif topology.get('status') == 'absent':
        print('  topology: journal {} configured but not created yet — no '
              'topology-armed reader has opened it'.format(
                  topology.get('path')))
    incidents = report.get('incidents') or {}
    if incidents.get('retained'):
        newest = (incidents.get('bundles') or [{}])[0]
        print('  WARNING: {} incident bundle(s) retained in {} (newest: {} — '
              'cause {}) — a failure edge black-boxed its evidence; run '
              '`petastorm-tpu-throughput autopsy {}` for the ranked '
              'probable-cause report (docs/observability.md "Incident '
              'autopsy plane")'.format(
                  incidents.get('retained'), incidents.get('home'),
                  newest.get('bundle'), newest.get('cause'),
                  newest.get('path', '<bundle>')))
    storage = report.get('storage') or {}
    if storage.get('status') == 'ok':
        print('  storage engine: footer cache {} hit(s) / {} miss(es), {} '
              'range(s) coalesced, hedges {} fired / {} won '
              '(docs/performance.md "Object-store ingest engine")'.format(
                  storage.get('footer_cache_hits', 0),
                  storage.get('footer_cache_misses', 0),
                  storage.get('ranges_coalesced', 0),
                  storage.get('hedges_fired', 0),
                  storage.get('hedges_won', 0)))
        if storage.get('hedges_fired', 0) and \
                storage.get('hedge_win_rate', 0.0) > 0.5:
            print('  WARNING: hedge-win rate is {:.0%} — storage is '
                  'tail-heavy; more than half the hedged duplicates beat '
                  'the primary GET, so the hedge deadline is doing the '
                  "store's job. Investigate the backing filesystem before "
                  'trusting throughput numbers'.format(
                      storage.get('hedge_win_rate', 0.0)))
    elif storage:
        print('  storage engine: FAIL ({}) — the force-armed probe read '
              'errored'.format(storage.get('detail', 'unknown')))
    pipecheck = report.get('pipecheck') or {}
    if pipecheck.get('status') == 'ok':
        print('  pipecheck: clean — {} files, {} call-graph function(s), '
              '{} suppression(s) honored (docs/static-analysis.md)'.format(
                  pipecheck.get('files', 0),
                  pipecheck.get('callgraph_functions', 0),
                  pipecheck.get('suppressed', 0)))
    elif pipecheck.get('status') == 'findings':
        print('  WARNING: pipecheck found {} data-plane invariant '
              'violation(s) ({}); first: {} — run '
              '`petastorm-tpu-pipecheck` for the full list'.format(
                  pipecheck.get('findings', 0),
                  ', '.join('{}={}'.format(rule, count) for rule, count
                            in sorted(pipecheck.get('by_rule', {}).items())),
                  pipecheck.get('first')))
    elif pipecheck:
        print('  pipecheck: FAIL ({}) — the analyzer itself errored'.format(
            pipecheck.get('detail', 'unknown')))
    print('  verdict: {}'.format('healthy' if report['healthy'] else 'BROKEN'))


def main(argv=None):
    """CLI: run all checks, print the report, exit 0 iff healthy."""
    parser = argparse.ArgumentParser(
        description='petastorm-tpu environment doctor')
    parser.add_argument('--json', action='store_true',
                        help='print one machine-readable JSON line instead')
    parser.add_argument('--probe-timeout', type=int, default=60,
                        help='backend probe subprocess timeout (seconds)')
    parser.add_argument('--link-timeout', type=int, default=180,
                        help='link probe subprocess timeout (seconds)')
    parser.add_argument('--no-link', action='store_true',
                        help='skip the link bandwidth probe')
    parser.add_argument('--service-url', default=None,
                        help='probe this input-service dispatcher (default: '
                             'the PETASTORM_TPU_SERVICE_URL env var; unset = '
                             'skip)')
    parser.add_argument('--topology-journal', default=None,
                        help='replay this elastic-sharding membership '
                             'journal (default: the '
                             'PETASTORM_TPU_TOPOLOGY_JOURNAL env var; '
                             'unset = skip)')
    args = parser.parse_args(argv)
    report = collect_report(probe_timeout_s=args.probe_timeout,
                            link=not args.no_link,
                            link_timeout_s=args.link_timeout,
                            service_url=args.service_url,
                            topology_journal=args.topology_journal)
    if args.json:
        print(json.dumps(report))
    else:
        _print_human(report)
    return 0 if report['healthy'] else 1


if __name__ == '__main__':
    sys.exit(main())
