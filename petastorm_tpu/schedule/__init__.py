"""Cost-aware sample scheduling (docs/performance.md "Cost-aware
scheduling"): consume the persisted per-rowgroup
:class:`~petastorm_tpu.telemetry.cost_model.CostLedger` to interleave heavy
and light rowgroups deterministically, split oversized rowgroups into
sub-range work items, pre-stage predicted-slow items, and price service
submits for the dispatcher's measured-cost DRR. Armed with
``make_reader(cost_schedule=...)``; off by default (byte-identical path)."""

from petastorm_tpu.schedule.cost_schedule import (MAX_COST_HINT,
                                                  MIN_COST_HINT,
                                                  CostAwareScheduler,
                                                  SchedulePolicy, load_ledger,
                                                  plan_preview,
                                                  resolve_schedule_policy)

__all__ = ['CostAwareScheduler', 'SchedulePolicy', 'load_ledger',
           'plan_preview', 'resolve_schedule_policy', 'MIN_COST_HINT',
           'MAX_COST_HINT']
