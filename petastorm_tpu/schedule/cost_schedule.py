"""Cost-aware sample scheduling: consume the persisted ``CostLedger`` to
decide WHEN and WHERE each rowgroup is processed (docs/performance.md
"Cost-aware scheduling").

Decode cost per rowgroup is wildly skewed (the image-vs-scalar spread in
``decode_bench`` is ~100x): under FIFO or a uniform shuffle, one p99 rowgroup
stalls the batch former — and the train step behind it — while the rest of
the fleet idles. PR 11 shipped the measurement half (the persistent
per-rowgroup :class:`~petastorm_tpu.telemetry.cost_model.CostLedger`); this
module is the scheduling half, closing the loop from measured cost to actual
dispatch order (MinatoLoader's slow/fast segregation + tf.data's
measured-cost pipeline optimization, PAPERS.md):

- **interleave** — :meth:`CostAwareScheduler.order_items` reorders each
  epoch's ventilation so heavy and light rowgroups alternate: heavies are
  spread at evenly spaced slots through the epoch instead of wherever the
  uniform shuffle dropped them, so the results queue drains smoothly. The
  reorder is a *seeded cost-balanced shuffle*: the same seed + the same
  ledger produce the same order on every pool (thread/process/service), and
  with no ledger the order is byte-identical to the plain seeded shuffle.
- **pre-stage** — each heavy item occupies the EARLIEST slot of its
  interleave window (position 0 ships the heaviest rowgroup of the epoch),
  so predicted-slow items enter the pool ahead of the batch deadline that
  would otherwise wait on them.
- **split** — :meth:`CostAwareScheduler.plan_items` turns a rowgroup whose
  measured cost crosses ``split_threshold`` x median into several sub-range
  work items (a ``row_range=(start_row, stop_row)`` coordinate threaded
  through ``reader_worker.process``), so one oversized rowgroup is decoded
  by several workers concurrently instead of serializing one.
- **route** — :meth:`CostAwareScheduler.cost_hint_for` prices each work item
  for the service path: the client ships the normalized cost with every
  ``submit`` and the dispatcher's DRR charges measured cost instead of a
  uniform unit, routing heavy items to the least-loaded workers
  (``service/dispatcher.py``).

Cold start: with no persisted ledger every cost is uniform — the plan is a
no-op and the read is byte-identical to an unscheduled reader — while the
reader feeds the live ledger from the per-batch telemetry sidecars it already
receives; :meth:`CostAwareScheduler.persist` folds those observations into
the sidecar file at ``Reader.stop`` so the NEXT run schedules from data. The
plan itself is frozen at construction (pure function of ledger + seed), so
ventilation order never depends on runtime timing — determinism is the
contract tests pin.

This module is deliberately wall-clock-free (pipecheck's clock-discipline
rule enforces it): scheduling decisions must be a pure function of the
ledger, the policy and the seed, never of when they were computed.
"""

from __future__ import annotations

import logging
import math
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Mapping, Optional, Tuple

#: the cost-hint clamp is a two-sided wire contract — the dispatcher
#: re-clamps with the SAME bounds, so they live in the wire module
from petastorm_tpu.service.wire import MAX_COST_HINT, MIN_COST_HINT
from petastorm_tpu.telemetry.cost_model import (COST_STAGES, CostLedger,
                                                default_ledger_path,
                                                percentile)
from petastorm_tpu.telemetry.tracing import trace_enabled, trace_instant

logger = logging.getLogger(__name__)

#: how many recent epoch orders :meth:`CostAwareScheduler.report` retains
_ORDER_HISTORY = 8


@dataclass(frozen=True)
class SchedulePolicy:
    """Frozen cost-aware scheduling policy (docs/performance.md knob table).

    ``heavy_skew`` and ``split_threshold`` are in units of the ledger's
    MEDIAN rowgroup cost: a rowgroup costing ``>= heavy_skew x median`` is
    interleave-spread (and pre-staged), one costing ``>= split_threshold x
    median`` is split into up to ``split_max`` sub-range work items (never
    below ``min_split_rows`` rows per part). ``ledger_path`` overrides where
    the persisted ledger sidecar is read from and written to (default: the
    :func:`~petastorm_tpu.telemetry.cost_model.default_ledger_path`
    location next to the disk cache / a local dataset)."""

    interleave: bool = True
    prestage: bool = True
    split: bool = True
    heavy_skew: float = 2.0
    split_threshold: float = 4.0
    split_max: int = 4
    min_split_rows: int = 1
    ledger_path: Optional[str] = None

    def __post_init__(self) -> None:
        if self.heavy_skew <= 1.0:
            raise ValueError('heavy_skew must be > 1.0 (a rowgroup at the '
                             'median is not heavy), got {!r}'
                             .format(self.heavy_skew))
        if self.split_threshold < self.heavy_skew:
            raise ValueError('split_threshold must be >= heavy_skew '
                             '(splitting is the stronger intervention), got '
                             '{!r} < {!r}'.format(self.split_threshold,
                                                  self.heavy_skew))
        if self.split_max < 2:
            raise ValueError('split_max must be >= 2, got {!r}'
                             .format(self.split_max))
        if self.min_split_rows < 1:
            raise ValueError('min_split_rows must be >= 1, got {!r}'
                             .format(self.min_split_rows))

    def as_dict(self) -> Dict[str, Any]:
        """JSON-safe policy view for reports and the schedule preview."""
        return {'interleave': self.interleave, 'prestage': self.prestage,
                'split': self.split, 'heavy_skew': self.heavy_skew,
                'split_threshold': self.split_threshold,
                'split_max': self.split_max,
                'min_split_rows': self.min_split_rows,
                'ledger_path': self.ledger_path}


def resolve_schedule_policy(value: Any) -> Optional[SchedulePolicy]:
    """Normalize the ``make_reader(cost_schedule=...)`` knob: ``None``/
    ``False`` -> no scheduler (the byte-identical default path), ``True`` ->
    the default :class:`SchedulePolicy`, a policy instance -> itself, a
    string -> default policy with that ``ledger_path``."""
    if value is None or value is False:
        return None
    if value is True:
        return SchedulePolicy()
    if isinstance(value, SchedulePolicy):
        return value
    if isinstance(value, str):
        return SchedulePolicy(ledger_path=value)
    raise TypeError('cost_schedule must be None/False, True, a ledger path, '
                    'or a SchedulePolicy; got {!r}'.format(value))


def load_ledger(dataset_url: str, dataset_token: str,
                cache_location: Optional[str] = None,
                ledger_path: Optional[str] = None
                ) -> Tuple[Optional[CostLedger], Optional[str]]:
    """Locate and load the persisted cost ledger for one reader: returns
    ``(ledger_or_None, resolved_path_or_None)``. A missing, unreadable or
    token-mismatched sidecar yields ``None`` (cold start) — never an error:
    absence of cost knowledge must degrade to the unscheduled order, not
    fail the read."""
    path = ledger_path or default_ledger_path(dataset_url, dataset_token,
                                              cache_location)
    if path is None:
        return None, None
    try:
        ledger = CostLedger.load(path)
    except FileNotFoundError:
        return None, path
    except (OSError, ValueError, KeyError) as exc:
        logger.warning('cost ledger at %s is unreadable (%s); scheduling '
                       'cold (uniform costs)', path, exc)
        return None, path
    if ledger.dataset_token != dataset_token:
        logger.warning('cost ledger at %s was recorded for dataset token %s '
                       '(this read is %s); scheduling cold (uniform costs)',
                       path, ledger.dataset_token, dataset_token)
        return None, path
    return ledger, path


def _ledger_costs(ledger: CostLedger) -> Dict[str, Dict[str, float]]:
    """Per-rowgroup per-stage cost sums out of a ledger, via its JSON view
    (the only public complete iteration surface)."""
    doc = ledger.to_dict()
    costs: Dict[str, Dict[str, float]] = {}
    for key, entry in (doc.get('rowgroups') or {}).items():
        stages = entry.get('stages') or {}
        costs[str(key)] = {
            str(stage): float(cell.get('sum_s', 0.0))
            for stage, cell in stages.items() if stage in COST_STAGES}
    return costs


def _median_cost(totals: Mapping[str, float]) -> float:
    """Median of the POSITIVE rowgroup costs (0.0 when none — the uniform
    cold-start signal)."""
    values = sorted(v for v in totals.values() if v > 0.0)
    if not values:
        return 0.0
    return percentile(values, 0.5)


def _split_parts(normalized: float, num_rows: int, policy: SchedulePolicy,
                 max_parts: Optional[int] = None) -> int:
    """How many sub-ranges a rowgroup of ``normalized`` (median-relative)
    cost and ``num_rows`` rows splits into; < 2 means "do not split".
    ``max_parts`` caps at the consuming pool's worker count: each sub-range
    re-pays the Parquet rowgroup read, so parts beyond the available
    parallelism are pure overhead."""
    if not policy.split or normalized < policy.split_threshold:
        return 1
    by_cost = int(math.ceil(normalized / policy.split_threshold)) + 1
    by_rows = num_rows // max(1, policy.min_split_rows)
    parts = min(policy.split_max, by_cost, by_rows)
    if max_parts is not None:
        parts = min(parts, max_parts)
    return max(1, parts)


def _sub_ranges(num_rows: int, parts: int) -> List[Tuple[int, int]]:
    """Contiguous, exhaustive, near-equal ``(start_row, stop_row)`` ranges."""
    bounds = [(i * num_rows) // parts for i in range(parts + 1)]
    return [(bounds[i], bounds[i + 1]) for i in range(parts)]


def _interleave_order(entries: List[Tuple[Any, float]], heavy_skew: float,
                      prestage: bool) -> List[Any]:
    """Deterministic cost-balanced interleave of ``(item, normalized_cost)``
    pairs: heavies (cost >= ``heavy_skew``) are spread at evenly spaced
    positions — with ``prestage`` each heavy takes the EARLIEST slot of its
    window, so the heaviest rowgroup of the epoch ventilates first — and the
    lights fill the gaps in their incoming (already seeded-shuffled) order."""
    n = len(entries)
    heavy_positions = [i for i, (_item, cost) in enumerate(entries)
                       if cost >= heavy_skew]
    k = len(heavy_positions)
    if k == 0 or k == n:
        return [item for item, _cost in entries]
    # heaviest first: ties broken by incoming position so the order is a
    # pure function of (ledger, seed)
    heavies = sorted((entries[i] for i in heavy_positions),
                     key=lambda pair: -pair[1])
    heavy_set = set(heavy_positions)
    lights = [entries[i][0] for i in range(n) if i not in heavy_set]
    if prestage:
        slots = [(i * n) // k for i in range(k)]
    else:
        slots = [((2 * i + 1) * n) // (2 * k) for i in range(k)]
    out: List[Any] = [None] * n
    for slot, (item, _cost) in zip(slots, heavies):
        out[slot] = item
    light_iter = iter(lights)
    for j in range(n):
        if out[j] is None:
            out[j] = next(light_iter)
    return out


class CostAwareScheduler(object):
    """One reader's cost-aware schedule: frozen at construction from the
    persisted ledger (module docstring), fed live observations for the NEXT
    run, and consulted by the ventilator (order), the work-item planner
    (splits) and the service client (cost hints).

    Thread model: the plan (``_piece_costs``, splits, locator) is built once
    on the constructing thread before the ventilator starts; afterwards the
    ventilator thread calls :meth:`order_items`, the consumer thread calls
    :meth:`observe`, and the autotune controller may flip
    :meth:`set_interleave` — the small mutable surface is lock-guarded."""

    def __init__(self, dataset_token: str, policy: SchedulePolicy,
                 ledger: Optional[CostLedger] = None,
                 ledger_path: Optional[str] = None) -> None:
        self.dataset_token = dataset_token
        self.policy = policy
        self.ledger_path = policy.ledger_path or ledger_path
        self._lock = threading.Lock()
        self._interleave = policy.interleave
        self._stage_costs: Dict[str, Dict[str, float]] = (
            _ledger_costs(ledger) if ledger is not None else {})
        totals = {key: sum(stages.values())
                  for key, stages in self._stage_costs.items()}
        #: 0.0 median == cold start: every plan below degrades to a no-op
        self._median = _median_cost(totals)
        self._totals = totals
        #: normalized (median-relative) cost per ventilated piece index,
        #: split-adjusted — filled by :meth:`plan_items`
        self._piece_costs: Dict[int, float] = {}
        #: piece index -> (fragment_path, row_group_id) incl. virtual pieces
        self._locator: Dict[int, Tuple[str, Any]] = {}
        self._splits: List[Dict[str, Any]] = []
        #: live per-rowgroup per-stage observations (consumer sidecars)
        self._live: Dict[str, Dict[str, List[float]]] = {}
        self._observed = 0
        self._orders: List[List[int]] = []
        self._epochs_planned = 0
        #: whether the consuming reader re-invokes :meth:`order_items` each
        #: epoch (shuffling readers do; a static-order reader calls it once
        #: at construction) — the ``schedule_interleave`` autotune knob is
        #: only registered when True, else the controller would hill-climb a
        #: toggle nothing ever reads again
        self.live_reorder = False

    # -------------------------------------------------------------- costs

    @staticmethod
    def rowgroup_key(fragment_path: str, row_group_id: Any) -> str:
        """The ledger's rowgroup key for one fragment/rowgroup pair."""
        return CostLedger._rowgroup_key(fragment_path, row_group_id)

    def normalized_cost(self, key: str) -> float:
        """Median-relative cost of one rowgroup: 1.0 when unknown or on a
        cold (empty/uniform) ledger."""
        if self._median <= 0.0:
            return 1.0
        total = self._totals.get(key, 0.0)
        if total <= 0.0:
            return 1.0
        return total / self._median

    def cost_hint_for(self, item_kwargs: Mapping[str, Any]) -> float:
        """The service submit's measured-cost hint for one ventilated work
        item (clamped to ``[MIN_COST_HINT, MAX_COST_HINT]`` so a pathological
        ledger entry cannot monopolize or starve the DRR budget)."""
        piece = item_kwargs.get('piece_index')
        cost = 1.0
        if piece is not None:
            cost = self._piece_costs.get(int(piece), 1.0)
        return max(MIN_COST_HINT, min(MAX_COST_HINT, cost))

    # --------------------------------------------------------------- plan

    def plan_items(self, items: List[Dict[str, Any]],
                   locator: Mapping[int, Tuple[str, Any, int]],
                   allow_split: bool = True,
                   max_parts: Optional[int] = None
                   ) -> Tuple[List[Dict[str, Any]],
                              Dict[int, Tuple[str, Any]]]:
        """Apply the split plan to the reader's work-item list.

        ``locator`` maps each piece index to ``(fragment_path, row_group_id,
        num_rows)``. A rowgroup whose measured cost crosses
        ``split_threshold x median`` is replaced by up to ``split_max``
        sub-range items: the first keeps the original piece index (so its
        trace context and cost-ledger attribution stay anchored), the rest
        get fresh *virtual* piece indexes and every one carries a
        ``row_range=(start_row, stop_row)`` kwarg into
        ``reader_worker.process``. ``max_parts`` caps parts per rowgroup at
        the consuming pool's worker count (sub-ranges re-pay the rowgroup
        read — parts beyond the parallelism are overhead). Returns
        ``(planned_items, virtual_locator)`` where ``virtual_locator`` maps
        the virtual pieces back to their rowgroup for cost attribution. With
        a cold ledger (or ``allow_split=False`` — the NGram path, whose
        windows span rows) the items pass through untouched."""
        self._locator = {piece: (frag, rg_id)
                         for piece, (frag, rg_id, _rows) in locator.items()}
        pieces = sorted({int(item['piece_index']) for item in items})
        # per-piece normalized costs (split-adjusted below)
        for piece in pieces:
            located = locator.get(piece)
            if located is None:
                self._piece_costs[piece] = 1.0
                continue
            key = self.rowgroup_key(located[0], located[1])
            self._piece_costs[piece] = self.normalized_cost(key)
        if self._median <= 0.0 or not allow_split or not self.policy.split:
            return list(items), {}
        next_piece = (pieces[-1] + 1) if pieces else 0
        decisions: Dict[int, Tuple[List[int], List[Tuple[int, int]]]] = {}
        extra_locator: Dict[int, Tuple[str, Any]] = {}
        for piece in pieces:
            located = locator.get(piece)
            if located is None:
                continue
            fragment_path, row_group_id, num_rows = located
            cost = self._piece_costs[piece]
            parts = _split_parts(cost, int(num_rows), self.policy, max_parts)
            if parts < 2:
                continue
            ranges = _sub_ranges(int(num_rows), parts)
            piece_ids = [piece] + list(range(next_piece,
                                             next_piece + parts - 1))
            next_piece += parts - 1
            decisions[piece] = (piece_ids, ranges)
            key = self.rowgroup_key(fragment_path, row_group_id)
            self._splits.append({'piece_index': piece,
                                 'rowgroup': key,
                                 'parts': parts,
                                 'rows': int(num_rows),
                                 'normalized_cost': round(cost, 3)})
            # Sub-pieces keep HEAVY status (cost floored at heavy_skew): they
            # exist because their rowgroup crossed the split threshold, and
            # demoting a part below heavy_skew (e.g. a 4.5x rowgroup in 3
            # parts = 1.5x each) would silently drop it out of the
            # interleave/pre-stage/least-loaded-routing mechanisms that the
            # split was meant to feed.
            part_cost = max(cost / parts, self.policy.heavy_skew)
            for sub_piece in piece_ids:
                self._piece_costs[sub_piece] = part_cost
                self._locator[sub_piece] = (fragment_path, row_group_id)
                if sub_piece != piece:
                    extra_locator[sub_piece] = (fragment_path, row_group_id)
        if not decisions:
            return list(items), {}
        planned: List[Dict[str, Any]] = []
        for item in items:
            decision = decisions.get(int(item['piece_index']))
            if decision is None:
                planned.append(item)
                continue
            piece_ids, ranges = decision
            for sub_piece, row_range in zip(piece_ids, ranges):
                sub_item = dict(item)
                sub_item['piece_index'] = sub_piece
                sub_item['row_range'] = row_range
                planned.append(sub_item)
        return planned, extra_locator

    # -------------------------------------------------------------- order

    def order_items(self, items: List[Dict[str, Any]],
                    random_state: Any = None) -> List[Dict[str, Any]]:
        """One epoch's ventilation order: the seeded shuffle (when the
        reader shuffles rowgroups — ``random_state`` is the ventilator's RNG,
        consumed exactly as the plain path consumes it) followed by the
        deterministic cost-balanced interleave. Same seed + same ledger =>
        same order on every pool; cold ledger or ``interleave`` off => the
        shuffle alone, byte-identical to an unscheduled reader."""
        ordered = list(items)
        if random_state is not None:
            random_state.shuffle(ordered)
        with self._lock:
            interleave = self._interleave and self._median > 0.0
        if interleave and len(ordered) > 1:
            entries = [(item,
                        self._piece_costs.get(int(item['piece_index']), 1.0))
                       for item in ordered]
            ordered = _interleave_order(entries, self.policy.heavy_skew,
                                        self.policy.prestage)
        order_ids = [int(item['piece_index']) for item in ordered]
        with self._lock:
            self._epochs_planned += 1
            self._orders.append(order_ids)
            del self._orders[:-_ORDER_HISTORY]
            epoch = self._epochs_planned
        if trace_enabled():
            trace_instant('schedule_plan',
                          args={'epoch': epoch,
                                'items': len(ordered),
                                'interleaved': bool(interleave),
                                'splits': len(self._splits)})
        return ordered

    # ---------------------------------------------------- live observation

    def set_interleave(self, value: bool) -> bool:
        """Runtime toggle of the interleave half (the autotune
        ``schedule_interleave`` knob, docs/autotuning.md): takes effect at
        the next epoch reorder; split decisions are frozen at construction
        (they shaped the work-item list). Returns the applied value."""
        value = bool(value)
        with self._lock:
            self._interleave = value
        return value

    @property
    def interleave(self) -> bool:
        """Whether the cost-balanced interleave is currently applied."""
        with self._lock:
            return self._interleave

    @property
    def split_count(self) -> int:
        """How many rowgroups the plan split (frozen at construction)."""
        return len(self._splits)

    def plan_fingerprint(self) -> Dict[str, Any]:
        """The frozen plan as a JSON-safe reproduction record: everything a
        dry replay needs to re-derive this scheduler's epoch orders without
        the ledger file (the lineage manifest header embeds it —
        docs/observability.md "Sample lineage & determinism audit"). A
        cost-ledger delta between two runs shows up as a difference here,
        which is how ``lineage diff`` attributes a reordered interleave to
        the schedule plan."""
        with self._lock:
            interleave = self._interleave
        return {'cold_start': self._median <= 0.0,
                'interleave': interleave,
                'prestage': self.policy.prestage,
                'heavy_skew': self.policy.heavy_skew,
                'policy': self.policy.as_dict(),
                'piece_costs': {str(piece): round(cost, 6)
                                for piece, cost
                                in sorted(self._piece_costs.items())},
                'splits': [dict(row) for row in self._splits]}

    def piece_locator(self) -> Dict[int, Tuple[str, Any]]:
        """``{piece_index: (fragment_path, row_group_id)}`` covering every
        planned piece INCLUDING the virtual sub-range pieces — the one map
        the reader's cost-ledger attribution should use (a hand-merged copy
        would silently go stale when the plan changes)."""
        return dict(self._locator)

    def observe(self, piece_index: int,
                stage_times: Mapping[str, Mapping[str, Any]]) -> None:
        """Fold one consumed batch's telemetry sidecar (``{stage:
        histogram_snapshot}``) into the live ledger: the cold-start feed.
        Only ``COST_STAGES`` contribute; attribution rides the piece index
        through the plan's locator (virtual split pieces fold into their
        parent rowgroup). Never reorders the CURRENT run — determinism —
        but :meth:`persist` hands the knowledge to the next one."""
        located = self._locator.get(int(piece_index))
        if located is None:
            return
        key = self.rowgroup_key(located[0], located[1])
        observed = False
        with self._lock:
            for stage in COST_STAGES:
                cell = stage_times.get(stage)
                if not cell:
                    continue
                seconds = float(cell.get('sum', 0.0))
                count = int(cell.get('count', 0))
                if seconds <= 0.0 and count <= 0:
                    continue
                live = self._live.setdefault(key, {})
                # [count, sum_s, max_s] — max is the largest SINGLE span
                # (the sidecar histogram's own max), never the run total:
                # CostLedger.merge keeps max(max_s), so an inflated value
                # would poison the sidecar forever
                acc = live.setdefault(stage, [0.0, 0.0, 0.0])
                acc[0] += count
                acc[1] += seconds
                acc[2] = max(acc[2], float(cell.get('max', 0.0)))
                observed = True
            if observed:
                self._observed += 1

    def live_ledger(self) -> CostLedger:
        """The run's live observations (so far) as a :class:`CostLedger`
        (additive — merge it with the persisted one). Does not drain;
        :meth:`persist` does."""
        with self._lock:
            live = {key: {stage: list(acc) for stage, acc in stages.items()}
                    for key, stages in self._live.items()}
        return self._ledger_of(live)

    def _ledger_of(self, live: Dict[str, Dict[str, List[float]]]
                   ) -> CostLedger:
        ledger = CostLedger(self.dataset_token)
        for key, stages in live.items():
            entry = ledger._entry(key)
            for stage, (count, seconds, max_s) in stages.items():
                entry['stages'][stage] = {'count': int(count),
                                          'sum_s': float(seconds),
                                          'max_s': float(max_s)}
        return ledger

    def persist(self, path: Optional[str] = None) -> Optional[str]:
        """Merge the live observations into the persisted sidecar (additive,
        token-guarded) and save atomically; returns the path written, or
        None when there is nothing to write or nowhere to write it. DRAINS
        the observations it takes — ``Reader.stop`` may run more than once
        (``stop()`` + context-manager ``__exit__``), and a second persist
        must not double-merge the same run into the sidecar. Best-effort: a
        failed save logs and drops the batch (a read must never fail over
        its cost bookkeeping)."""
        path = path or self.ledger_path
        with self._lock:
            observed = self._observed
            if path is None or not observed:
                return None
            live, self._live = self._live, {}
            self._observed = 0
        ledger = self._ledger_of(live)
        try:
            previous, _resolved = load_ledger('', self.dataset_token,
                                              ledger_path=path)
            if previous is not None:
                ledger.merge(previous)
            ledger.save(path)
        except OSError as exc:
            logger.warning('could not persist cost ledger to %s: %s',
                           path, exc)
            return None
        return path

    # -------------------------------------------------------------- report

    def cost_skew(self) -> Optional[float]:
        """p95-over-median skew of the ledger's per-rowgroup costs — the
        longitudinal run record's ``cost_skew_p95_over_median`` field
        (docs/observability.md "Longitudinal observatory"); None on a cold
        start (no ledger, nothing to skew)."""
        totals = sorted(self._totals.values())
        if not totals or self._median <= 0.0:
            return None
        p95 = totals[min(len(totals) - 1,
                         int(round(0.95 * (len(totals) - 1))))]
        return p95 / self._median

    def report(self) -> Dict[str, Any]:
        """JSON-safe schedule view for ``Reader.diagnostics['schedule']``:
        the policy, ledger coverage, split decisions, heavy count, recent
        epoch orders (piece indexes) and the live-observation tally."""
        with self._lock:
            orders = [list(order) for order in self._orders]
            observed = self._observed
            interleave = self._interleave
        heavy = sorted(key for key, total in self._totals.items()
                       if self._median > 0.0
                       and total / self._median >= self.policy.heavy_skew)
        return {'enabled': True,
                'policy': self.policy.as_dict(),
                'interleave': interleave,
                'cold_start': self._median <= 0.0,
                'ledger_rowgroups': len(self._totals),
                'median_cost_s': round(self._median, 6),
                'heavy_rowgroups': heavy,
                'splits': [dict(row) for row in self._splits],
                'epoch_orders': orders,
                'live_observations': observed,
                'ledger_path': self.ledger_path}


def plan_preview(ledger: CostLedger,
                 policy: Optional[SchedulePolicy] = None) -> Dict[str, Any]:
    """The ``petastorm-tpu-throughput costs --json`` ``schedule_preview``
    block: what the cost-aware scheduler WOULD do with this ledger — planned
    interleave order (rowgroup keys, deterministic FIFO base so operators can
    diff previews across runs) and split decisions — without running an
    epoch. Splitting is previewed from cost alone (the planner additionally
    caps parts by the rowgroup's row count, which a ledger does not
    record)."""
    policy = policy or SchedulePolicy()
    stage_costs = _ledger_costs(ledger)
    totals = {key: sum(stages.values()) for key, stages in stage_costs.items()}
    median = _median_cost(totals)
    keys = sorted(totals)
    if median <= 0.0:
        return {'policy': policy.as_dict(), 'rowgroups': len(keys),
                'median_cost_s': 0.0, 'cold_start': True,
                'interleave_order': keys, 'heavy': [], 'splits': []}
    normalized = {key: (totals[key] / median if totals[key] > 0.0 else 1.0)
                  for key in keys}
    entries = [(key, normalized[key]) for key in keys]
    order = _interleave_order(entries, policy.heavy_skew, policy.prestage) \
        if policy.interleave and len(entries) > 1 else keys
    heavy = [key for key in keys if normalized[key] >= policy.heavy_skew]
    splits = []
    for key in keys:
        parts = _split_parts(normalized[key], 10 ** 9, policy)
        if parts >= 2:
            splits.append({'rowgroup': key, 'parts': parts,
                           'normalized_cost': round(normalized[key], 3),
                           'cost_s': round(totals[key], 6)})
    return {'policy': policy.as_dict(), 'rowgroups': len(keys),
            'median_cost_s': round(median, 6), 'cold_start': False,
            'interleave_order': order, 'heavy': heavy, 'splits': splits}
