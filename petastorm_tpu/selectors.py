"""Rowgroup selectors over prebuilt indexes (reference: petastorm/selectors.py:21-101 —
fully functional here; the reference disables them at Reader level, reader.py:551-555)."""


class RowGroupSelectorBase(object):
    """Rowgroup-selector interface (reference: petastorm/selectors.py) over built
    rowgroup indexes."""

    def select_row_groups(self, index_dict):
        """Return the set of piece indexes to read, given {index_name: indexer}."""
        raise NotImplementedError()


class SingleIndexSelector(RowGroupSelectorBase):
    """Rowgroups containing any of ``values`` in the named index (reference:
    selectors.py:30-55)."""

    def __init__(self, index_name, values_list):
        self._index_name = index_name
        self._values = list(values_list)

    def select_row_groups(self, index_dict):
        if self._index_name not in index_dict:
            raise ValueError('Index {!r} not found in dataset metadata (available: {})'
                             .format(self._index_name, sorted(index_dict)))
        indexer = index_dict[self._index_name]
        selected = set()
        for value in self._values:
            selected |= indexer.get_row_group_indexes(value)
        return selected


class IntersectIndexSelector(RowGroupSelectorBase):
    """Rowgroups selected by ALL child selectors (reference: selectors.py:58-78)."""

    def __init__(self, selectors):
        self._selectors = list(selectors)

    def select_row_groups(self, index_dict):
        result = None
        for selector in self._selectors:
            pieces = selector.select_row_groups(index_dict)
            result = pieces if result is None else (result & pieces)
        return result or set()


class UnionIndexSelector(RowGroupSelectorBase):
    """Rowgroups selected by ANY child selector (reference: selectors.py:81-101)."""

    def __init__(self, selectors):
        self._selectors = list(selectors)

    def select_row_groups(self, index_dict):
        result = set()
        for selector in self._selectors:
            result |= selector.select_row_groups(index_dict)
        return result
