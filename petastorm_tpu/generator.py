"""Random datapoint generation from any Unischema (reference: petastorm/generator.py:21-47)
— dtype-range aware, used by examples/benchmarks to synthesize datasets."""

from decimal import Decimal

import numpy as np


def generate_random_datapoint(schema, rng=None, var_dim_max=10, string_length=8):
    """One row dict with random values matching each field's dtype/shape."""
    rng = rng or np.random.RandomState()
    row = {}
    for name, field in schema.fields.items():
        shape = tuple(var_dim_max if dim is None else dim for dim in field.shape)
        row[name] = _random_value(field, shape, rng, string_length)
    return row


def _random_value(field, shape, rng, string_length):
    if field.numpy_dtype is Decimal:
        return Decimal('{:.2f}'.format(rng.rand() * 100))
    dtype = np.dtype(field.numpy_dtype)
    if dtype.kind in ('U', 'S'):
        letters = np.array(list('abcdefghijklmnopqrstuvwxyz'))

        def _one_string():
            value = ''.join(rng.choice(letters, string_length))
            return value.encode('utf-8') if dtype.kind == 'S' else value

        if shape == ():
            return _one_string()
        count = int(np.prod(shape))
        return np.array([_one_string() for _ in range(count)]).reshape(shape)
    if dtype.kind == 'b':
        data = rng.randint(0, 2, shape).astype(bool)
    elif dtype.kind in ('i', 'u'):
        info = np.iinfo(dtype)
        low = max(info.min, -(1 << 30))
        high = min(info.max, 1 << 30)
        data = rng.randint(low, high, size=shape or None)
        data = np.asarray(data, dtype=dtype)
    elif dtype.kind == 'f':
        data = rng.rand(*shape).astype(dtype) if shape else dtype.type(rng.rand())
    elif dtype.kind == 'M':
        data = (np.datetime64('2020-01-01') +
                np.timedelta64(1, 'h') * rng.randint(0, 10000, size=shape or None))
    else:
        raise ValueError('Cannot generate data for dtype {}'.format(dtype))
    if shape == ():
        return data if np.isscalar(data) or isinstance(data, np.generic) \
            else dtype.type(data)
    return np.asarray(data, dtype=dtype).reshape(shape)


#: Reference-name alias (petastorm/generator.py:21 ``generate_datapoint``) for
#: drop-in migration; same callable.
generate_datapoint = generate_random_datapoint
