"""On-device ops: jitted/Pallas decode+augment kernels and sequence-parallel attention
(the compute-side extension points the TPU build adds over the reference's host-only
OpenCV/numpy decode — SURVEY.md §2.9, §5.7)."""

from petastorm_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention, flash_attention_segmented)
from petastorm_tpu.ops.image import normalize_image, random_crop_flip  # noqa: F401
from petastorm_tpu.ops.ring_attention import ring_attention  # noqa: F401
from petastorm_tpu.ops.packing import (  # noqa: F401
    make_packing_transform, pack_sequences, packed_next_token_loss,
    segment_causal_attention)
from petastorm_tpu.ops.sharded_moe import (  # noqa: F401
    expert_alltoall_ffn, sharded_moe_ffn)
