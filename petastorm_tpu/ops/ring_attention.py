"""Ring attention: exact attention over a sequence sharded across a mesh axis.

The reference has no model-side sequence parallelism (SURVEY.md §5.7: NGram is pure data
windowing); long-context consumers of this framework need the compute side too. This is
blockwise/flash-style streaming attention where each device holds one sequence shard of
K/V and the shards rotate around the ring via ``lax.ppermute`` (ICI neighbor exchange),
with an online log-sum-exp softmax so the result is exact — the standard ring-attention
construction (Liu et al., 2023), written for XLA: static shapes, ``lax.fori_loop``, no
host control flow.

Use inside ``shard_map`` over a mesh axis carrying the sequence dimension; or call
:func:`ring_attention_sharded` to get the shard_map wrapper built for you.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _block_attn(q, k, v, bias):
    """One blockwise attention contribution: returns (scores_max, exp-weights sum,
    weighted values) for the online-softmax accumulator. Shapes: q [B,Tq,H,D],
    k/v [B,Tb,H,D], bias broadcastable to [B,H,Tq,Tb]."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum('bqhd,bkhd->bhqk', q, k) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1)                                    # [B,H,Tq]
    p = jnp.exp(s - m[..., None])                              # [B,H,Tq,Tb]
    # A fully-masked row has every s at _NEG_INF, making exp(s - m) == 1 — zero
    # those entries so a masked-out block contributes nothing to the accumulator
    # (segment masking can fully mask a block; plain causal never does).
    p = p * (s > _NEG_INF / 2)
    l = jnp.sum(p, axis=-1)                                    # [B,H,Tq]
    o = jnp.einsum('bhqk,bkhd->bqhd', p, v)                    # [B,Tq,H,D]
    return m, l, o


def ring_attention(q, k, v, axis_name, causal=False, segments=None):
    """Exact attention with K/V ring-rotated over ``axis_name``. Must run inside
    ``shard_map``; every array is the per-device shard ``[B, T_local, H, D]``. The global
    sequence is the concatenation of shards in ring order.

    :param causal: apply a causal mask over GLOBAL positions (shard offsets accounted
        for), so the result equals dense causal attention on the gathered sequence.
    :param segments: optional ``[B, T_local]`` int32 shard of packed-sequence segment
        ids (``ops.packing`` convention: 0 = padding, documents numbered from 1).
        Attention is confined to same-segment pairs; padding positions attend to
        nothing and return zeros. Segment ids rotate around the ring with their K/V
        blocks, so packing composes with sequence parallelism.
    """
    axis_size = lax.psum(1, axis_name)
    my_index = lax.axis_index(axis_name)
    t_local = q.shape[1]
    q_positions = my_index * t_local + jnp.arange(t_local)      # global positions
    has_segments = segments is not None

    def make_bias(source_index, k_seg_blk):
        if not (causal or has_segments):
            return None
        allow = jnp.ones((1, 1, t_local, t_local), dtype=bool)  # [B?, 1, Tq, Tb]
        if causal:
            k_positions = source_index * t_local + jnp.arange(t_local)
            allow = allow & (q_positions[:, None]
                             >= k_positions[None, :])[None, None]
        if has_segments:
            # ONE definition of the segment/padding mask (ops.packing convention).
            from petastorm_tpu.ops.packing import segment_mask
            allow = allow & segment_mask(segments, k_seg_blk, causal=False)
        return jnp.where(allow, 0.0, _NEG_INF)

    def body(step, carry):
        if has_segments:
            o_acc, l_acc, m_acc, k_blk, v_blk, k_seg_blk = carry
        else:
            o_acc, l_acc, m_acc, k_blk, v_blk = carry
            k_seg_blk = None
        # K/V block currently held arrived from (my_index - step) around the ring.
        source_index = (my_index - step) % axis_size
        m_blk, l_blk, o_blk = _block_attn(q, k_blk, v_blk,
                                          make_bias(source_index, k_seg_blk))
        # Online softmax merge (flash-attention accumulator).
        m_new = jnp.maximum(m_acc, m_blk)
        corr_acc = jnp.exp(m_acc - m_new)
        corr_blk = jnp.exp(m_blk - m_new)
        l_new = l_acc * corr_acc + l_blk * corr_blk
        o_new = (o_acc * jnp.swapaxes(corr_acc, 1, 2)[..., None]
                 + o_blk * jnp.swapaxes(corr_blk, 1, 2)[..., None])
        # Rotate K/V to the next device; overlaps with the next block's compute on TPU.
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        if has_segments:
            # Segment ids travel WITH their K/V block; unsegmented calls skip this
            # collective entirely.
            seg_next = lax.ppermute(k_seg_blk, axis_name, perm)
            return o_new, l_new, m_new, k_next, v_next, seg_next
        return o_new, l_new, m_new, k_next, v_next

    b, t, h, d = q.shape
    o0 = jnp.zeros((b, t, h, d), dtype=jnp.float32)
    l0 = jnp.zeros((b, h, t), dtype=jnp.float32)
    m0 = jnp.full((b, h, t), _NEG_INF, dtype=jnp.float32)
    carry = (o0, l0, m0, k.astype(jnp.float32), v.astype(jnp.float32))
    if has_segments:
        carry = carry + (segments,)
    out = lax.fori_loop(0, axis_size, body, carry)
    o, l = out[0], out[1]
    # Padding rows attend to nothing (l == 0): emit zeros, not NaN.
    l = jnp.swapaxes(l, 1, 2)[..., None]
    o = jnp.where(l > 0, o / jnp.where(l > 0, l, 1.0), 0.0)
    return o.astype(q.dtype)


def ring_attention_sharded(mesh, seq_axis, causal=False, with_segments=False,
                           batch_axis=None):
    """Build a jittable ``fn(q, k, v)`` — or ``fn(q, k, v, segments)`` when
    ``with_segments`` — running ring attention with the sequence dimension sharded
    over ``mesh[seq_axis]``. ``batch_axis`` optionally shards the batch dimension
    (dp+sp); default replicates it. Inputs/outputs are GLOBAL arrays of shape
    [B, T, H, D] (segments [B, T] int32, ``ops.packing`` convention)."""
    from jax.sharding import PartitionSpec as P

    from petastorm_tpu.parallel.mesh import shard_map_compat

    spec = P(batch_axis, seq_axis, None, None)
    inner = functools.partial(ring_attention, axis_name=seq_axis, causal=causal)
    if with_segments:
        def with_seg(q, k, v, segments):
            return inner(q, k, v, segments=segments)

        return jax.jit(shard_map_compat(
            with_seg, mesh, (spec, spec, spec, P(batch_axis, seq_axis)), spec))
    return jax.jit(shard_map_compat(inner, mesh, (spec, spec, spec), spec))


def dense_attention(q, k, v, causal=False):
    """Reference single-device attention (for testing ring_attention exactness)."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum('bqhd,bkhd->bhqk', q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bhqk,bkhd->bqhd', p, v.astype(jnp.float32)).astype(q.dtype)
