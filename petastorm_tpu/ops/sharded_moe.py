"""Explicit all-to-all MoE dispatch under ``shard_map`` (the GShard wiring).

:class:`petastorm_tpu.models.MoEMlp` expresses expert parallelism as sharding
annotations and lets XLA place the all-to-all — the right default under plain
``jit``. Inside a ``shard_map`` region, however, there is no compiler to place
collectives: code that already lives there (ring attention over a ``seq`` axis, the
pipeline schedule over ``stage``) needs the expert exchange written out. This module
is that spelled-out data path, built on the SAME routing math
(``models.moe.switch_routing``) so the two paths can never route differently:

1. each data shard dispatches its local tokens into per-expert capacity slots
   ``[experts, C_local, d]`` (one-hot einsum — MXU work, static shapes);
2. ``lax.all_to_all`` over the expert axis exchanges expert blocks so every device
   holds ONLY its own experts' slots from every peer ``[local_experts, ne*C_local, d]``
   — the collective rides ICI;
3. the local expert FFN runs (two einsums + activation);
4. the inverse ``all_to_all`` returns results to the tokens' home shards, where the
   combine einsum weighs them back into token order.

Gradients flow through both collectives (``all_to_all`` is its own transpose up to
axis bookkeeping), so ``jax.grad`` of a loss through this op yields the standard
MoE backward with the same two exchanges.
"""

import jax
import jax.numpy as jnp
from jax import lax


def expert_alltoall_ffn(tokens, dispatch, combine, w1, w2, axis_name,
                        activation=jax.nn.gelu):
    """Run the expert FFN with explicit all-to-all exchange. Call INSIDE shard_map.

    :param tokens: ``[S_local, d]`` this data shard's tokens.
    :param dispatch: ``[S_local, X, C_local]`` routing dispatch mask over ALL ``X``
        experts (from :func:`petastorm_tpu.models.moe.switch_routing` on the local
        shard's router probabilities).
    :param combine: ``[S_local, X, C_local]`` matching combine weights.
    :param w1: ``[X_local, d, f]`` THIS device's expert slice (X_local = X / ne).
    :param w2: ``[X_local, f, d]`` likewise.
    :param axis_name: mesh axis the experts are sharded over (size ``ne``).
    :param activation: FFN nonlinearity.
    :returns: ``[S_local, d]`` expert outputs in token order (dtype of ``tokens``).
    """
    ne = lax.psum(1, axis_name)
    n_exp = dispatch.shape[1]
    if n_exp % ne != 0:
        raise ValueError('experts {} not divisible by axis {!r} size {}'
                         .format(n_exp, axis_name, ne))
    x_local = n_exp // ne
    if w1.shape[0] != x_local or w2.shape[0] != x_local:
        raise ValueError('expert weight leading dim {} != local experts {} '
                         '(= {} experts / {} devices)'
                         .format(w1.shape[0], x_local, n_exp, ne))
    cap = dispatch.shape[2]
    dtype = tokens.dtype

    # [S, X, C] x [S, d] -> [X, C, d]: local tokens into capacity slots.
    slots = jnp.einsum('sxc,sd->xcd', dispatch.astype(dtype), tokens)
    # Group by owning device and exchange: after all_to_all, dim 0 is the SOURCE
    # data shard and dim 1 this device's local experts.
    slots = slots.reshape(ne, x_local, cap, -1)
    slots = lax.all_to_all(slots, axis_name, split_axis=0, concat_axis=0)
    # [ne, X_local, C, d] -> [X_local, ne*C, d]: every peer's slots for my experts.
    slots = slots.transpose(1, 0, 2, 3).reshape(x_local, ne * cap, -1)

    h = activation(jnp.einsum('xcd,xdf->xcf', slots, w1.astype(dtype)))
    out = jnp.einsum('xcf,xfd->xcd', h, w2.astype(dtype))

    # Inverse exchange: back to [S-home-shard, ...] layout, then combine.
    out = out.reshape(x_local, ne, cap, -1).transpose(1, 0, 2, 3)
    out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0)
    out = out.reshape(n_exp, cap, -1)                                  # [X, C, d]
    return jnp.einsum('xcd,sxc->sd', out.astype(jnp.float32),
                      combine.astype(jnp.float32)).astype(dtype)


def sharded_moe_ffn(tokens, router_kernel, w1, w2, axis_name, capacity_factor=1.25,
                    num_selected=1, activation=jax.nn.gelu):
    """Routing + exchange + FFN in one call (inside shard_map): ``[S_local, d]`` ->
    ``([S_local, d], aux, drop_fraction)``.

    Routing runs per data shard on ``router_kernel [d, X]`` (replicated across the
    expert axis); capacity is computed from the LOCAL token count, matching what
    :class:`MoEMlp` computes per global batch divided by data shards. ``aux`` and
    ``drop_fraction`` are local-shard scalars — ``lax.pmean`` them over the data
    axis for the global values."""
    from petastorm_tpu.models.moe import _capacity, switch_routing
    n_exp = router_kernel.shape[1]
    probs = jax.nn.softmax(tokens.astype(jnp.float32) @ router_kernel.astype(
        jnp.float32), axis=-1)
    cap = _capacity(tokens.shape[0], n_exp, num_selected, capacity_factor)
    dispatch, combine, aux, drop_fraction = switch_routing(probs, cap, num_selected)
    out = expert_alltoall_ffn(tokens, dispatch, combine, w1, w2, axis_name,
                              activation=activation)
    return out, aux, drop_fraction
