"""Raw-payload decode kernels for the device-resident decode tail.

When a reader ships codec payloads raw (``make_reader(device_decode_fields=...)``,
docs/performance.md "Device-resident decode tail"), the loader uploads compressed
or packed bytes and the decode math runs on the accelerator. Two kernel families
live here:

- **npy-unpack** (:func:`bitcast_rows`, :func:`unpack_npy_rows`): a packed
  ``(n, stride)`` uint8 byte matrix of equal-layout ``.npy`` payloads becomes a
  typed ``(n,) + shape`` array through static slices + ``bitcast_convert_type``
  — pure view-level work XLA fuses into the consuming program, matching
  ``jax.device_put``'s dtype canonicalization exactly (under x32, int64/uint64
  land as the little-endian low word, like the loader's coalesced unpack).
- **deflate-lite** (:func:`parse_stored_deflate_layout`, :func:`plan_stored_batch`,
  :func:`stored_inflate`): raw-deflate streams whose every block is *stored*
  (BTYPE=00 — what zlib emits for incompressible input, and always what level-0
  encoding produces) are just framed memcpys; the host parses the 5-byte block
  headers into a segment table and a Pallas kernel performs the gather-copy on
  device. Streams with Huffman-coded blocks return ``None`` from the parser —
  entropy decode is bit-serial and stays on the host (the same split
  ``ops/image_decode.py`` documents for JPEG).

The Pallas kernel runs compiled on TPU and in interpreter mode elsewhere
(``interpret=None`` resolves like ``ops/flash_attention.py``), so CPU test runs
exercise the same kernel logic without an accelerator.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np

#: bytes moved per grid step of the stored-inflate kernel; stored-block payload
#: segments are chunked to this size on the host so the kernel's VMEM window is
#: fixed regardless of block sizes (a stored block may span up to 65535 bytes)
STORED_COPY_WINDOW = 1024


# ------------------------------------------------------------------ npy unpack

def bitcast_rows(buf: Any, dtype_str: str, row_shape: Tuple[int, ...],
                 x64: Optional[bool] = None) -> Any:
    """Reinterpret a packed ``(n, stride)`` uint8 byte matrix as a typed
    ``(n,) + row_shape`` array on device.

    ``dtype_str`` is the numpy dtype string of the stored payload (little-endian
    or byteorder-free). The result matches what ``jax.device_put`` of the
    host-decoded array would produce: under x32 (``x64=False``), 8-byte integer
    payloads canonicalize to their low 4-byte word (little-endian), and
    ``float64`` payloads are rejected — the rounding conversion cannot be
    expressed without 64-bit types, so callers must keep such fields on the
    host path (the same gate as ``parallel.loader.coalescible_layout``)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    if x64 is None:
        x64 = bool(jax.config.jax_enable_x64)
    dtype = np.dtype(dtype_str)
    n = buf.shape[0]
    if dtype.kind == 'f' and dtype.itemsize == 8 and not x64:
        raise ValueError('float64 payloads cannot be unpacked under x32; '
                         'keep this field on the host decode path')
    if dtype == np.uint8:
        arr = buf
    elif dtype == np.bool_:
        arr = buf != 0
    elif dtype.itemsize == 1:
        arr = lax.bitcast_convert_type(buf, jnp.dtype(dtype))
    elif dtype.itemsize == 8 and dtype.kind in 'iu' and not x64:
        words = lax.bitcast_convert_type(buf.reshape(n, -1, 4), jnp.uint32)
        low = words.reshape(n, -1, 2)[:, :, 0]  # little-endian low word
        target = jnp.int32 if dtype.kind == 'i' else jnp.uint32
        arr = lax.bitcast_convert_type(low, target)
    else:
        arr = lax.bitcast_convert_type(buf.reshape(n, -1, dtype.itemsize),
                                       jnp.dtype(dtype))
    return arr.reshape((n,) + tuple(row_shape))


def unpack_npy_rows(packed: Any, header_len: int, dtype_str: str,
                    row_shape: Tuple[int, ...],
                    x64: Optional[bool] = None) -> Any:
    """``(n, blob_len)`` uint8 matrix of equal-header ``.npy`` blobs -> typed
    ``(n,) + row_shape`` array: a static slice drops the shared ``header_len``
    prefix, then :func:`bitcast_rows` reinterprets the payload region. The
    header is parsed ONCE on the host (it is identical across rows for a
    fixed-shape field); the device never sees Python parsing."""
    return bitcast_rows(packed[:, header_len:], dtype_str, row_shape, x64=x64)


# ---------------------------------------------------------------- deflate-lite

def parse_stored_deflate_layout(frame: Any) -> Optional[List[Tuple[int, int]]]:
    """Scan one raw-deflate stream; if EVERY block is stored (BTYPE=00), return
    its payload segments as ``[(src_offset, length), ...]``; else None.

    Stored blocks are byte-aligned (the 3 header bits are followed by a pad to
    the next byte boundary, then LEN/NLEN and LEN literal bytes), so an
    all-stored stream is fully described by byte offsets — the on-device
    "inflate" is a gather-copy. Malformed streams (truncation, LEN/NLEN
    mismatch) also return None; the caller keeps the host zlib path, which
    raises its own precise error."""
    buf = bytes(memoryview(frame))
    pos = 0
    segments: List[Tuple[int, int]] = []
    while True:
        if pos >= len(buf):
            return None  # truncated before a final block
        header = buf[pos]
        if (header >> 1) & 0x3 != 0:
            return None  # Huffman-coded block: host inflate territory
        if pos + 5 > len(buf):
            return None
        length = int.from_bytes(buf[pos + 1:pos + 3], 'little')
        nlen = int.from_bytes(buf[pos + 3:pos + 5], 'little')
        if length ^ 0xFFFF != nlen:
            return None
        if pos + 5 + length > len(buf):
            return None
        if length:
            segments.append((pos + 5, length))
        pos += 5 + length
        if header & 0x1:
            return segments


def plan_stored_batch(
        frames: List[Any]) -> Optional[Tuple[np.ndarray, List[int]]]:
    """Build the device copy plan for a batch of raw-deflate frames that are
    ALL stored-block-only: returns ``(segments, frame_lengths)`` where
    ``segments`` is an ``(m, 3)`` int32 table of ``(src_offset, dst_offset,
    length)`` chunks (each at most :data:`STORED_COPY_WINDOW` bytes — the
    kernel's fixed VMEM window) with ``src_offset`` indexing the CONCATENATION
    of the frames and ``dst_offset`` the concatenation of their inflated
    payloads, and ``frame_lengths`` the per-frame inflated sizes (callers
    needing a dense ``(n, len)`` view must check they are uniform — a total
    divisible by ``n`` does not imply that). Returns None when any frame
    contains a non-stored block — callers inflate on the host."""
    rows: List[Tuple[int, int, int]] = []
    frame_lengths: List[int] = []
    src_base = 0
    dst_base = 0
    for frame in frames:
        layout = parse_stored_deflate_layout(frame)
        if layout is None:
            return None
        frame_len = 0
        for src_off, length in layout:
            start = 0
            while start < length:
                chunk = min(STORED_COPY_WINDOW, length - start)
                rows.append((src_base + src_off + start, dst_base + start, chunk))
                start += chunk
            dst_base += length
            frame_len += length
        frame_lengths.append(frame_len)
        src_base += len(frame)
    if not rows:
        return np.zeros((0, 3), dtype=np.int32), frame_lengths
    return np.asarray(rows, dtype=np.int32), frame_lengths


def _stored_copy_kernel(seg_ref: Any, src_ref: Any, out_ref: Any) -> None:
    """One grid step = one <=WINDOW-byte chunk: read a fixed window at the
    chunk's dynamic source offset, read-modify-write it into the output at the
    destination offset (lanes past ``length`` keep the existing bytes — a later
    grid step owns them; the grid is sequential, so the RMW overlap at chunk
    boundaries is ordered). Program 0 zero-fills the output so every
    read-before-write is defined."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init() -> None:
        out_ref[...] = jnp.zeros_like(out_ref)

    src_off = seg_ref[0, 0]
    dst_off = seg_ref[0, 1]
    length = seg_ref[0, 2]
    window = src_ref[0, pl.ds(src_off, STORED_COPY_WINDOW)]
    current = out_ref[0, pl.ds(dst_off, STORED_COPY_WINDOW)]
    lane = jax.lax.broadcasted_iota(jnp.int32, (STORED_COPY_WINDOW,), 0)
    out_ref[0, pl.ds(dst_off, STORED_COPY_WINDOW)] = \
        jnp.where(lane < length, window, current)


def stored_inflate(packed_src: Any, segments: Any, out_len: int,
                   interpret: Optional[bool] = None) -> Any:
    """Inflate a stored-block-only deflate batch on device: a Pallas gather-copy
    over the :func:`plan_stored_batch` segment table.

    :param packed_src: uint8 ``(s,)`` array — the concatenated raw frames
        (host or device resident).
    :param segments: int32 ``(m, 3)`` chunk table from :func:`plan_stored_batch`.
    :param out_len: total inflated length (static).
    :param interpret: run the kernel in interpreter mode; None resolves to
        "not on a TPU backend" (same gate as ``ops/flash_attention.py``).
    :returns: uint8 ``(out_len,)`` device array of the inflated payloads.

    The per-step copy window is fixed (:data:`STORED_COPY_WINDOW`), but the
    whole source and output buffers are staged for the kernel — on a real TPU
    that staging is VMEM-bounded, so callers must budget total bytes (the
    loader's device stage caps the path at a few MB per batch and falls back
    to host inflate above it).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    m = int(segments.shape[0])
    if m == 0 or out_len == 0:
        return jnp.zeros((out_len,), dtype=jnp.uint8)
    window = STORED_COPY_WINDOW
    src = jnp.asarray(packed_src, dtype=jnp.uint8)
    # pad so every window read/write stays in bounds at the tail
    src = jnp.pad(src, (0, window))[None, :]
    out_pad = out_len + window

    out = pl.pallas_call(
        _stored_copy_kernel,
        grid=(m,),
        in_specs=[
            pl.BlockSpec((1, 3), lambda i: (i, 0)),
            pl.BlockSpec(src.shape, lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, out_pad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, out_pad), jnp.uint8),
        interpret=interpret,
    )(jnp.asarray(segments, dtype=jnp.int32), src)
    return out[0, :out_len]
