"""On-device image preprocessing (the TransformSpec-on-chip path the north star asks for:
decode/normalize/augment as jitted ops instead of host numpy — BASELINE.json north_star).

All ops are shape-static and jit/vmap-friendly; they compose with the JaxDataLoader by
running on already-device-resident uint8 batches, keeping host->device traffic at 1
byte/pixel and doing the float conversion on-chip.
"""

import jax
import jax.numpy as jnp


def normalize_image(images, mean, std, dtype=jnp.bfloat16):
    """uint8 [B,H,W,C] -> normalized ``dtype``; mean/std are per-channel sequences.
    On-chip analog of the host-side transform in examples (e.g. MNIST's transform)."""
    mean = jnp.asarray(mean, dtype=jnp.float32)
    std = jnp.asarray(std, dtype=jnp.float32)
    x = images.astype(jnp.float32) / 255.0
    return ((x - mean) / std).astype(dtype)


def random_crop_flip(rng, images, crop_hw, flip=True):
    """Random crop to ``crop_hw`` + horizontal flip, batched, shape-static (the imagenet
    training augmentation, on-chip)."""
    b, h, w, c = images.shape
    ch, cw = crop_hw
    rng_crop, rng_flip = jax.random.split(rng)
    max_y = h - ch
    max_x = w - cw
    offsets_y = jax.random.randint(rng_crop, (b,), 0, max_y + 1)
    offsets_x = jax.random.randint(jax.random.fold_in(rng_crop, 1), (b,), 0, max_x + 1)

    def crop_one(image, oy, ox):
        return jax.lax.dynamic_slice(image, (oy, ox, 0), (ch, cw, c))

    cropped = jax.vmap(crop_one)(images, offsets_y, offsets_x)
    if flip:
        do_flip = jax.random.bernoulli(rng_flip, 0.5, (b,))
        flipped = jnp.flip(cropped, axis=2)
        cropped = jnp.where(do_flip[:, None, None, None], flipped, cropped)
    return cropped
