"""Pallas TPU flash attention: O(T)-memory blockwise attention on the MXU.

Forward pass is a Pallas kernel (grid over [batch*heads, q-blocks, kv-blocks], online
log-sum-exp softmax accumulated in VMEM scratch, matmuls in fp32 on the MXU) that also
emits the per-row log-sum-exp. Backward is the flash backward: two Pallas kernels (dQ,
and dK/dV) that REMATERIALIZE the score blocks from Q/K and the saved LSE — the
[T, T] attention matrix never exists in any pass, so training memory is O(T * block),
sub-quadratic in sequence length.

Falls back to the XLA path (:func:`petastorm_tpu.ops.ring_attention.dense_attention`)
when shapes don't tile (T % block != 0, head_dim not lane-aligned) and runs in Pallas
interpret mode on CPU so tests exercise the same kernel logic without a TPU.

No reference analog (petastorm is data-layer only; SURVEY.md §5.7) — this is the compute
side of the long-context story next to :mod:`petastorm_tpu.ops.ring_attention`.
"""

import functools

import jax
import jax.numpy as jnp

_NEG_INF = -1e30
_LANE = 128


def _tpu_compiler_params(pltpu, dimension_semantics):
    """jax API-drift shim: pallas TPU compiler params were named
    ``TPUCompilerParams`` before jax 0.4.34-era releases renamed the class to
    ``CompilerParams``. Resolve whichever this jax ships so the kernels work (and
    the 13 flash tests stay green) across the drift."""
    cls = getattr(pltpu, 'CompilerParams', None) or pltpu.TPUCompilerParams
    return cls(dimension_semantics=dimension_semantics)


def _block_segment_mask(qseg, kseg):
    """[Bq], [Bk] int32 -> [Bq, Bk] bool: same packed segment, both non-padding
    (``ops.packing`` convention: 0 = padding)."""
    same = qseg[:, None] == kseg[None, :]
    valid = (qseg[:, None] > 0) & (kseg[None, :] > 0)
    return same & valid


def _flash_kernel(q_ref, k_ref, v_ref, *rest, causal, segmented, block_q, block_k,
                  scale):
    """One (bh, qi, ki) grid step: fold K/V block ``ki`` into the online softmax
    accumulator for Q block ``qi``. With ``segmented``, two extra int32 refs carry
    the packed-segment ids and attention is confined within segments."""
    from jax.experimental import pallas as pl

    if segmented:
        qseg_ref, kseg_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest

    # program_id must be read at kernel top level: inside a pl.when closure it does not
    # substitute under the CPU interpreter.
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _fold():
        q = q_ref[0].astype(jnp.float32)                       # [Bq, D]
        k = k_ref[0].astype(jnp.float32)                       # [Bk, D]
        v = v_ref[0].astype(jnp.float32)                       # [Bk, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale  # [Bq, Bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        if segmented:
            s = jnp.where(_block_segment_mask(qseg_ref[0], kseg_ref[0]), s,
                          _NEG_INF)
        m_prev = m_scr[:, :1]                                  # [Bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                                 # [Bq, Bk]
        if segmented:
            # A fully-masked row has every s at _NEG_INF and would get p == 1
            # everywhere (exp(0)); zero those so empty rows accumulate nothing.
            p = p * (s > _NEG_INF / 2)
        corr = jnp.exp(m_prev - m_new)                         # [Bq, 1]
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # Blocks strictly above the diagonal contribute nothing: skip their matmuls.
        @pl.when(ki * block_k <= qi * block_q + (block_q - 1))
        def _():
            _fold()
    else:
        _fold()

    @pl.when(ki == nk - 1)
    def _finalize():
        if segmented:
            l = l_scr[:, :1]
            nonempty = l > 0
            # Padding rows attend to nothing: emit zeros, and an lse of 0 so the
            # backward's replay exp(s - lse) underflows to 0 instead of NaN.
            o_ref[0] = jnp.where(
                nonempty, acc_scr[:] / jnp.where(nonempty, l, 1.0), 0.0
            ).astype(o_ref.dtype)
            lse_ref[0] = jnp.where(nonempty, m_scr[:, :1] + jnp.log(
                jnp.where(nonempty, l, 1.0)), 0.0)[:, 0]
        else:
            o_ref[0] = (acc_scr[:] / l_scr[:, :1]).astype(o_ref.dtype)
            # log-sum-exp per query row: the backward's softmax replay key
            lse_ref[0] = (m_scr[:, :1] + jnp.log(l_scr[:, :1]))[:, 0]


def _flash_forward(q, k, v, causal, block_q, block_k, interpret, segments=None,
                   heads=None):
    """q/k/v: [BH, T, D] -> (o: [BH, T, D], lse: [BH, T] float32). ``segments`` is
    the [B, T] int32 packed-segment array (shared across the ``heads`` interleaved
    into the BH dim)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q.shape
    tk = k.shape[1]
    nq, nk = t // block_q, tk // block_k
    scale = d ** -0.5
    segmented = segments is not None
    kernel = functools.partial(_flash_kernel, causal=causal, segmented=segmented,
                               block_q=block_q, block_k=block_k, scale=scale)
    grid = (bh, nq, nk)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
    ]
    operands = [q, k, v]
    if segmented:
        h = heads
        in_specs += [
            pl.BlockSpec((1, block_q), lambda b, i, j: (b // h, i)),
            pl.BlockSpec((1, block_k), lambda b, i, j: (b // h, j)),
        ]
        operands += [segments, segments]
    return pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((bh, t, d), q.dtype),
                   jax.ShapeDtypeStruct((bh, t), jnp.float32)],
        grid=grid,
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                   pl.BlockSpec((1, block_q), lambda b, i, j: (b, i))],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANE), jnp.float32),   # running max (lane-replicated)
            pltpu.VMEM((block_q, _LANE), jnp.float32),   # running denominator
            pltpu.VMEM((block_q, d), jnp.float32),       # output accumulator
        ],
        compiler_params=_tpu_compiler_params(
            pltpu, ('parallel', 'parallel', 'arbitrary')),
        interpret=interpret,
    )(*operands)


def _rematerialized_p_ds(q, k, v, do, lse, delta, qi, ki, causal, block_q, block_k,
                         scale, seg_mask=None):
    """Shared backward-block math: replay P from (Q, K, LSE), form dS.

    Returns (p, ds), both [Bq, Bk] fp32. ``delta = rowsum(dO * O)`` is the softmax
    jacobian's diagonal correction (flash-attention backward identity).
    ``seg_mask`` re-applies the forward's segment confinement (the replayed
    exp(s - lse) is only meaningful where the forward attended)."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    p = jnp.exp(s - lse[:, None])                               # [Bq, Bk]
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        p = jnp.where(q_pos >= k_pos, p, 0.0)
    if seg_mask is not None:
        p = jnp.where(seg_mask, p, 0.0)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)  # [Bq, Bk]
    ds = p * (dp - delta[:, None])
    return p, ds


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                         causal, segmented, block_q, block_k, scale):
    """Grid (bh, qi, ki): accumulate dQ for q-block qi over all k-blocks."""
    from jax.experimental import pallas as pl

    if segmented:
        qseg_ref, kseg_ref, dq_ref, dq_scr = rest
    else:
        dq_ref, dq_scr = rest

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _fold():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        seg_mask = (_block_segment_mask(qseg_ref[0], kseg_ref[0])
                    if segmented else None)
        _, ds = _rematerialized_p_ds(q, k, v, do, lse_ref[0], delta_ref[0], qi, ki,
                                     causal, block_q, block_k, scale, seg_mask)
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        @pl.when(ki * block_k <= qi * block_q + (block_q - 1))
        def _():
            _fold()
    else:
        _fold()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                          causal, segmented, block_q, block_k, scale):
    """Grid (bh, ki, qi): accumulate dK/dV for k-block ki over all q-blocks."""
    from jax.experimental import pallas as pl

    if segmented:
        qseg_ref, kseg_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest

    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _fold():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        seg_mask = (_block_segment_mask(qseg_ref[0], kseg_ref[0])
                    if segmented else None)
        p, ds = _rematerialized_p_ds(q, k, v, do, lse_ref[0], delta_ref[0], qi, ki,
                                     causal, block_q, block_k, scale, seg_mask)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        # q-blocks entirely above the diagonal (every q_pos < k_pos) contribute nothing
        @pl.when(qi * block_q + (block_q - 1) >= ki * block_k)
        def _():
            _fold()
    else:
        _fold()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, do, causal, block_q, block_k, interpret,
                    segments=None, heads=None):
    """q/k/v/o/do: [BH, T, D], lse: [BH, T] -> (dq, dk, dv), blockwise (no [T, T])."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q.shape
    nq, nk = t // block_q, t // block_k
    scale = d ** -0.5
    segmented = segments is not None
    # Softmax jacobian diagonal: delta_i = sum_d dO_id * O_id (O(T*D), no score matrix).
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # [BH, T]

    qspec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
    qrow = pl.BlockSpec((1, block_q), lambda b, i, j: (b, i))

    dq_in_specs = [qspec, kspec, kspec, qspec, qrow, qrow]
    dq_operands = [q, k, v, do, lse, delta]
    if segmented:
        h = heads
        dq_in_specs += [pl.BlockSpec((1, block_q), lambda b, i, j: (b // h, i)),
                        pl.BlockSpec((1, block_k), lambda b, i, j: (b // h, j))]
        dq_operands += [segments, segments]
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, causal=causal, segmented=segmented,
                          block_q=block_q, block_k=block_k, scale=scale),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        grid=(bh, nq, nk),
        in_specs=dq_in_specs,
        out_specs=qspec,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_tpu_compiler_params(
            pltpu, ('parallel', 'parallel', 'arbitrary')),
        interpret=interpret,
    )(*dq_operands)

    # dK/dV iterate the OTHER way: outer over k-blocks, inner over q-blocks.
    kspec_o = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0))
    qspec_i = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0))
    qrow_i = pl.BlockSpec((1, block_q), lambda b, i, j: (b, j))
    dkv_in_specs = [qspec_i, kspec_o, kspec_o, qspec_i, qrow_i, qrow_i]
    dkv_operands = [q, k, v, do, lse, delta]
    if segmented:
        h = heads
        dkv_in_specs += [pl.BlockSpec((1, block_q), lambda b, i, j: (b // h, j)),
                         pl.BlockSpec((1, block_k), lambda b, i, j: (b // h, i))]
        dkv_operands += [segments, segments]
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, causal=causal, segmented=segmented,
                          block_q=block_q, block_k=block_k, scale=scale),
        out_shape=[jax.ShapeDtypeStruct((bh, t, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, t, d), v.dtype)],
        grid=(bh, nk, nq),
        in_specs=dkv_in_specs,
        out_specs=[kspec_o, kspec_o],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_tpu_compiler_params(
            pltpu, ('parallel', 'parallel', 'arbitrary')),
        interpret=interpret,
    )(*dkv_operands)
    return dq, dk, dv


def _tiles(t, d, block_q, block_k):
    return t % block_q == 0 and t % block_k == 0 and d % _LANE == 0


# 'auto' preference order: 256 first (the measured default — keeps behavior
# identical for every shape that already tiled), then 128 to widen Pallas
# coverage (e.g. T=384, T=1920). Both MXU/VPU-lane aligned.
_BLOCK_CANDIDATES = (256, 128)


def _resolve_blocks(t, block_q, block_k):
    """Turn ``'auto'`` block sizes into concrete tile sizes for sequence
    length ``t``. Deterministic in (t, request), so the custom-vjp forward and
    backward always resolve identically. When nothing divides ``t`` the 256
    placeholder simply fails ``_tiles`` and the dense path runs, exactly like
    an explicit non-dividing request."""
    def one(req):
        if req == 'auto':
            for cand in _BLOCK_CANDIDATES:
                if t % cand == 0:
                    return cand
            return 256
        return req
    return one(block_q), one(block_k)


def _dispatch(q, k, block_q, block_k):
    """Single resolve-then-decide point shared by every fwd/bwd path:
    ``(use_pallas, resolved_block_q, resolved_block_k)``."""
    b, t, h, d = q.shape
    block_q, block_k = _resolve_blocks(t, block_q, block_k)
    return (_tiles(t, d, block_q, block_k) and t == k.shape[1],
            block_q, block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal=False, block_q='auto', block_k='auto'):
    """Flash attention over ``[B, T, H, D]`` inputs (same layout as
    :func:`~petastorm_tpu.ops.ring_attention.dense_attention`). Exact; both passes run
    as Pallas TPU kernels when shapes tile (XLA dense fallback otherwise), with
    O(T * block) memory in forward AND backward. Block sizes default to
    ``'auto'``: 256 when it divides T (the measured default), else 128 — pass
    ints to pin them (e.g. from a tile-size sweep)."""
    return _attention_impl(q, k, v, causal, block_q, block_k)


def _use_pallas(q, k, block_q, block_k):
    """Dispatch predicate only (bench.py asserts flash_no_fallback with it);
    kernel paths use _dispatch to also get the resolved block sizes."""
    return _dispatch(q, k, block_q, block_k)[0]


def _to_bh(x):
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _from_bh(x, b, h):
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _attention_impl(q, k, v, causal, block_q, block_k):
    return _fwd(q, k, v, causal, block_q, block_k)[0]


def _fwd(q, k, v, causal, block_q, block_k):
    from petastorm_tpu.ops.ring_attention import dense_attention
    use, block_q, block_k = _dispatch(q, k, block_q, block_k)
    if not use:
        return dense_attention(q, k, v, causal=causal), (q, k, v, None, None, None)
    b, t, h, d = q.shape
    interpret = jax.default_backend() != 'tpu'
    # Residuals stay in the kernels' [BH, T, D] layout so the backward re-uses the
    # forward's transposes instead of redoing them.
    q_bh, k_bh, v_bh = _to_bh(q), _to_bh(k), _to_bh(v)
    o_bh, lse = _flash_forward(q_bh, k_bh, v_bh, causal, block_q, block_k, interpret)
    return _from_bh(o_bh, b, h), (q_bh, k_bh, v_bh, o_bh, lse, (b, h))


def _bwd(causal, block_q, block_k, residuals, g):
    q_bh, k_bh, v_bh, o_bh, lse, bh_dims = residuals
    if o_bh is None:
        # Fallback shapes: recompute through the dense path (O(T^2) memory there too).
        from petastorm_tpu.ops.ring_attention import dense_attention
        _, vjp = jax.vjp(lambda a, b_, c: dense_attention(a, b_, c, causal=causal),
                         q_bh, k_bh, v_bh)
        return vjp(g)
    b, h = bh_dims
    interpret = jax.default_backend() != 'tpu'
    block_q, block_k = _resolve_blocks(q_bh.shape[1], block_q, block_k)
    dq, dk, dv = _flash_backward(q_bh, k_bh, v_bh, o_bh, lse, _to_bh(g), causal,
                                 block_q, block_k, interpret)
    return _from_bh(dq, b, h), _from_bh(dk, b, h), _from_bh(dv, b, h)



flash_attention.defvjp(_fwd, _bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def flash_attention_segmented(q, k, v, segments, causal=False, block_q='auto',
                              block_k='auto'):
    """Flash attention confined to packed-sequence segments: ``[B, T, H, D]``
    inputs plus ``segments [B, T]`` int32 (``ops.packing`` convention — 0 is
    padding, documents numbered from 1; padding rows emit zeros). Same Pallas
    kernels as :func:`flash_attention` with the segment mask fused into every
    block, so packed single-chip training keeps the O(T * block) memory bound;
    falls back to the masked XLA dense path when shapes don't tile. Block
    sizes default to ``'auto'`` (see :func:`flash_attention`)."""
    return _seg_fwd(q, k, v, segments, causal, block_q, block_k)[0]


def _seg_fwd(q, k, v, segments, causal, block_q, block_k):
    use, block_q, block_k = _dispatch(q, k, block_q, block_k)
    if not use:
        from petastorm_tpu.ops.packing import masked_dense_attention, segment_mask
        mask = segment_mask(segments, segments, causal=causal)
        return (masked_dense_attention(q, k, v, mask),
                (q, k, v, segments, None, None, None))
    b, t, h, d = q.shape
    interpret = jax.default_backend() != 'tpu'
    q_bh, k_bh, v_bh = _to_bh(q), _to_bh(k), _to_bh(v)
    o_bh, lse = _flash_forward(q_bh, k_bh, v_bh, causal, block_q, block_k,
                               interpret, segments=segments, heads=h)
    return _from_bh(o_bh, b, h), (q_bh, k_bh, v_bh, segments, o_bh, lse, (b, h))


def _seg_zero_cotangent(segments):
    import numpy as np
    return np.zeros(segments.shape, dtype=jax.dtypes.float0)


def _seg_bwd(causal, block_q, block_k, residuals, g):
    q_bh, k_bh, v_bh, segments, o_bh, lse, bh_dims = residuals
    if o_bh is None:
        from petastorm_tpu.ops.packing import masked_dense_attention, segment_mask
        mask = segment_mask(segments, segments, causal=causal)
        _, vjp = jax.vjp(lambda a, b_, c: masked_dense_attention(a, b_, c, mask),
                         q_bh, k_bh, v_bh)
        return vjp(g) + (_seg_zero_cotangent(segments),)
    b, h = bh_dims
    interpret = jax.default_backend() != 'tpu'
    block_q, block_k = _resolve_blocks(q_bh.shape[1], block_q, block_k)
    dq, dk, dv = _flash_backward(q_bh, k_bh, v_bh, o_bh, lse, _to_bh(g), causal,
                                 block_q, block_k, interpret, segments=segments,
                                 heads=h)
    return (_from_bh(dq, b, h), _from_bh(dk, b, h), _from_bh(dv, b, h),
            _seg_zero_cotangent(segments))


flash_attention_segmented.defvjp(_seg_fwd, _seg_bwd)
