"""Pallas TPU flash attention: O(T)-memory blockwise attention on the MXU.

Forward pass is a Pallas kernel (grid over [batch*heads, q-blocks, kv-blocks], online
log-sum-exp softmax accumulated in VMEM scratch, matmuls in fp32 on the MXU). Backward
is a ``jax.custom_vjp`` that recomputes attention blockwise with XLA ops — correct and
memory-bounded, with the forward savings where they matter most for inference/serving.

Falls back to the XLA path (:func:`petastorm_tpu.ops.ring_attention.dense_attention`)
when shapes don't tile (T % block != 0, head_dim not lane-aligned) and runs in Pallas
interpret mode on CPU so tests exercise the same kernel logic without a TPU.

No reference analog (petastorm is data-layer only; SURVEY.md §5.7) — this is the compute
side of the long-context story next to :mod:`petastorm_tpu.ops.ring_attention`.
"""

import functools

import jax
import jax.numpy as jnp

_NEG_INF = -1e30
_LANE = 128


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *, causal,
                  block_q, block_k, scale):
    """One (bh, qi, ki) grid step: fold K/V block ``ki`` into the online softmax
    accumulator for Q block ``qi``."""
    from jax.experimental import pallas as pl

    # program_id must be read at kernel top level: inside a pl.when closure it does not
    # substitute under the CPU interpreter.
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _fold():
        q = q_ref[0].astype(jnp.float32)                       # [Bq, D]
        k = k_ref[0].astype(jnp.float32)                       # [Bk, D]
        v = v_ref[0].astype(jnp.float32)                       # [Bk, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale  # [Bq, Bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_scr[:, :1]                                  # [Bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                                 # [Bq, Bk]
        corr = jnp.exp(m_prev - m_new)                         # [Bq, 1]
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # Blocks strictly above the diagonal contribute nothing: skip their matmuls.
        @pl.when(ki * block_k <= qi * block_q + (block_q - 1))
        def _():
            _fold()
    else:
        _fold()

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / l_scr[:, :1]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, block_q, block_k, interpret):
    """q/k/v: [BH, T, D] -> o: [BH, T, D]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q.shape
    tk = k.shape[1]
    nq, nk = t // block_q, tk // block_k
    scale = d ** -0.5
    kernel = functools.partial(_flash_kernel, causal=causal, block_q=block_q,
                               block_k=block_k, scale=scale)
    grid = (bh, nq, nk)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANE), jnp.float32),   # running max (lane-replicated)
            pltpu.VMEM((block_q, _LANE), jnp.float32),   # running denominator
            pltpu.VMEM((block_q, d), jnp.float32),       # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'arbitrary')),
        interpret=interpret,
    )(q, k, v)


def _tiles(t, d, block_q, block_k):
    return t % block_q == 0 and t % block_k == 0 and d % _LANE == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal=False, block_q=256, block_k=256):
    """Flash attention over ``[B, T, H, D]`` inputs (same layout as
    :func:`~petastorm_tpu.ops.ring_attention.dense_attention`). Exact; forward runs as a
    Pallas TPU kernel when shapes tile, XLA blockwise otherwise."""
    return _attention_impl(q, k, v, causal, block_q, block_k)


def _attention_impl(q, k, v, causal, block_q, block_k):
    from petastorm_tpu.ops.ring_attention import dense_attention
    b, t, h, d = q.shape
    if not _tiles(t, d, block_q, block_k) or t != k.shape[1]:
        return dense_attention(q, k, v, causal=causal)
    interpret = jax.default_backend() != 'tpu'
    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    o = _flash_forward(to_bh(q), to_bh(k), to_bh(v), causal, block_q, block_k, interpret)
    return o.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _fwd(q, k, v, causal, block_q, block_k):
    return _attention_impl(q, k, v, causal, block_q, block_k), (q, k, v)


def _bwd(causal, block_q, block_k, residuals, g):
    """Recompute-backward in XLA: correct gradients at O(T^2) flops, O(T^2) attention
    matrix rematerialized under XLA fusion (not stored from forward)."""
    from petastorm_tpu.ops.ring_attention import dense_attention
    q, k, v = residuals
    _, vjp = jax.vjp(lambda a, b_, c: dense_attention(a, b_, c, causal=causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
