"""Materialization-free random index permutation (TPU-native epoch shuffling).

``jax.random.permutation(key, n)`` compiles to a sort — ~50 ms for n=50k on a v5e,
which can rival the *training compute* of an entire small-model epoch. TPUs are
systolic-array machines; sorting is their weakest op. This module provides the
standard alternative (the trick behind tf.random_index_shuffle): a **cycle-walking
Feistel cipher** over ``[0, n)`` — a keyed bijection evaluated *pointwise*, so a batch
of B positions costs O(B) elementwise uint32 ops, nothing is materialized, and the
permutation for any batch of any epoch is computed on demand inside the same compiled
program that consumes it.

Construction: round up the domain to ``2^k`` with ``k = ceil(log2 n)`` exactly, split
indices into a high ``k//2``-bit half and a low ``k - k//2``-bit half, and run a fixed
number of *alternating* Feistel rounds (odd/even rounds mix opposite halves — the
alternating form keeps the bijection for unequal half widths, so ``k`` never needs
rounding up to even and the domain stays ``< 2n``). The round function is murmur-style
keyed mixing in uint32 wraparound arithmetic. Values landing in ``[n, 2^k)``
cycle-walk by re-encrypting until they fall below ``n`` — expected < 2 walks since
``2^k < 2n``. Each round key derives from a ``jax.random`` key, so the permutation is
seeded and reproducible like the sort it replaces.

No reference analog: petastorm shuffles with numpy/torch permutations on the host
(reference: reader_impl/shuffling_buffer.py:116-140, pytorch.py:464-489).
"""

import jax
import jax.numpy as jnp
import numpy as np

_DEFAULT_ROUNDS = 4


def _round_fn(value, round_key, mask):
    """Murmur3-style mixing of one Feistel half under a round key (uint32 wrap)."""
    h = (value ^ round_key) * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h & mask


def _encrypt(x, round_keys, right_bits, left_mask, right_mask):
    # Alternating Feistel over unequal halves: XOR-ing one half with a keyed hash of
    # the other is invertible regardless of widths, so any k works (no even-k padding
    # of the domain).
    left = (x >> right_bits) & left_mask
    right = x & right_mask
    for i, round_key in enumerate(round_keys):
        if i % 2 == 0:
            left = left ^ _round_fn(right, round_key, left_mask)
        else:
            right = right ^ _round_fn(left, round_key, right_mask)
    return (left << right_bits) | right


def random_index_shuffle(positions, key, n, rounds=_DEFAULT_ROUNDS):
    """Map ``positions`` in ``[0, n)`` through a seeded pseudorandom permutation of
    ``[0, n)``, elementwise — the TPU-friendly replacement for indexing into
    ``jax.random.permutation(key, n)``.

    :param positions: int array of indices in ``[0, n)`` (any shape).
    :param key: ``jax.random`` PRNG key selecting the permutation.
    :param n: domain size (python int; static under jit).
    :param rounds: Feistel rounds (4 is plenty for decorrelation; not crypto).
    :return: int32 array, same shape: ``perm[positions]`` of a full permutation.
    """
    n = int(n)
    if n < 1:
        raise ValueError('n must be >= 1')
    if n == 1:
        return jnp.zeros_like(jnp.asarray(positions, jnp.int32))
    k = max(1, int(np.ceil(np.log2(n))))
    left_bits = k // 2
    right_bits = jnp.uint32(k - left_bits)       # >= left_bits; k never padded
    left_mask = jnp.uint32((1 << left_bits) - 1)
    right_mask = jnp.uint32((1 << (k - left_bits)) - 1)
    round_keys = list(jax.random.randint(
        key, (rounds,), 0, np.iinfo(np.int32).max, dtype=jnp.int32).astype(jnp.uint32))
    x = jnp.asarray(positions).astype(jnp.uint32)
    limit = jnp.uint32(n)

    x = _encrypt(x, round_keys, right_bits, left_mask, right_mask)

    def any_out_of_range(x):
        return jnp.any(x >= limit)

    def walk(x):
        # Re-encrypt only the out-of-range lanes; in-range lanes stay put. The cipher
        # is a bijection on [0, 2^k), so walking always terminates (expected < 2
        # iterations because 2^k < 2n).
        walked = _encrypt(x, round_keys, right_bits, left_mask, right_mask)
        return jnp.where(x >= limit, walked, x)

    return jax.lax.while_loop(any_out_of_range, walk, x).astype(jnp.int32)
