"""On-chip image decode: JPEG-style DCT-domain storage with the IDCT on the MXU.

SURVEY.md §7.3 asks for a decode-as-jax-op variant of the image codec. A literal JPEG
decoder is a poor fit for TPU: Huffman/entropy decoding is bit-serial with
data-dependent control flow — exactly what XLA/the MXU cannot vectorize. The TPU-first
split keeps the *transform* FLOPs (dequantize + 8x8 inverse DCT + color conversion — the
bulk of decode compute) on-chip and removes the entropy stage entirely: images are
stored as JPEG-style quantized DCT coefficients (int16, zigzag-free) and Parquet's
page-level compression (zstd/snappy over the many zero coefficients) plays the role of
the entropy coder.

- :func:`dct_encode_image` (host, vectorized numpy): RGB->YCbCr, 8x8 blockwise DCT,
  JPEG quality-scaled quantization -> int16 coefficient blocks.
- :func:`dct_decode_image` (host, numpy): exact mirror — the
  :class:`~petastorm_tpu.codecs.DctImageCodec` host parity path.
- :func:`dct_decode_images_jax` (device, jit): batched dequant + IDCT as two 8x8
  matmul sandwiches per block (einsum -> MXU) + YCbCr->RGB, uint8 out. This is the
  codec's decode-on-device variant: the loader ships int16 coefficients
  (~= pixel bytes before page compression) and the chip does the math.

The quantization/limits match libjpeg's quality scaling, so storage cost and fidelity
are JPEG-like (without its entropy coding, recovered by Parquet page compression).
"""

import numpy as np

# Standard JPEG base quantization tables (Annex K) — luminance and chrominance.
_LUM_BASE = np.array([
    [16, 11, 10, 16, 24, 40, 51, 61],
    [12, 12, 14, 19, 26, 58, 60, 55],
    [14, 13, 16, 24, 40, 57, 69, 56],
    [14, 17, 22, 29, 51, 87, 80, 62],
    [18, 22, 37, 56, 68, 109, 103, 77],
    [24, 35, 55, 64, 81, 104, 113, 92],
    [49, 64, 78, 87, 103, 121, 120, 101],
    [72, 92, 95, 98, 112, 100, 103, 99]], dtype=np.float32)
_CHROM_BASE = np.array([
    [17, 18, 24, 47, 99, 99, 99, 99],
    [18, 21, 26, 66, 99, 99, 99, 99],
    [24, 26, 56, 99, 99, 99, 99, 99],
    [47, 66, 99, 99, 99, 99, 99, 99],
    [99, 99, 99, 99, 99, 99, 99, 99],
    [99, 99, 99, 99, 99, 99, 99, 99],
    [99, 99, 99, 99, 99, 99, 99, 99],
    [99, 99, 99, 99, 99, 99, 99, 99]], dtype=np.float32)


def _dct_matrix():
    """8x8 DCT-II basis: D = C @ F @ C.T, F = C.T @ D @ C."""
    n = np.arange(8)
    k = n[:, None]
    c = np.cos((2 * n[None, :] + 1) * k * np.pi / 16)
    c *= np.where(k == 0, np.sqrt(1.0 / 8.0), np.sqrt(2.0 / 8.0))
    return c.astype(np.float32)


_C = _dct_matrix()


def quant_tables(quality, channels):
    """libjpeg-style quality scaling of the base tables -> [8, 8, channels] float32."""
    quality = int(np.clip(quality, 1, 100))
    scale = 5000.0 / quality if quality < 50 else 200.0 - 2.0 * quality
    tables = []
    for c in range(channels):
        base = _LUM_BASE if c == 0 else _CHROM_BASE
        tables.append(np.clip(np.floor((base * scale + 50.0) / 100.0), 1, 255))
    return np.stack(tables, axis=-1).astype(np.float32)


def _rgb_to_ycbcr(x):
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    y = 0.299 * r + 0.587 * g + 0.114 * b
    cb = -0.168736 * r - 0.331264 * g + 0.5 * b + 128.0
    cr = 0.5 * r - 0.418688 * g - 0.081312 * b + 128.0
    return np.stack([y, cb, cr], axis=-1)


def _pad_to_blocks(x):
    h, w = x.shape[:2]
    ph, pw = (-h) % 8, (-w) % 8
    if ph or pw:
        x = np.pad(x, ((0, ph), (0, pw), (0, 0)), mode='edge')
    return x


def dct_encode_image(image, quality=75):
    """uint8 [H, W, 3] (or [H, W] / [H, W, 1] grayscale) -> int16 coefficient blocks
    [H8, W8, 8, 8, C] (edge-padded to /8)."""
    if image.dtype != np.uint8:
        raise ValueError('dct_encode_image expects uint8, got {}'.format(image.dtype))
    squeeze = image.ndim == 2
    if squeeze:
        image = image[..., None]
    x = image.astype(np.float32)
    channels = x.shape[-1]
    if channels == 3:
        x = _rgb_to_ycbcr(x)
    elif channels != 1:
        raise ValueError('DCT codec supports 1 or 3 channels, got {}'.format(channels))
    x = _pad_to_blocks(x) - 128.0
    h, w = x.shape[:2]
    blocks = x.reshape(h // 8, 8, w // 8, 8, channels).transpose(0, 2, 1, 3, 4)
    # D = C F C^T over the two intra-block axes
    coeffs = np.einsum('ij,hwjkc,lk->hwilc', _C, blocks, _C)
    q = quant_tables(quality, channels)
    return np.round(coeffs / q).astype(np.int16)


def dct_decode_image(coeffs, quality=75, orig_hw=None):
    """int16 [H8, W8, 8, 8, C] -> uint8 [H, W, C] (or [H, W] when C == 1), cropped to
    ``orig_hw`` when given — the host mirror of the on-chip decode."""
    h8, w8 = coeffs.shape[:2]
    channels = coeffs.shape[-1]
    q = quant_tables(quality, channels)
    deq = coeffs.astype(np.float32) * q
    blocks = np.einsum('ji,hwjkc,kl->hwilc', _C, deq, _C)
    x = blocks.transpose(0, 2, 1, 3, 4).reshape(h8 * 8, w8 * 8, channels) + 128.0
    if channels == 3:
        x = _ycbcr_to_rgb_np(x)
    out = np.clip(np.round(x), 0, 255).astype(np.uint8)
    if orig_hw is not None:
        out = out[:orig_hw[0], :orig_hw[1]]
    return out[..., 0] if channels == 1 else out


def _ycbcr_to_rgb_np(x):
    y, cb, cr = x[..., 0], x[..., 1] - 128.0, x[..., 2] - 128.0
    r = y + 1.402 * cr
    g = y - 0.344136 * cb - 0.714136 * cr
    b = y + 1.772 * cb
    return np.stack([r, g, b], axis=-1)


def dct_decode_images_jax(coeffs, quality=75):
    """Jit-friendly batched decode: int16 [B, H8, W8, 8, 8, C] -> uint8 [B, H, W, C].

    The two einsums are 8x8 matmul sandwiches batched over every block — the shape XLA
    tiles straight onto the MXU; dequant/offset/color-convert fuse around them. Use
    inside a jitted train step so decode overlaps the rest of the step and the
    host->device transfer carries coefficients instead of decoded floats."""
    import jax.numpy as jnp

    channels = coeffs.shape[-1]
    q = jnp.asarray(quant_tables(quality, channels))
    c = jnp.asarray(_C)
    deq = coeffs.astype(jnp.float32) * q
    blocks = jnp.einsum('ji,bhwjkc,kl->bhwilc', c, deq, c)
    b, h8, w8 = blocks.shape[:3]
    x = blocks.transpose(0, 1, 3, 2, 4, 5).reshape(b, h8 * 8, w8 * 8, channels) + 128.0
    if channels == 3:
        y, cb, cr = x[..., 0], x[..., 1] - 128.0, x[..., 2] - 128.0
        x = jnp.stack([y + 1.402 * cr,
                       y - 0.344136 * cb - 0.714136 * cr,
                       y + 1.772 * cb], axis=-1)
    return jnp.clip(jnp.round(x), 0, 255).astype(jnp.uint8)
