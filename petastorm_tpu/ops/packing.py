"""Sequence packing: variable-length token rows -> fixed-shape bins with segments.

XLA compiles static shapes, so variable-length LM corpora either pad every row to the
max (wasting ``1 - mean/max`` of the FLOPs) or PACK — several documents per
fixed-length bin, with segment ids keeping attention and the LM loss from crossing
document boundaries. The reference has no analog (its NGram builds windows from
fixed-length rows); this is the TPU-first treatment of ragged text:

- **host side**: :func:`pack_sequences` (greedy first-fit, deterministic) runs inside
  the reader worker via :func:`make_packing_transform` — a ``TransformSpec`` for
  ``make_batch_reader``, so packing parallelizes across rowgroup workers and the
  loader ships only dense ``[n_bins, seq_len]`` columns;
- **device side**: :func:`segment_causal_attention` (inject as ``TransformerLM``'s
  ``attention_fn``) masks attention to (same segment AND causal AND not padding), and
  :func:`packed_next_token_loss` masks targets that would cross a boundary.

Note on positions: pass the packed ``<field>_positions`` column as the models'
``positions`` argument (``TransformerLM``/``MoETransformerLM`` accept explicit
per-token position ids) so every packed document's position embedding restarts at
0; without it the bin-global arange leaks positions across document boundaries.
"""

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = -1e30


def pack_sequences(sequences, seq_len, dtype=np.int32):
    """Greedy first-fit packing of 1-D arrays into fixed-length bins.

    :param sequences: iterable of 1-D integer arrays, each with
        ``0 < len <= seq_len`` (longer sequences raise — split upstream).
    :param seq_len: bin length.
    :returns: dict with ``tokens [n_bins, seq_len]``, ``segments`` (1-based per-bin
        segment ids, 0 = padding), ``positions`` (offset within the segment) — all
        ``dtype``/int32 numpy arrays. Deterministic: first-fit in arrival order.
    """
    bins = []          # per bin: list of sequences
    space = []         # per bin: remaining capacity
    for i, seq in enumerate(sequences):
        seq = np.asarray(seq)
        if seq.ndim != 1:
            raise ValueError('sequence {} has ndim {} (expected 1)'.format(i, seq.ndim))
        if len(seq) == 0:
            continue
        if len(seq) > seq_len:
            raise ValueError('sequence {} has length {} > seq_len {}; split it '
                             'upstream'.format(i, len(seq), seq_len))
        for b, free in enumerate(space):
            if free >= len(seq):
                bins[b].append(seq)
                space[b] -= len(seq)
                break
        else:
            bins.append([seq])
            space.append(seq_len - len(seq))

    n_bins = max(1, len(bins))
    tokens = np.zeros((n_bins, seq_len), dtype=dtype)
    segments = np.zeros((n_bins, seq_len), dtype=np.int32)
    positions = np.zeros((n_bins, seq_len), dtype=np.int32)
    for b, seqs in enumerate(bins):
        offset = 0
        for seg_id, seq in enumerate(seqs, start=1):
            end = offset + len(seq)
            tokens[b, offset:end] = seq
            segments[b, offset:end] = seg_id
            positions[b, offset:end] = np.arange(len(seq))
            offset = end
    return {'tokens': tokens, 'segments': segments, 'positions': positions}


def make_packing_transform(field, seq_len, dtype=np.int32):
    """``TransformSpec`` packing a ragged ``field`` inside ``make_batch_reader``
    workers: each rowgroup batch of variable-length rows becomes ``[n_bins,
    seq_len]`` columns ``field``, ``<field>_segments``, ``<field>_positions``.
    Feed the reader to ``JaxDataLoader`` as usual — every shape downstream is
    static. (Packing is per rowgroup batch: bins never mix rowgroups, mirroring the
    NGram window locality rule.)"""
    import pandas as pd

    from petastorm_tpu.transform import TransformSpec

    seg_field = field + '_segments'
    pos_field = field + '_positions'

    def func(frame):
        values = list(frame[field])
        if values and isinstance(values[0], bytes):
            raise ValueError(
                'field {!r} arrived as raw bytes: make_batch_reader on a Unischema '
                'store emits codec-encoded values. Pack from a NATIVE Parquet '
                'list column (the make_batch_reader contract), or decode with '
                'make_reader upstream.'.format(field))
        packed = pack_sequences(values, seq_len, dtype=dtype)
        return pd.DataFrame({field: list(packed['tokens']),
                             seg_field: list(packed['segments']),
                             pos_field: list(packed['positions'])})

    return TransformSpec(
        func,
        edit_fields=[(field, dtype, (seq_len,), False),
                     (seg_field, np.int32, (seq_len,), False),
                     (pos_field, np.int32, (seq_len,), False)],
        selected_fields=[field, seg_field, pos_field])


def segment_mask(q_segments, k_segments, causal=True):
    """Attention mask ``[B, 1, Tq, Tk]`` (broadcasts over heads): same segment AND
    both positions non-padding AND (optionally) causal."""
    same = q_segments[:, None, :, None] == k_segments[:, None, None, :]
    valid = ((q_segments > 0)[:, None, :, None]
             & (k_segments > 0)[:, None, None, :])
    mask = jnp.logical_and(same, valid)
    if causal:
        t_q, t_k = q_segments.shape[1], k_segments.shape[1]
        tri = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        mask = jnp.logical_and(mask, tri[None, None])
    return mask


def masked_dense_attention(q, k, v, mask):
    """``[B, T, H, D]`` attention with an explicit ``[B, 1, Tq, Tk]`` mask (fp32
    scores, like ``ops.ring_attention.dense_attention``). Query positions with no
    valid key (padding) return zeros instead of NaN."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum('bqhd,bkhd->bhqk', q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    any_valid = jnp.any(mask, axis=-1, keepdims=True)          # [B, 1, Tq, 1]
    p = jnp.where(any_valid, p, 0.0)
    return jnp.einsum('bhqk,bkhd->bqhd', p, v.astype(jnp.float32)).astype(q.dtype)


def segment_causal_attention(segments, use_flash=False, block_q='auto',
                             block_k='auto'):
    """Attention backend for packed batches — inject into ``TransformerLM``:

        model = TransformerLM(attention_fn=segment_causal_attention(batch['tokens_segments']))

    Tokens attend causally WITHIN their segment only; padding attends nowhere.
    ``use_flash`` routes through the Pallas segmented flash kernels
    (:func:`petastorm_tpu.ops.flash_attention.flash_attention_segmented`,
    O(T * block) memory; falls back to this dense path when shapes don't tile)."""
    if use_flash:
        from petastorm_tpu.ops.flash_attention import (_use_pallas,
                                                       flash_attention_segmented)

        def attention_fn(q, k, v):
            if not _use_pallas(q, k, block_q, block_k):
                # The flag promises the O(T*block) flash memory bound; a silent
                # dense fallback here would materialize [B, H, T, T] with no signal.
                import warnings
                warnings.warn(
                    'segment_causal_attention(use_flash=True): shapes {}x{} head_dim'
                    ' {} do not tile (need T % block == 0 and head_dim % 128 == 0); '
                    'running the O(T^2) masked dense path instead.'.format(
                        q.shape[1], k.shape[1], q.shape[-1]), stacklevel=2)
            return flash_attention_segmented(q, k, v, segments, True,
                                             block_q, block_k)
        return attention_fn

    def attention_fn(q, k, v):
        return masked_dense_attention(q, k, v, segment_mask(segments, segments))
    return attention_fn


def packed_next_token_loss(logits, tokens, segments):
    """Causal LM loss over a packed batch: position ``t`` predicts ``t+1`` only when
    both lie in the SAME non-padding segment; the mean runs over valid predictions
    only."""
    if tokens.shape[1] < 2:
        raise ValueError('packed_next_token_loss needs seq_len >= 2 (got {})'
                         .format(tokens.shape[1]))
    valid = jnp.logical_and(segments[:, 1:] == segments[:, :-1],
                            segments[:, :-1] > 0).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, tokens[:, 1:, None].astype(jnp.int32),
                               axis=-1)[..., 0]
    return jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1.0)
