"""Host<->device link characterization for streaming-floor analysis.

The streaming loaders' throughput ceiling on a tunneled accelerator is set by
the link, not the framework: every ``__iter__`` batch pays one host->device
transfer plus one dispatch round trip (``parallel/loader.py``), so

    streaming_ceiling_rows_per_sec ~= 1 / (rtt_s + row_bytes / h2d_bytes_per_sec)
                                      (per batch, divided by batch size)

This module measures the three link primitives directly — dispatch round-trip
time, host->device bandwidth, device->host bandwidth — so a bench capture can
report the measured streaming rate AGAINST the day's link ceiling instead of
against an unknowable constant.  Round-2 vs round-4 of this build measured the
same code at 98k-409k vs 7.4k rows/s streaming MNIST; the delta is the tunnel,
and this probe is the committed evidence separating framework cost from link
cost (VERDICT r3, weak item 2 / next-round item 3).

Bandwidth estimation uses a least-squares line over several transfer sizes:
``t(bytes) = t0 + bytes / bandwidth`` — the slope isolates bandwidth from the
per-op overhead ``t0``, which a single-size measurement would conflate (the
per-op overhead is itself reported as the intercept).  All timings gate on a
value readback, not ``block_until_ready`` (observed returning early through
the device tunnel — see bench.py ``force_done``).
"""
from __future__ import annotations

import json
import time

import numpy as np

__all__ = ['probe_link', 'streaming_ceiling_rows_per_sec']


def _readback_gate(x):
    """Force completion by pulling a value to the host (the project-wide
    honest-timing idiom, shared with the loaders)."""
    from petastorm_tpu.utils import value_readback_gate
    value_readback_gate(x)


def _median_time(fn, iters):
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def _fit_bandwidth(sizes_bytes, times_s):
    """Least-squares ``t = t0 + bytes/bw`` -> (bytes_per_sec, t0_s).

    With only one size, falls back to attributing the whole time to bandwidth
    (overhead indistinguishable; t0 reported as 0).
    """
    if len(sizes_bytes) < 2:
        return sizes_bytes[0] / times_s[0], 0.0
    slope, intercept = np.polyfit(np.asarray(sizes_bytes, dtype=np.float64),
                                  np.asarray(times_s, dtype=np.float64), 1)
    if slope <= 0:  # noise floor: transfers too small to resolve the slope
        return max(sizes_bytes) / min(times_s), 0.0
    return 1.0 / slope, max(float(intercept), 0.0)


def probe_link(sizes_mb=(1, 4, 16), dispatch_iters=30, transfer_iters=5):
    """Measure dispatch RTT and H2D/D2H bandwidth on the default jax device.

    Returns a dict with ``dispatch_rtt_ms``, ``h2d_mbytes_per_sec``,
    ``d2h_mbytes_per_sec``, the per-transfer overheads from the linear fit,
    and the probed ``platform``.
    """
    import jax
    import jax.numpy as jnp

    device = jax.devices()[0]

    @jax.jit
    def bump(x):
        return x + 1

    # warm: compile bump, touch the allocator at every probed size
    seed = jax.device_put(jnp.zeros((8, 128), jnp.float32), device)
    _readback_gate(bump(seed))

    rtt_s = _median_time(lambda: _readback_gate(bump(seed)), dispatch_iters)

    h2d_sizes, h2d_times = [], []
    d2h_sizes, d2h_times = [], []
    for size_mb in sizes_mb:
        n_bytes = int(size_mb * (1 << 20))
        host = np.random.RandomState(7).randint(
            0, 255, size=(n_bytes,), dtype=np.uint8)

        def h2d():
            _readback_gate(jax.device_put(host, device))

        h2d_sizes.append(n_bytes)
        h2d_times.append(_median_time(h2d, transfer_iters))

        # jax.Array caches its host copy after the first conversion, so each
        # timed conversion needs its own resident array or iterations 2..N
        # measure a cache hit instead of a transfer. `bump` makes each array a
        # distinct device buffer even if device_put dedupes the host source.
        residents = []
        for _ in range(transfer_iters):
            r = bump(jax.device_put(host, device))
            _readback_gate(r)
            residents.append(r)
        d2h_times_i = []
        for r in residents:
            t0 = time.perf_counter()
            np.asarray(r)
            d2h_times_i.append(time.perf_counter() - t0)
        del residents
        d2h_sizes.append(n_bytes)
        d2h_times.append(float(np.median(d2h_times_i)))

    h2d_bw, h2d_t0 = _fit_bandwidth(h2d_sizes, h2d_times)
    d2h_bw, d2h_t0 = _fit_bandwidth(d2h_sizes, d2h_times)
    return {
        'platform': device.platform,
        'dispatch_rtt_ms': round(rtt_s * 1e3, 3),
        'h2d_mbytes_per_sec': round(h2d_bw / (1 << 20), 2),
        'h2d_per_transfer_overhead_ms': round(h2d_t0 * 1e3, 3),
        'd2h_mbytes_per_sec': round(d2h_bw / (1 << 20), 2),
        'd2h_per_transfer_overhead_ms': round(d2h_t0 * 1e3, 3),
        'probe_sizes_mb': list(sizes_mb),
    }


def streaming_ceiling_rows_per_sec(link, row_bytes, batch_size):
    """Upper bound for a per-batch streaming loader on the measured link.

    Each batch pays one H2D transfer of ``batch_size * row_bytes`` (plus the
    fitted per-transfer overhead) and one dispatch round trip; compute overlap
    can hide compute but not the serial transfer+dispatch path this bounds.
    """
    batch_bytes = row_bytes * batch_size
    per_batch_s = (link['dispatch_rtt_ms'] / 1e3
                   + link['h2d_per_transfer_overhead_ms'] / 1e3
                   + batch_bytes / (link['h2d_mbytes_per_sec'] * (1 << 20)))
    return batch_size / per_batch_s


def main():
    """CLI: print one JSON line of link measurements on the default device."""
    import os
    if os.environ.get('JAX_PLATFORMS') == 'cpu':
        # the axon accelerator plugin pins the platform at import and ignores
        # the env var; the explicit config update is load-bearing (bench.py
        # child_main does the same)
        import jax
        jax.config.update('jax_platforms', 'cpu')
    print(json.dumps(dict(probe_link(), metric='link_probe', value=0.0,
                          unit='link', vs_baseline=0.0)))


if __name__ == '__main__':
    main()
