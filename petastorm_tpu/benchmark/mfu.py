"""Model-FLOPs-Utilization (MFU) accounting for the benchmark suite.

VERDICT r3 item 2: rows/s and tokens/s against the reference's 2018-era CPU number
(709.84 samples/s — reference docs/benchmarks_tutorial.rst:20-21) say nothing about
whether the chip is actually busy. MFU = achieved model FLOPs/s divided by the
chip's peak bf16 FLOPs/s is the honest utilization metric (the "How to Scale Your
Model" convention): *model* FLOPs are the analytically-required FLOPs of the
training step — what the math needs, not what the hardware happened to execute —
so recompute (remat) and masked-out attention don't inflate the score.

Conventions used here:

- 2 FLOPs per MAC; training = 3x forward (backward is ~2x forward for matmuls).
- Causal attention counts the causal half only (2*B*T^2*E forward per layer):
  dense attention executes the full T^2 then masks, flash skips the masked blocks
  — both get credited the same useful work.
- Embedding lookups are gathers (0 matmul FLOPs); the unembedding projection
  (E x vocab) is counted.
- For convnets, hand formulas are error-prone across stage configs, so
  :func:`xla_cost_flops` asks XLA's cost analysis for the compiled step's FLOPs
  instead. NOTE: cost analysis counts *executed* FLOPs (a Pallas/custom-call
  kernel contributes zero) — use it only for programs lowered entirely to XLA HLO
  (the ResNet step qualifies; the flash-attention step does not, which is why the
  transformer sections use the analytic path).
"""

import logging
import os

logger = logging.getLogger(__name__)

# Peak dense bf16 FLOPs/s per chip generation (public spec sheets; per chip, not
# per pod). v5e: 197 TFLOPs bf16; v4: 275; v5p: 459; v6e (Trillium): 918.
PEAK_BF16_FLOPS = {
    'v4': 275e12,
    'v5e': 197e12,
    'v5litepod': 197e12,
    'v5p': 459e12,
    'v6e': 918e12,
    'trillium': 918e12,
}


def chip_generation():
    """Best-effort TPU generation string, or None when unknown/CPU.

    The live backend decides cpu-ness FIRST: ``PALLAS_AXON_TPU_GEN`` stays set in
    the environment even when a child runs with ``JAX_PLATFORMS=cpu``, so trusting
    the env var alone would fabricate a TPU MFU for CPU fallback runs. The env var
    only refines the generation once the backend is known to be non-cpu (the axon
    tunnel reports a generic device_kind)."""
    try:
        import jax
        dev = jax.devices()[0]
    except Exception:  # any backend-init failure means "unknown", not a crash
        return None
    if dev.platform == 'cpu':
        return None
    env = os.environ.get('PALLAS_AXON_TPU_GEN')
    if env:
        return env.strip().lower()
    kind = (getattr(dev, 'device_kind', '') or '').lower()
    kind = kind.replace('tpu', '').replace(' ', '')
    for key in PEAK_BF16_FLOPS:
        if key in kind:
            return key
    if 'v5lite' in kind:
        return 'v5e'
    return kind or None


def peak_flops(generation=None):
    """Peak dense bf16 FLOPs/s for ``generation`` (default: detected), else None."""
    gen = generation if generation is not None else chip_generation()
    if gen is None:
        return None
    return PEAK_BF16_FLOPS.get(str(gen).strip().lower())


def transformer_train_flops_per_step(batch, seq_len, vocab, embed, layers,
                                     mlp_mult=4, causal=True):
    """Analytic model FLOPs for one TransformerLM train step (fwd+bwd).

    Per token per layer (forward, 2 FLOPs/MAC): qkv projection ``6E^2``, attention
    output ``2E^2``, MLP ``2*2*mlp_mult*E^2``; attention scores+values
    ``4*T*E`` full / ``2*T*E`` causal; unembedding ``2*E*vocab`` per token once.
    Heads don't change the FLOP count (H * d = E)."""
    dense_per_token = (8 + 4 * mlp_mult) * embed * embed * layers
    attn_factor = 2 if causal else 4
    attn_per_token = attn_factor * seq_len * embed * layers
    unembed_per_token = 2 * embed * vocab
    fwd = batch * seq_len * (dense_per_token + attn_per_token + unembed_per_token)
    return 3 * fwd


def moe_transformer_train_flops_per_step(batch, seq_len, vocab, embed, layers,
                                         num_experts, num_selected=1, moe_every=1,
                                         hidden_mult=4, causal=True):
    """Analytic model FLOPs for one MoETransformerLM train step (fwd+bwd).

    MoE layers swap the dense MLP for a router (``2*E*num_experts`` per token) plus
    ``num_selected`` expert MLPs (``4*hidden_mult*E^2`` per routed token). Assumes
    no token drops (capacity_factor >= num_selected with balanced routing) — a
    slight overcount when the router drops, which only *lowers* reported MFU, never
    flatters it. Dense layers (positions where ``(i+1) % moe_every != 0``) match the
    TransformerLM formula."""
    n_moe = sum(1 for i in range(layers) if (i + 1) % moe_every == 0)
    n_dense = layers - n_moe
    attn_per_layer_token = 8 * embed * embed + (2 if causal else 4) * seq_len * embed
    dense_mlp = 4 * hidden_mult * embed * embed
    moe_mlp = 2 * embed * num_experts + num_selected * 4 * hidden_mult * embed * embed
    per_token = (layers * attn_per_layer_token + n_dense * dense_mlp
                 + n_moe * moe_mlp + 2 * embed * vocab)
    return 3 * batch * seq_len * per_token


def xla_cost_flops(jitted, *args, **kwargs):
    """FLOPs of one execution of ``jitted(*args, **kwargs)`` per XLA cost analysis,
    or None when unavailable. Compiles the program (hits jax's lowering cache /
    the persistent compilation cache when warm). Counts *executed* HLO FLOPs:
    programs with custom-call kernels (Pallas) undercount — see module docstring."""
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        flops = float(analysis.get('flops', 0.0))
        return flops if flops > 0 else None
    except Exception as exc:
        logger.warning('XLA cost analysis unavailable: %s', exc)
        return None


def mfu_fields(prefix, flops_per_step, steps, elapsed_s, generation=None):
    """Bench-result fields for a measured section: ``{prefix}_model_tflops_per_sec``
    always (when FLOPs are known), ``{prefix}_mfu`` only when the chip's peak is
    known (never fabricated on CPU fallbacks). Returns {} when flops_per_step is
    None so callers can ``results.update(...)`` unconditionally."""
    if not flops_per_step or not elapsed_s or elapsed_s <= 0:
        return {}
    achieved = flops_per_step * steps / elapsed_s
    fields = {prefix + '_model_tflops_per_sec': round(achieved / 1e12, 3)}
    peak = peak_flops(generation)
    if peak:
        fields[prefix + '_mfu'] = round(achieved / peak, 4)
        fields.setdefault('mfu_peak_bf16_tflops', round(peak / 1e12, 1))
    return fields
