"""wire-bench: microbenchmarks of the zero-copy data plane (docs/performance.md).

Three fast, CPU-only measurements proving the data-plane claims from counters rather
than asserting them:

- **serializer roundtrip**: in-process serialize+deserialize MB/s of a synthetic
  ``ColumnarBatch`` through :class:`PickleSerializer` vs :class:`ArrowIpcSerializer`
  (the per-payload CPU cost, no transport).
- **transport**: a real spawned :class:`ProcessPool` streaming synthetic batches
  under three wire configurations — pickle over ZMQ, Arrow-IPC over ZMQ, Arrow-IPC
  over the shared-memory slot ring — reporting delivered MB/s and the pool's
  ``wire_bytes_copied_per_batch`` counter for each, plus the copy-reduction ratio
  of shm vs the ZMQ/pickle path (the ISSUE-2 acceptance number).
- **cache**: a dummy-pool reader over a synthetic codec store with the
  :class:`ArrowIpcDiskCache`: wall time of the cold (fill) epoch vs the warm
  (mmap-hit) epoch and their speedup ratio.

Run via ``petastorm-tpu-throughput wire-bench`` or ``python -m
petastorm_tpu.benchmark.wire_bench``; ``bench.py`` embeds it as the ``wire_bench``
section. All numbers are emitted as one JSON-safe dict.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time
from typing import Any, Dict, Optional

import numpy as np

_DEFAULT_BATCH_ROWS = 2048
_DEFAULT_BATCH_COLS = 4
_DEFAULT_BATCHES = 24
_DEFAULT_CACHE_ROWS = 1500


def _make_batch(rows: int, cols: int, seed: int = 0) -> Any:
    from petastorm_tpu.reader_worker import ColumnarBatch
    rng = np.random.RandomState(seed)
    columns = {'col_{}'.format(i): rng.rand(rows, 16).astype(np.float32)
               for i in range(cols)}
    columns['idx'] = np.arange(rows, dtype=np.int64)
    return ColumnarBatch(columns, rows, item_id=(0, 0, 0))


def _batch_payload_bytes(batch: Any) -> int:
    return sum(col.nbytes for col in batch.columns.values())


class WirePayloadWorker:
    """Pool worker that publishes one synthetic ColumnarBatch per ventilated item
    (the pool contract: exactly one result per item) — a pure transport load
    generator (no IO, no decode)."""

    def __init__(self, worker_id: int, publish_func: Any, args: Any) -> None:
        self.worker_id = worker_id
        self.publish_func = publish_func
        self.args = args

    def process(self, **kwargs: Any) -> None:
        """Publish one batch of ``rows`` x ``cols`` float32 columns."""
        # Absolute import (not the module global): when this module runs as
        # __main__, dill ships the class by value and globals don't follow.
        from petastorm_tpu.benchmark.wire_bench import _make_batch
        self.publish_func(_make_batch(kwargs['rows'], kwargs['cols'],
                                      seed=kwargs.get('seed', 0)))

    def shutdown(self) -> None:
        """Nothing to release."""


def serializer_roundtrip_bench(rows: int = _DEFAULT_BATCH_ROWS,
                               cols: int = _DEFAULT_BATCH_COLS,
                               iters: int = 20) -> Dict[str, float]:
    """In-process serialize+deserialize MB/s for pickle vs arrow-ipc."""
    from petastorm_tpu.workers.serializers import (ArrowIpcSerializer,
                                                   PickleSerializer)
    batch = _make_batch(rows, cols)
    payload = _batch_payload_bytes(batch)
    out: Dict[str, float] = {}
    for name, serializer in (('pickle', PickleSerializer()),
                             ('arrow', ArrowIpcSerializer())):
        serializer.deserialize(serializer.serialize(batch))  # warmup
        start = time.perf_counter()
        for _ in range(iters):
            frames = serializer.serialize(batch)
            serializer.deserialize([bytes(memoryview(f)) for f in frames])
        elapsed = time.perf_counter() - start
        out['roundtrip_{}_mb_s'.format(name)] = round(
            iters * payload / elapsed / (1 << 20), 2)
    return out


def _run_transport(serializer: Any, shm_transport: bool, rows: int, cols: int,
                   batches: int, workers: int) -> Dict[str, float]:
    from petastorm_tpu.workers.process_pool import ProcessPool
    pool = ProcessPool(workers, payload_serializer=serializer,
                       shm_transport=shm_transport)
    payload = _batch_payload_bytes(_make_batch(rows, cols))
    try:
        pool.start(WirePayloadWorker, None)
        for i in range(batches):
            pool.ventilate(rows=rows, cols=cols, seed=i)
        start = time.perf_counter()
        for _ in range(batches):
            pool.get_results(timeout=60)
        elapsed = time.perf_counter() - start
        diag = pool.diagnostics
    finally:
        pool.stop()
        pool.join()
    return {
        'mb_s': round(batches * payload / elapsed / (1 << 20), 2),
        'bytes_copied_per_batch': diag['wire_bytes_copied_per_batch'],
        'shm_batches': diag['shm_batches'],
        'shm_fallback_batches': diag['shm_fallback_batches'],
    }


def transport_bench(rows: int = _DEFAULT_BATCH_ROWS, cols: int = _DEFAULT_BATCH_COLS,
                    batches: int = _DEFAULT_BATCHES,
                    workers: int = 2) -> Dict[str, float]:
    """Spawned-pool transport comparison: pickle/ZMQ vs arrow/ZMQ vs arrow/shm.

    The headline counter is ``wire_bytes_copied_per_batch`` (bytes materialized
    into new host memory per delivered batch, wire receive + deserialize); the
    emitted ``copy_reduction_vs_pickle_zmq`` is that counter's ratio between
    the ZMQ/pickle path and the shm path."""
    from petastorm_tpu.workers.serializers import (ArrowIpcSerializer,
                                                   PickleSerializer)
    out: Dict[str, float] = {}
    configs = (('pickle_zmq', PickleSerializer(), False),
               ('arrow_zmq', ArrowIpcSerializer(), False),
               ('arrow_shm', ArrowIpcSerializer(), True))
    for name, serializer, shm in configs:
        result = _run_transport(serializer, shm, rows, cols, batches, workers)
        for key, value in result.items():
            out['{}_{}'.format(name, key)] = value
    pickle_copies = out.get('pickle_zmq_bytes_copied_per_batch', 0.0)
    shm_copies = out.get('arrow_shm_bytes_copied_per_batch', 0.0)
    if shm_copies:
        out['copy_reduction_vs_pickle_zmq'] = round(
            pickle_copies / shm_copies, 2)
    return out


def cache_bench(rows: int = _DEFAULT_CACHE_ROWS,
                cache_dir: Optional[str] = None) -> Dict[str, float]:
    """Cold fill vs warm (mmap-hit) epoch over the ArrowIpcDiskCache.

    Builds a small NdarrayCodec store (decode cost per row is real work), then
    reads it twice through a dummy-pool reader sharing one cache: epoch 1 pays
    Parquet read + codec decode + cache write, epoch 2 serves decoded columns as
    zero-copy mmap views. Emits both wall times and the speedup ratio."""
    own_tmp = cache_dir is None
    base = cache_dir or tempfile.mkdtemp(prefix='ptpu-wire-bench-')
    try:
        return _cache_bench_in(base, rows)
    finally:
        # any-path cleanup: a failed epoch must not leave tens of MB in /tmp
        if own_tmp:
            shutil.rmtree(base, ignore_errors=True)


def _cache_bench_in(base: str, rows: int) -> Dict[str, float]:
    from petastorm_tpu import make_reader
    from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_rows
    from petastorm_tpu.unischema import Unischema, UnischemaField

    url = os.path.join(base, 'store')
    schema = Unischema('WireBench', [
        UnischemaField('idx', np.int64, (), ScalarCodec(), False),
        UnischemaField('vec', np.float32, (48, 48), NdarrayCodec(), False),
    ])
    rng = np.random.RandomState(0)
    write_rows('file://' + url, schema,
               [{'idx': i, 'vec': rng.rand(48, 48).astype(np.float32)}
                for i in range(rows)],
               rowgroup_size_mb=1, n_files=2)

    def epoch() -> 'tuple[float, int]':
        reader = make_reader('file://' + url, reader_pool_type='dummy',
                             num_epochs=1, shuffle_row_groups=False,
                             cache_type='local-disk',
                             cache_location=os.path.join(base, 'cache'),
                             cache_size_limit=512 * (1 << 20),
                             cache_format='arrow-ipc')
        start = time.perf_counter()
        n = sum(batch.num_rows for batch in reader.iter_columnar())
        elapsed = time.perf_counter() - start
        hits = reader.diagnostics['cache_hits']
        reader.stop()
        reader.join()
        assert n == rows, (n, rows)
        return elapsed, hits

    cold_s, cold_hits = epoch()
    warm_s, warm_hits = epoch()
    return {
        'cache_cold_fill_s': round(cold_s, 4),
        'cache_warm_epoch_s': round(warm_s, 4),
        'cache_warm_speedup': round(cold_s / warm_s, 2) if warm_s else 0.0,
        'cache_cold_hits': cold_hits,
        'cache_warm_hits': warm_hits,
    }


def run_wire_bench(rows: int = _DEFAULT_BATCH_ROWS, cols: int = _DEFAULT_BATCH_COLS,
                   batches: int = _DEFAULT_BATCHES, workers: int = 2,
                   cache_rows: int = _DEFAULT_CACHE_ROWS,
                   include_transport: bool = True,
                   include_cache: bool = True) -> Dict[str, float]:
    """Run every wire-bench section and merge the JSON-safe result dict.

    ``include_transport=False`` skips the spawned-pool comparison (the only part
    that needs subprocesses), ``include_cache=False`` the store build."""
    out: Dict[str, float] = {}
    out.update(serializer_roundtrip_bench(rows, cols))
    if include_transport:
        out.update(transport_bench(rows, cols, batches, workers))
    if include_cache:
        out.update(cache_bench(cache_rows))
    return out


def main(argv: Optional[list] = None) -> int:
    """``wire-bench`` CLI entry: run the microbench and print one JSON line."""
    import argparse
    parser = argparse.ArgumentParser(
        description='petastorm_tpu zero-copy data-plane microbench')
    parser.add_argument('--rows', type=int, default=_DEFAULT_BATCH_ROWS,
                        help='rows per synthetic batch')
    parser.add_argument('--cols', type=int, default=_DEFAULT_BATCH_COLS,
                        help='float32[16] columns per synthetic batch')
    parser.add_argument('--batches', type=int, default=_DEFAULT_BATCHES,
                        help='batches per transport configuration')
    parser.add_argument('--workers', type=int, default=2)
    parser.add_argument('--cache-rows', type=int, default=_DEFAULT_CACHE_ROWS)
    parser.add_argument('--no-transport', action='store_true',
                        help='skip the spawned process-pool comparison')
    parser.add_argument('--no-cache', action='store_true',
                        help='skip the cold-vs-warm cache epochs')
    args = parser.parse_args(argv)
    result = run_wire_bench(rows=args.rows, cols=args.cols, batches=args.batches,
                            workers=args.workers, cache_rows=args.cache_rows,
                            include_transport=not args.no_transport,
                            include_cache=not args.no_cache)
    print(json.dumps(result))
    return 0


if __name__ == '__main__':
    sys.exit(main())
