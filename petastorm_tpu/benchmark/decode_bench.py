"""decode-bench: microbenchmarks of the vectorized columnar decode engine
(docs/performance.md "Vectorized decode engine").

Per-codec whole-column kernel timings over synthetic in-memory Arrow columns —
no filesystem, no pools — so the numbers isolate exactly what ISSUE-7 changed:

- **codec kernels**: decoded rows/s and decoded MB/s for each codec through the
  compiled :class:`~petastorm_tpu.decode_engine.DecodePlan` (the engine path the
  rowgroup worker runs) vs the per-cell fallback path (base
  ``FieldCodec.decode_column`` + stacking — the pre-engine behavior), plus
  their ratio ``<codec>_speedup`` (the ISSUE-7 acceptance number for
  ``compressed_ndarray`` and the image codecs).
- **predicate pushdown**: ``in_set`` keep-mask rows/s through
  :func:`~petastorm_tpu.decode_engine.compile_predicate` (Arrow ``is_in`` on the
  pre-decode table) vs the per-row decoded dict loop, and the
  ``in_pseudorandom_split`` vectorized-vs-row-loop ratio.

Image-kernel note: ``cv2.imdecode`` dominates image columns, so their engine
win scales with the GIL-released decode fan-out (``PETASTORM_TPU_DECODE_THREADS``,
default ``min(4, cpu_count)``); the emitted ``decode_threads`` field records
what this run had. Run via ``petastorm-tpu-throughput decode-bench`` or
``python -m petastorm_tpu.benchmark.decode_bench``; ``bench.py`` embeds it as
the ``decode_bench`` section. All numbers are one JSON-safe dict.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

_DEFAULT_ROWS = 2000
_DEFAULT_IMAGE_ROWS = 512
_DEFAULT_NDARRAY_HW = 32
_DEFAULT_IMAGE_HW = 32
_TIMED_REPEATS = 3


def _best_rate(fn: Callable[[], Any], repeats: int = _TIMED_REPEATS) -> Tuple[float, Any]:
    """(best wall seconds, last result) over ``repeats`` runs — best-of defends a
    microbench against shared-host scheduling transients."""
    best = float('inf')
    result: Any = None
    for _ in range(repeats):
        start = time.perf_counter()
        result = fn()
        best = min(best, time.perf_counter() - start)
    return best, result


def _decoded_nbytes(columns: Any) -> int:
    """Total decoded payload bytes of one column result (ndarray or list)."""
    if isinstance(columns, np.ndarray):
        return int(columns.nbytes)
    return int(sum(0 if v is None else np.asarray(v).nbytes for v in columns))


def _make_codec_column(kind: str, rows: int, ndarray_hw: int,
                       image_hw: int) -> Tuple[Any, Any]:
    """(UnischemaField, encoded Arrow column) for one synthetic codec column."""
    import pyarrow as pa
    from petastorm_tpu.codecs import (CompressedImageCodec,
                                      CompressedNdarrayCodec, NdarrayCodec,
                                      ScalarCodec)
    from petastorm_tpu.unischema import UnischemaField
    rng = np.random.RandomState(17)
    if kind == 'scalar':
        field = UnischemaField('value', np.int64, (), ScalarCodec(), False)
        return field, pa.chunked_array([pa.array(
            rng.randint(0, 1 << 40, size=rows).tolist(), type=pa.int64())])
    if kind == 'ndarray':
        field = UnischemaField('tensor', np.float32, (ndarray_hw, ndarray_hw),
                               NdarrayCodec(), False)
    elif kind == 'compressed_ndarray':
        field = UnischemaField('tensor', np.float32, (ndarray_hw, ndarray_hw),
                               CompressedNdarrayCodec(), False)
    elif kind in ('image_png', 'image_jpeg'):
        codec = CompressedImageCodec('png' if kind == 'image_png' else 'jpeg',
                                     quality=80)
        field = UnischemaField('image', np.uint8, (image_hw, image_hw, 3),
                               codec, False)
    else:
        raise ValueError('Unknown codec kind {!r}'.format(kind))
    if kind.startswith('image'):
        values: List[np.ndarray] = [
            rng.randint(0, 255, (image_hw, image_hw, 3), dtype=np.uint8)
            for _ in range(rows)]
    else:
        values = [(rng.rand(ndarray_hw, ndarray_hw) * 8).astype(np.float32)
                  for _ in range(rows)]
    blobs = [field.codec.encode(field, v) for v in values]
    return field, pa.chunked_array([pa.array(blobs, type=pa.binary())])


def codec_kernel_bench(rows: int = _DEFAULT_ROWS,
                       image_rows: int = _DEFAULT_IMAGE_ROWS,
                       ndarray_hw: int = _DEFAULT_NDARRAY_HW,
                       image_hw: int = _DEFAULT_IMAGE_HW) -> Dict[str, float]:
    """Engine (compiled DecodePlan) vs per-cell fallback for every codec: rows/s
    both ways, decoded MB/s through the engine, and the speedup ratio."""
    import pyarrow as pa
    from petastorm_tpu.codecs import FieldCodec
    from petastorm_tpu.decode_engine import compile_decode_plan, stack_if_uniform
    from petastorm_tpu.unischema import Unischema
    out: Dict[str, float] = {}
    for kind in ('scalar', 'ndarray', 'compressed_ndarray', 'image_png',
                 'image_jpeg'):
        n = image_rows if kind.startswith('image') else rows
        field, column = _make_codec_column(kind, n, ndarray_hw, image_hw)
        schema = Unischema('DecodeBench', [field])
        plan = compile_decode_plan(schema, [field.name])
        table = pa.table({field.name: column})
        engine_s, engine_result = _best_rate(
            lambda plan=plan, table=table, name=field.name:
            plan.execute(table)[name])

        def fallback() -> Any:
            # the pre-engine worker path: python-object cells, per-cell decode
            # dispatch, stacked at the end
            values = FieldCodec.decode_column(field.codec, field,
                                              column.to_pylist())
            return stack_if_uniform(values, field)

        fallback_s, fallback_result = _best_rate(fallback)
        if isinstance(engine_result, np.ndarray):
            np.testing.assert_array_equal(engine_result,
                                          np.asarray(fallback_result))
        out['{}_engine_rows_per_sec'.format(kind)] = round(n / engine_s, 1)
        out['{}_fallback_rows_per_sec'.format(kind)] = round(n / fallback_s, 1)
        out['{}_engine_mb_per_sec'.format(kind)] = round(
            _decoded_nbytes(engine_result) / engine_s / (1 << 20), 2)
        out['{}_speedup'.format(kind)] = round(fallback_s / engine_s, 2)
    return out


def pushdown_bench(rows: int = _DEFAULT_ROWS * 10) -> Dict[str, float]:
    """Compiled predicate mask vs the decoded per-row dict loop, over an int64
    ``in_set`` and a string-keyed ``in_pseudorandom_split``."""
    import pyarrow as pa
    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.decode_engine import (compile_decode_plan,
                                             compile_predicate,
                                             evaluate_predicate_mask)
    from petastorm_tpu.predicates import in_pseudorandom_split, in_set
    from petastorm_tpu.unischema import Unischema, UnischemaField
    rng = np.random.RandomState(5)
    field = UnischemaField('label', np.int64, (), ScalarCodec(), False)
    schema = Unischema('PushdownBench', [field])
    table = pa.table({'label': pa.array(
        rng.randint(0, 100, size=rows).tolist(), type=pa.int64())})
    predicate = in_set({1, 5, 12, 77}, 'label')
    compiled = compile_predicate(predicate, schema)
    assert compiled is not None
    pushdown_s, mask = _best_rate(lambda: compiled.evaluate(table))
    decoded = compile_decode_plan(schema, ['label']).execute(table)

    def python_rows() -> np.ndarray:
        out = np.zeros(rows, dtype=bool)
        col = decoded['label']
        for i in range(rows):
            out[i] = bool(predicate.do_include({'label': col[i]}))
        return out

    python_s, python_mask = _best_rate(python_rows, repeats=1)
    np.testing.assert_array_equal(mask, python_mask)

    split = in_pseudorandom_split([0.5, 0.5], 0, 'label')
    vector_s, vector_mask = _best_rate(
        lambda: evaluate_predicate_mask(split, decoded, rows), repeats=1)

    def split_rows() -> np.ndarray:
        out = np.zeros(rows, dtype=bool)
        col = decoded['label']
        for i in range(rows):
            out[i] = bool(split.do_include({'label': col[i]}))
        return out

    split_python_s, split_python_mask = _best_rate(split_rows, repeats=1)
    np.testing.assert_array_equal(vector_mask, split_python_mask)
    return {
        'pushdown_in_set_rows_per_sec': round(rows / pushdown_s, 1),
        'pushdown_python_rows_per_sec': round(rows / python_s, 1),
        'pushdown_in_set_speedup': round(python_s / pushdown_s, 2),
        'pushdown_split_speedup': round(split_python_s / vector_s, 2),
    }


def run_decode_bench(rows: int = _DEFAULT_ROWS,
                     image_rows: int = _DEFAULT_IMAGE_ROWS,
                     ndarray_hw: int = _DEFAULT_NDARRAY_HW,
                     image_hw: int = _DEFAULT_IMAGE_HW,
                     include_pushdown: bool = True) -> Dict[str, float]:
    """Run every decode-bench section and merge the JSON-safe result dict."""
    from petastorm_tpu.codecs import decode_thread_count
    out: Dict[str, float] = {'decode_threads': float(decode_thread_count())}
    out.update(codec_kernel_bench(rows, image_rows, ndarray_hw, image_hw))
    if include_pushdown:
        out.update(pushdown_bench())
    return out


def main(argv: Optional[list] = None) -> int:
    """``decode-bench`` CLI entry: run the microbench and print one JSON line."""
    import argparse
    parser = argparse.ArgumentParser(
        description='petastorm_tpu vectorized decode-engine microbench')
    parser.add_argument('--rows', type=int, default=_DEFAULT_ROWS,
                        help='cells per non-image codec column')
    parser.add_argument('--image-rows', type=int, default=_DEFAULT_IMAGE_ROWS,
                        help='cells per image codec column')
    parser.add_argument('--ndarray-hw', type=int, default=_DEFAULT_NDARRAY_HW,
                        help='square tensor side for the ndarray codecs')
    parser.add_argument('--image-hw', type=int, default=_DEFAULT_IMAGE_HW,
                        help='square image side for the image codecs')
    parser.add_argument('--no-pushdown', action='store_true',
                        help='skip the predicate pushdown section')
    args = parser.parse_args(argv)
    result = run_decode_bench(rows=args.rows, image_rows=args.image_rows,
                              ndarray_hw=args.ndarray_hw, image_hw=args.image_hw,
                              include_pushdown=not args.no_pushdown)
    print(json.dumps(result))
    return 0


if __name__ == '__main__':
    sys.exit(main())
