"""Throughput CLI: ``python -m petastorm_tpu.benchmark.cli <dataset_url>`` (reference:
petastorm/benchmark/cli.py / petastorm-throughput.py console script).

Subcommands: a first positional of ``wire-bench`` dispatches to
:mod:`petastorm_tpu.benchmark.wire_bench` (zero-copy data-plane microbench, JSON
output); ``decode-bench`` dispatches to
:mod:`petastorm_tpu.benchmark.decode_bench` (vectorized decode-engine
microbench: per-codec engine-vs-fallback kernel rates + predicate pushdown —
docs/performance.md "Vectorized decode engine"); ``analyze`` dispatches to
:mod:`petastorm_tpu.telemetry.analyze` (stage
time-share ranking + bottleneck-to-knob mapping over a telemetry snapshot /
JSONL event log — docs/observability.md); ``costs`` dispatches to
:mod:`petastorm_tpu.telemetry.cost_model` (per-rowgroup/per-field cost
profiler: one trace-armed epoch folded into the persistent ledger,
expensive-rowgroup ranking + what-if rows — docs/observability.md "Cost
profiler"); ``lineage`` dispatches to
:mod:`petastorm_tpu.telemetry.lineage` (sample-lineage audit: record a
lineage-armed epoch, dry-replay-verify a recorded manifest, or diff two
recorded runs to the first divergent step — docs/observability.md "Sample
lineage & determinism audit"); ``trace`` dispatches to
:mod:`petastorm_tpu.telemetry.trace_export` (flight-recorder capture of a real
read, exported as Chrome-trace/Perfetto JSON — docs/observability.md "Flight
recorder"); ``autopsy`` dispatches to
:mod:`petastorm_tpu.telemetry.incident` (ranked probable-cause postmortem
over a captured incident bundle, exit-coded by top cause —
docs/observability.md "Incident autopsy plane"); ``pipecheck`` dispatches to
:mod:`petastorm_tpu.analysis` (AST-based data-plane invariant analyzer —
docs/static-analysis.md); ``serve`` dispatches to
:mod:`petastorm_tpu.service.fleet` (disaggregated input service: dispatcher +
decode workers in one command — docs/service.md); ``chaos`` dispatches to
:mod:`petastorm_tpu.test_util.chaos` (seeded control-plane chaos proof:
dispatcher/worker kills mid-epoch against a ledger-armed fleet, verdict by
rows-exact + lineage diff — docs/service.md "Failure modes"; ``chaos
--hosts N [--kill-host|--join-host]`` proves the elastic-sharding plane
instead: a simulated pod over a shared membership journal, verdict by
rows-exact + topology-invariant composed digest — docs/robustness.md
"Elastic pod-scale sharding"); ``doctor``
dispatches to
:mod:`petastorm_tpu.tools.doctor` (environment health report); ``history``
dispatches to :mod:`petastorm_tpu.telemetry.history` (longitudinal
observatory: list/show/compare the cross-run goodput records, exit-coded by
regression verdict — docs/observability.md "Longitudinal observatory");
anything else is the legacy dataset-throughput measurement."""

import argparse
import logging
import sys

from petastorm_tpu.benchmark.throughput import READ_JAX, READ_PYTHON, reader_throughput


def main(argv=None):
    """``petastorm-tpu-throughput`` console entry: dispatch the ``wire-bench``
    subcommand, else parse args and run
    :func:`petastorm_tpu.benchmark.throughput.reader_throughput`, printing the
    report."""
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == 'wire-bench':
        from petastorm_tpu.benchmark.wire_bench import main as wire_bench_main
        return wire_bench_main(argv[1:])
    if argv and argv[0] == 'decode-bench':
        from petastorm_tpu.benchmark.decode_bench import main as decode_bench_main
        return decode_bench_main(argv[1:])
    if argv and argv[0] == 'analyze':
        from petastorm_tpu.telemetry.analyze import main as analyze_main
        return analyze_main(argv[1:])
    if argv and argv[0] == 'costs':
        from petastorm_tpu.telemetry.cost_model import main as costs_main
        return costs_main(argv[1:])
    if argv and argv[0] == 'lineage':
        from petastorm_tpu.telemetry.lineage import main as lineage_main
        return lineage_main(argv[1:])
    if argv and argv[0] == 'trace':
        from petastorm_tpu.telemetry.trace_export import main as trace_main
        return trace_main(argv[1:])
    if argv and argv[0] == 'autopsy':
        from petastorm_tpu.telemetry.incident import main as autopsy_main
        return autopsy_main(argv[1:])
    if argv and argv[0] == 'pipecheck':
        from petastorm_tpu.analysis.cli import main as pipecheck_main
        return pipecheck_main(argv[1:])
    if argv and argv[0] == 'serve':
        from petastorm_tpu.service.fleet import serve as serve_main
        return serve_main(argv[1:])
    if argv and argv[0] == 'chaos':
        from petastorm_tpu.test_util.chaos import main as chaos_main
        return chaos_main(argv[1:])
    if argv and argv[0] == 'doctor':
        from petastorm_tpu.tools.doctor import main as doctor_main
        return doctor_main(argv[1:])
    if argv and argv[0] == 'history':
        from petastorm_tpu.telemetry.history import main as history_main
        return history_main(argv[1:])
    parser = argparse.ArgumentParser(
        description='Measure petastorm_tpu reader throughput on a dataset')
    parser.add_argument('dataset_url')
    parser.add_argument('-f', '--field-regex', nargs='+',
                        help='read only fields matching these regexes')
    parser.add_argument('-w', '--workers-count', type=int, default=3)
    parser.add_argument('-p', '--pool-type', choices=['thread', 'process', 'dummy'],
                        default='thread')
    parser.add_argument('-m', '--warmup-cycles', type=int, default=200)
    parser.add_argument('-n', '--measure-cycles', type=int, default=1000)
    parser.add_argument('-d', '--read-method', choices=[READ_PYTHON, READ_JAX],
                        default=READ_PYTHON)
    # No short flag: -q used to mean the OPPOSITE (--spawn-new-process, now the
    # default); recycling it would silently invert existing invocations.
    parser.add_argument('--in-process', action='store_true',
                        help='measure in THIS interpreter instead of a spawned one '
                             '(default spawns for a clean RSS reading, matching the '
                             'reference)')
    parser.add_argument('--jax-batch-size', type=int, default=256)
    parser.add_argument('--no-shuffle-row-groups', action='store_true')
    parser.add_argument('--profile-threads', action='store_true',
                        help='sampled cProfile across thread-pool workers (one shared '
                             'profiler slot on py3.12+); aggregate logged on shutdown')
    parser.add_argument('--ngram-length', type=int,
                        help='measure NGram windows/sec with windows of this many '
                             'timesteps instead of plain rows')
    parser.add_argument('--ngram-ts-field',
                        help='timestamp field ordering the NGram windows')
    parser.add_argument('--ngram-delta-threshold', type=int,
                        help='max timestamp gap between consecutive window timesteps '
                             '(default: unbounded)')
    parser.add_argument('--pack-field',
                        help='measure packed-bin formation: pack this native list '
                             'column inside the batch-reader workers '
                             '(ops.packing.make_packing_transform)')
    parser.add_argument('--pack-seq-len', type=int,
                        help='bin length for --pack-field')
    parser.add_argument('-v', '--verbose', action='store_true')
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.INFO)
    result = reader_throughput(
        args.dataset_url, field_regex=args.field_regex,
        warmup_cycles_count=args.warmup_cycles,
        measure_cycles_count=args.measure_cycles, pool_type=args.pool_type,
        loaders_count=args.workers_count, read_method=args.read_method,
        shuffle_row_groups=not args.no_shuffle_row_groups,
        jax_batch_size=args.jax_batch_size, spawn_new_process=not args.in_process,
        profile_threads=args.profile_threads, ngram_length=args.ngram_length,
        ngram_ts_field=args.ngram_ts_field,
        ngram_delta_threshold=args.ngram_delta_threshold,
        pack_field=args.pack_field, pack_seq_len=args.pack_seq_len)
    unit = ('windows/sec' if args.ngram_length
            else 'bins/sec' if args.pack_field else 'samples/sec')
    print('Throughput: {:.2f} {}; RSS: {:.2f} MB; CPU: {:.2f}%{}'.format(
        result.samples_per_second, unit, result.memory_info.rss / (1 << 20), result.cpu,
        '; input-stall: {:.1%}'.format(result.input_stall_fraction)
        if result.input_stall_fraction else ''))
    return 0


if __name__ == '__main__':
    sys.exit(main())
