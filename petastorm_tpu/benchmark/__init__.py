"""Throughput benchmarking (reference: petastorm/benchmark/)."""

from petastorm_tpu.benchmark.throughput import BenchmarkResult, reader_throughput  # noqa: F401
