"""Reader throughput measurement (reference: petastorm/benchmark/throughput.py:112-217).

Warmup/measure cycle split, psutil RSS + CPU%%, rows/sec — plus the TPU additions the
build plan calls for (SURVEY.md §6): per-chip rates and input-stall%% when measuring
through the JAX loader.
"""

import logging
import re
import time
from collections import namedtuple

logger = logging.getLogger(__name__)

BenchmarkResult = namedtuple('BenchmarkResult',
                             ['time_mean', 'samples_per_second', 'memory_info', 'cpu',
                              'input_stall_fraction'])

READ_PYTHON = 'python'
READ_JAX = 'jax'


def reader_throughput(dataset_url, field_regex=None, warmup_cycles_count=200,
                      measure_cycles_count=1000, pool_type='thread', loaders_count=3,
                      read_method=READ_PYTHON, shuffle_row_groups=True,
                      jax_batch_size=256, spawn_new_process=True,
                      profile_threads=False, ngram_length=None, ngram_ts_field=None,
                      ngram_delta_threshold=None, pack_field=None, pack_seq_len=None):
    """Measure read throughput of a dataset (reference: throughput.py:112-172).

    ``read_method='python'`` iterates raw reader rows; ``'jax'`` drives a JaxDataLoader
    (cycle = one batch) and also reports the loader's input-stall fraction.
    ``spawn_new_process`` (default True, matching the reference's default —
    throughput.py:115,144-149) re-runs the measurement in a fresh interpreter so the
    RSS reading reflects the pipeline alone, not the caller's footprint.
    ``profile_threads`` wraps each thread-pool worker in cProfile; the aggregate is
    logged on shutdown (reference: thread_pool.py:41-49 + benchmark/cli.py:56-57).

    ``ngram_length`` + ``ngram_ts_field`` switch the measurement to NGram window
    formation (cycle = one window of ``ngram_length`` timesteps, every field at every
    offset): the windows/sec figure benchmarks the columnar gather path.

    ``pack_field`` + ``pack_seq_len`` switch to packed-bin formation over a NATIVE
    parquet list column (cycle = one worker batch of packed bins; the rate reported
    is bins/sec): benchmarks ``ops.packing.make_packing_transform`` inside
    ``make_batch_reader`` workers."""
    # Argument validation stays ahead of the spawn so bad combinations raise in the
    # caller, not through a child interpreter.
    if profile_threads and pool_type != 'thread':
        raise ValueError('--profile-threads requires the thread pool')
    if ngram_length is None and (ngram_ts_field or ngram_delta_threshold is not None):
        raise ValueError('ngram_ts_field / ngram_delta_threshold require ngram_length')
    if ngram_length is not None:
        if not ngram_ts_field:
            raise ValueError('ngram_ts_field is required with ngram_length')
        if read_method != READ_PYTHON:
            raise ValueError('NGram benchmarking uses the python read method')
    if (pack_field is None) != (pack_seq_len is None):
        raise ValueError('pack_field and pack_seq_len must be given together')
    if pack_field is not None:
        if ngram_length is not None:
            raise ValueError('packing and NGram modes are mutually exclusive')
        if read_method != READ_PYTHON:
            raise ValueError('packing benchmarking uses the python read method')
        if profile_threads:
            # make_batch_reader takes pool_type/workers_count, not a pre-built pool.
            raise ValueError('profile_threads is not supported with pack_field')
        if field_regex and not any(re.fullmatch(pattern, pack_field)
                                   for pattern in field_regex):
            # fullmatch mirrors Unischema.match_unischema_fields (the selection
            # this guard predicts): a prefix-only pattern must fail here too.
            # A regex set that drops the packed column would otherwise surface as an
            # opaque KeyError inside a worker (ADVICE r3).
            raise ValueError(
                'field_regex {!r} does not match pack_field {!r}; the packed column '
                'must be read for packing to run'.format(field_regex, pack_field))

    if spawn_new_process:
        from petastorm_tpu.utils import run_in_subprocess
        return run_in_subprocess(reader_throughput, dataset_url, field_regex,
                                 warmup_cycles_count, measure_cycles_count, pool_type,
                                 loaders_count, read_method, shuffle_row_groups,
                                 jax_batch_size, False, profile_threads, ngram_length,
                                 ngram_ts_field, ngram_delta_threshold, pack_field,
                                 pack_seq_len)

    import psutil
    from petastorm_tpu.reader import make_reader

    process = psutil.Process()
    reader_pool = None
    if profile_threads:
        from petastorm_tpu.workers.thread_pool import ThreadPool
        reader_pool = ThreadPool(loaders_count, profiling_enabled=True)
    schema_fields = field_regex
    if ngram_length is not None:
        from petastorm_tpu.ngram import NGram
        fields = field_regex if field_regex else ['.*']
        schema_fields = NGram({offset: list(fields) for offset in range(ngram_length)},
                              delta_threshold=(ngram_delta_threshold
                                               if ngram_delta_threshold is not None
                                               else (1 << 62)),
                              timestamp_field=ngram_ts_field)
    pool_kwargs = ({'reader_pool': reader_pool} if reader_pool is not None
                   else {'reader_pool_type': pool_type, 'workers_count': loaders_count})
    if pack_field is not None:
        from petastorm_tpu.ops.packing import make_packing_transform
        from petastorm_tpu.reader import make_batch_reader
        reader = make_batch_reader(
            dataset_url,
            # Only the packed column need ever leave the parquet files (the
            # transform's selected_fields discards everything else anyway).
            schema_fields=field_regex if field_regex else [pack_field],
            transform_spec=make_packing_transform(pack_field, pack_seq_len),
            shuffle_row_groups=shuffle_row_groups, num_epochs=None, **pool_kwargs)
    else:
        reader = make_reader(dataset_url, schema_fields=schema_fields,
                             shuffle_row_groups=shuffle_row_groups, num_epochs=None,
                             **pool_kwargs)
    stall = 0.0
    packed_units = 0
    try:
        if read_method == READ_PYTHON:
            iterator = iter(reader)
            rows_per_cycle = 1
        elif read_method == READ_JAX:
            from petastorm_tpu.parallel.loader import JaxDataLoader
            loader = JaxDataLoader(reader, batch_size=jax_batch_size, prefetch=2)
            iterator = iter(loader)
            rows_per_cycle = jax_batch_size
        else:
            raise ValueError('Unknown read_method {!r}'.format(read_method))

        for _ in range(warmup_cycles_count):
            next(iterator)
        process.cpu_percent()  # reset the cpu meter
        start = time.perf_counter()
        next_report = start + 5
        for cycle in range(measure_cycles_count):
            item = next(iterator)
            if pack_field is not None:
                # A batch-reader cycle yields one worker batch of packed bins;
                # the honest unit is bins, counted from the actual batch.
                packed_units += len(getattr(item, pack_field))
            now = time.perf_counter()
            if now > next_report:
                logger.debug('cycle %d/%d, %.1f rows/s, diagnostics=%s', cycle,
                             measure_cycles_count,
                             (cycle + 1) * rows_per_cycle / (now - start),
                             getattr(reader, 'diagnostics', {}))
                next_report = now + 5
        elapsed = time.perf_counter() - start
        cpu = process.cpu_percent()
        memory = process.memory_info()
        if read_method == READ_JAX:
            stall = loader.stats.input_stall_fraction
        if pack_field is not None:
            rate = packed_units / elapsed
        else:
            rate = measure_cycles_count * rows_per_cycle / elapsed
        return BenchmarkResult(time_mean=elapsed / measure_cycles_count,
                               samples_per_second=rate, memory_info=memory, cpu=cpu,
                               input_stall_fraction=stall)
    finally:
        reader.stop()
        reader.join()
