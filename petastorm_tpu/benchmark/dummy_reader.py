"""Loader micro-benchmark over a synthetic in-memory reader (reference:
petastorm/benchmark/dummy_reader.py:26-88): times DataLoader vs BatchedDataLoader vs
JaxDataLoader across batch sizes with zero IO, isolating adapter overhead."""

import time

import numpy as np

from petastorm_tpu.codecs import ScalarCodec
from petastorm_tpu.unischema import Unischema, UnischemaField

BenchSchema = Unischema('DummyBench', [
    UnischemaField('id', np.int64, (), ScalarCodec(), False),
    UnischemaField('value', np.float32, (16,), None, False),
])


class DummyReader(object):
    """Infinite synthetic reader emitting precomputed rows (row mode)."""

    def __init__(self, num_distinct_rows=128):
        self.result_schema = BenchSchema
        self.is_batched_reader = False
        self.ngram = None
        self.last_row_consumed = False
        rng = np.random.RandomState(0)
        self._rows = [BenchSchema.make_namedtuple(
            id=i, value=rng.rand(16).astype(np.float32))
            for i in range(num_distinct_rows)]
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self):
        row = self._rows[self._i % len(self._rows)]
        self._i += 1
        return row

    def reset(self):
        pass

    def stop(self):
        pass

    def join(self):
        pass


def measure_loader(loader_factory, batches=100):
    """Rows/sec through ``batches`` batches of the loader built by
    ``loader_factory`` — the loader-overhead micro-benchmark's measuring loop."""
    loader = loader_factory()
    iterator = iter(loader)
    next(iterator)  # warmup
    start = time.perf_counter()
    rows = 0
    for _ in range(batches):
        batch = next(iterator)
        first = batch[next(iter(batch))] if isinstance(batch, dict) else batch
        rows += len(first)
    return rows / (time.perf_counter() - start)


def main():
    """Run the dummy-reader micro-bench over each loader adapter and print rates
    (reference: petastorm/benchmark/dummy_reader.py)."""
    from petastorm_tpu.parallel.loader import JaxDataLoader
    from petastorm_tpu.pytorch import DataLoader
    for batch_size in (16, 256, 1024):
        torch_rate = measure_loader(
            lambda: DataLoader(DummyReader(), batch_size=batch_size))
        jax_rate = measure_loader(
            lambda: JaxDataLoader(DummyReader(), batch_size=batch_size,
                                  device_put=False))
        print('batch={:5d}  torch DataLoader: {:>10.0f} rows/s   '
              'JaxDataLoader(host): {:>10.0f} rows/s'
              .format(batch_size, torch_rate, jax_rate))


if __name__ == '__main__':
    main()
