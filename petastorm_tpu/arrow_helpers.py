"""Arrow-table batching queue: FIFO of tables re-chunked to fixed-size-row tables
(reference: petastorm/pyarrow_helpers/batching_table_queue.py:20-95)."""

from collections import deque

import pyarrow as pa


class BatchingTableQueue(object):
    """``put`` arbitrary-size tables, ``get`` tables of exactly ``batch_size`` rows."""

    def __init__(self, batch_size):
        if batch_size < 1:
            raise ValueError('batch_size must be >= 1')
        self._batch_size = batch_size
        self._batches = deque()
        self._head_offset = 0
        self._buffered_rows = 0

    def put(self, table):
        for batch in table.to_batches():
            if batch.num_rows:
                self._batches.append(batch)
                self._buffered_rows += batch.num_rows

    def empty(self):
        return self._buffered_rows < self._batch_size

    def get(self):
        if self.empty():
            raise ValueError('Not enough rows buffered: {} < {}'
                             .format(self._buffered_rows, self._batch_size))
        needed = self._batch_size
        parts = []
        while needed > 0:
            head = self._batches[0]
            available = head.num_rows - self._head_offset
            take = min(available, needed)
            parts.append(head.slice(self._head_offset, take))
            needed -= take
            self._head_offset += take
            if self._head_offset >= head.num_rows:
                self._batches.popleft()
                self._head_offset = 0
        self._buffered_rows -= self._batch_size
        return pa.Table.from_batches(parts)
