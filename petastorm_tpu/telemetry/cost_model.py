"""Persistent per-rowgroup / per-field cost profiler: the measurement half of
the cost-aware-scheduling roadmap item (docs/observability.md "Cost
profiler").

Aggregate histograms say *decode is slow*; they cannot say *which rowgroup*.
MinatoLoader (PAPERS.md) shows per-sample preprocessing cost skews by ~100x
in real corpora — exactly the skew that stalls a batch former behind one
pathological rowgroup while the rest of the fleet idles. The flight recorder
already records every ``rowgroup_read`` / ``decode`` span tagged with its
causal ``(epoch, rowgroup, attempt)`` context (plus per-field
``decode_field`` spans while tracing is armed); this module folds that span
history into a :class:`CostLedger` keyed by the dataset token, persists it
as an ATOMIC JSON sidecar (``save``: temp file + ``os.replace`` — a crashed
writer can never corrupt the ledger), and reloads it across runs, so cost
knowledge accumulates instead of dying with each process.

Consumers:

- ``petastorm-tpu-throughput costs <dataset_url>`` — run one trace-armed
  epoch, fold it into the ledger next to the dataset (or ``--ledger``), and
  print the most expensive rowgroups, the p95/median skew, and the what-if
  rows;
- :meth:`Reader.cost_ledger` — the programmatic form over any traced read;
- ``analyze.attribute_bottleneck(snapshot, cost_ledger=...)`` — the
  bottleneck report grows ``what_if`` rows ("if every rowgroup above the p95
  cost dropped to the median, total decode time −X%");
- the cost-aware scheduler (``petastorm_tpu/schedule/``) reads the persisted
  ledger as-is: ``make_reader(cost_schedule=True)`` interleaves, splits and
  routes from it (docs/performance.md "Cost-aware scheduling"), and the
  ``costs --json`` output carries a ``schedule_preview`` of the plan.

``COST_STAGES`` declares which stage spans feed the ledger; pipecheck's
telemetry-names rule checks it against the ``STAGES`` catalog so the
profiler cannot silently drift from the span names the workers emit.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Mapping, Optional, Tuple

#: stage spans folded into per-rowgroup costs — must be a subset of
#: ``spans.STAGES`` (pipecheck's telemetry-names rule enforces it); the sum
#: over these IS a rowgroup's cost (``decode_field`` nests inside ``decode``
#: and is tracked separately per field, never added to the total)
COST_STAGES = ('rowgroup_read', 'decode', 'range_fetch')

#: the per-field span name (emitted by the decode plan while tracing is on)
FIELD_STAGE = 'decode_field'

#: ledger file format version (bumped on incompatible schema changes)
LEDGER_VERSION = 1

#: default ledger basename pattern next to the disk cache / dataset
LEDGER_BASENAME = '_petastorm_tpu_costs_{token}.json'


def percentile(sorted_values: List[float], q: float) -> float:
    """Deterministic nearest-rank percentile over an ASCENDING-sorted list
    (``q`` clamped into [0, 1]; empty input -> 0.0). Nearest-rank (not
    interpolated) so persist → reload → recompute is bit-identical, and the
    rank is double-clamped so a tiny population (a 1-entry ledger) can never
    IndexError."""
    if not sorted_values:
        return 0.0
    q = min(1.0, max(0.0, float(q)))
    rank = min(len(sorted_values) - 1,
               max(0, int(math.ceil(q * len(sorted_values))) - 1))
    return sorted_values[rank]


def default_ledger_path(dataset_url_or_path: str, dataset_token: str,
                        cache_location: Optional[str] = None
                        ) -> Optional[str]:
    """Where the ledger sidecar lives: the dataset's local state home
    (:func:`petastorm_tpu.dataset_state.sidecar_path` — next to the disk
    cache when one is configured, else next to a LOCAL dataset); None for
    remote stores with no cache — the caller must pass an explicit path."""
    from petastorm_tpu.dataset_state import sidecar_path
    return sidecar_path(dataset_url_or_path,
                        LEDGER_BASENAME.format(token=dataset_token),
                        cache_location)


class CostLedger(object):
    """Per-rowgroup cost history for ONE dataset token (module docstring).

    Entries are keyed ``'<fragment_path>#<row_group_id>'`` and hold per-stage
    ``{count, sum_s, max_s}`` plus per-field ``{count, sum_s}`` decode costs.
    When the storage engine is armed, ``range_fetch`` spans additionally
    carry per-fetch totals in their trace args, folded into an optional
    ``fetch`` cell per entry: ``{bytes, ranges, hedges_fired, hedges_won,
    sum_s, count}`` — so the measured-cost DRR scheduler prices network I/O,
    not just decode (docs/performance.md "Object-store ingest engine").
    All mutation is additive, so ledgers merge across runs, processes and
    re-dispatched attempts exactly like histogram snapshots do."""

    def __init__(self, dataset_token: str) -> None:
        self.dataset_token = dataset_token
        self._entries: Dict[str, Dict[str, Any]] = {}

    # ------------------------------------------------------------ ingestion

    @staticmethod
    def _rowgroup_key(fragment_path: str, row_group_id: Any) -> str:
        return '{}#{}'.format(fragment_path,
                              row_group_id if row_group_id is not None
                              else 'all')

    def _entry(self, key: str) -> Dict[str, Any]:
        entry = self._entries.get(key)
        if entry is None:
            entry = {'stages': {}, 'fields': {}}
            self._entries[key] = entry
        return entry

    def ingest_trace(self, trace_snapshot: Mapping[str, Any],
                     piece_map: Mapping[int, Tuple[str, Any]]) -> int:
        """Fold one flight-recorder snapshot
        (:func:`~petastorm_tpu.telemetry.tracing.trace_snapshot`) into the
        ledger. ``piece_map`` maps the trace context's rowgroup piece index
        to ``(fragment_path, row_group_id)`` — the reader's shard
        enumeration. Spans of re-dispatched attempts accumulate additively
        (the rowgroup genuinely cost that much fleet time). Returns the
        number of spans ingested."""
        ingested = 0
        for event in trace_snapshot.get('events') or []:
            if event.get('ph') != 'X':
                continue
            name = event.get('name')
            is_field = name == FIELD_STAGE
            if name not in COST_STAGES and not is_field:
                continue
            ctx = event.get('ctx')
            if not ctx or len(ctx) < 2:
                continue
            located = piece_map.get(int(ctx[1]))
            if located is None:
                continue
            seconds = float(event.get('dur_us', 0.0)) / 1e6
            entry = self._entry(self._rowgroup_key(located[0], located[1]))
            if is_field:
                args = event.get('args') or {}
                field = args.get('field')
                if not field:
                    continue
                cell = entry['fields'].setdefault(
                    str(field), {'count': 0, 'sum_s': 0.0})
                cell['count'] += 1
                cell['sum_s'] += seconds
            else:
                cell = entry['stages'].setdefault(
                    str(name), {'count': 0, 'sum_s': 0.0, 'max_s': 0.0})
                cell['count'] += 1
                cell['sum_s'] += seconds
                cell['max_s'] = max(float(cell['max_s']), seconds)
                if name == 'range_fetch':
                    self._fold_fetch(entry, event.get('args') or {}, seconds)
            ingested += 1
        return ingested

    @staticmethod
    def _fold_fetch(entry: Dict[str, Any], args: Mapping[str, Any],
                    seconds: float) -> None:
        """Fold one ``range_fetch`` span's trace args (bytes / ranges /
        hedge totals from storage/fetcher.py) into the entry's additive
        ``fetch`` cell."""
        cell = entry.setdefault('fetch', {
            'bytes': 0, 'ranges': 0, 'hedges_fired': 0, 'hedges_won': 0,
            'sum_s': 0.0, 'count': 0})
        cell['bytes'] += int(args.get('bytes', 0))
        cell['ranges'] += int(args.get('ranges', 0))
        cell['hedges_fired'] += int(args.get('hedges_fired', 0))
        cell['hedges_won'] += int(args.get('hedges_won', 0))
        cell['sum_s'] += seconds
        cell['count'] += 1

    def merge(self, other: 'CostLedger') -> None:
        """Fold another ledger in additively (same dataset token required —
        costs of different field sets / stores must never mix)."""
        if other.dataset_token != self.dataset_token:
            raise ValueError(
                'cannot merge cost ledgers of different dataset tokens '
                '({!r} vs {!r}) — the store, field set or decode mode '
                'differ'.format(other.dataset_token, self.dataset_token))
        for key, entry in other._entries.items():
            mine = self._entry(key)
            for stage, cell in entry['stages'].items():
                acc = mine['stages'].setdefault(
                    stage, {'count': 0, 'sum_s': 0.0, 'max_s': 0.0})
                acc['count'] += int(cell['count'])
                acc['sum_s'] += float(cell['sum_s'])
                acc['max_s'] = max(float(acc['max_s']), float(cell['max_s']))
            for field, cell in entry['fields'].items():
                acc = mine['fields'].setdefault(
                    field, {'count': 0, 'sum_s': 0.0})
                acc['count'] += int(cell['count'])
                acc['sum_s'] += float(cell['sum_s'])
            fetch = entry.get('fetch')
            if fetch:
                acc = mine.setdefault('fetch', {
                    'bytes': 0, 'ranges': 0, 'hedges_fired': 0,
                    'hedges_won': 0, 'sum_s': 0.0, 'count': 0})
                for k in ('bytes', 'ranges', 'hedges_fired', 'hedges_won',
                          'count'):
                    acc[k] += int(fetch.get(k, 0))
                acc['sum_s'] += float(fetch.get('sum_s', 0.0))

    # ------------------------------------------------------------- analysis

    def __len__(self) -> int:
        return len(self._entries)

    def rowgroup_cost(self, key: str) -> float:
        """Total recorded cost of one rowgroup (sum over ``COST_STAGES``)."""
        entry = self._entries.get(key)
        if entry is None:
            return 0.0
        return sum(float(cell['sum_s'])
                   for stage, cell in entry['stages'].items()
                   if stage in COST_STAGES)

    def total_seconds(self) -> float:
        """Total recorded cost across every rowgroup."""
        return sum(self.rowgroup_cost(key) for key in self._entries)

    def ranking(self, top_n: int = 10) -> List[Dict[str, Any]]:
        """The most expensive rowgroups, descending (ties broken by key so
        the order survives persist → reload byte-identically): ``{'rowgroup',
        'seconds', 'share', 'stages', 'top_fields'}`` rows."""
        total = self.total_seconds()
        costs = sorted(((self.rowgroup_cost(key), key)
                        for key in self._entries),
                       key=lambda item: (-item[0], item[1]))
        rows = []
        for seconds, key in costs[:max(top_n, 1)]:
            entry = self._entries[key]
            fields = sorted(((float(cell['sum_s']), field)
                             for field, cell in entry['fields'].items()),
                            key=lambda item: (-item[0], item[1]))
            row = {
                'rowgroup': key,
                'seconds': round(seconds, 6),
                'share': round(seconds / total, 4) if total else 0.0,
                'stages': {stage: round(float(cell['sum_s']), 6)
                           for stage, cell in sorted(entry['stages'].items())},
                'top_fields': [{'field': field, 'seconds': round(s, 6)}
                               for s, field in fields[:3]],
            }
            fetch = entry.get('fetch')
            if fetch:
                row['fetch'] = {
                    'bytes': int(fetch['bytes']),
                    'ranges': int(fetch['ranges']),
                    'hedges_fired': int(fetch['hedges_fired']),
                    'hedges_won': int(fetch['hedges_won']),
                    'seconds': round(float(fetch['sum_s']), 6),
                }
            rows.append(row)
        return rows

    def what_if(self) -> List[Dict[str, Any]]:
        """What-if rows for the bottleneck report: per scope (``total`` plus
        each cost stage), "if every rowgroup costing more than the p95
        dropped to the median, total {scope} time −X%" — the skew exposure a
        cost-aware scheduler would exploit. Deterministic (nearest-rank
        percentiles, sorted keys), so persist → reload → recompute yields an
        identical ranking."""
        rows: List[Dict[str, Any]] = []
        scopes: List[Tuple[str, Dict[str, float]]] = []
        totals = {key: self.rowgroup_cost(key) for key in self._entries}
        scopes.append(('total', totals))
        for stage in COST_STAGES:
            per_stage = {
                key: float(entry['stages'].get(stage, {}).get('sum_s', 0.0))
                for key, entry in self._entries.items()}
            scopes.append((stage, per_stage))
        for scope, costs in scopes:
            values = sorted(v for v in costs.values() if v > 0.0)
            if not values:
                if not costs:
                    continue
                # every entry recorded zero for this scope (e.g. a ledger from
                # a clock too coarse to time a trivial stage): emit an honest
                # flat row — skew 1.0, nothing to save — instead of silently
                # dropping the scope (or dividing by a zero median)
                rows.append({
                    'scope': scope, 'rowgroups': len(costs),
                    'total_s': 0.0, 'median_s': 0.0, 'p95_s': 0.0,
                    'skew_p95_over_median': 1.0, 'saving_fraction': 0.0,
                    'detail': 'no {} cost recorded — flat distribution, '
                              'nothing to reschedule'.format(scope),
                })
                continue
            total = sum(values)
            median = percentile(values, 0.5)
            p95 = percentile(values, 0.95)
            # "the p95 cost drops to the median": every rowgroup AT or above
            # the p95 is capped (>= — with nearest-rank percentiles over a
            # small population the p95 IS the max, and the tail must still
            # count); a flat distribution (p95 == median) saves nothing
            capped = sum(median if (v >= p95 and p95 > median) else v
                         for v in values)
            saving = (total - capped) / total if total else 0.0
            rows.append({
                'scope': scope,
                'rowgroups': len(values),
                'total_s': round(total, 6),
                'median_s': round(median, 6),
                'p95_s': round(p95, 6),
                # a flat (all-equal, incl. single-rowgroup) distribution is
                # skew 1.0 by definition — never NaN/0.0, so dashboards can
                # alert on "skew > threshold" without special cases
                'skew_p95_over_median': round(p95 / median, 3)
                if median > 0.0 else 1.0,
                'saving_fraction': round(saving, 4),
                'detail': 'if every rowgroup above the p95 {} cost dropped '
                          'to the median, total {} time -{:.1%}'.format(
                              scope, scope, saving),
            })
        rows.sort(key=lambda row: (-row['saving_fraction'], row['scope']))
        return rows

    # ---------------------------------------------------------- persistence

    def to_dict(self) -> Dict[str, Any]:
        """JSON-safe ledger document (sorted keys — stable on disk)."""
        return {
            'version': LEDGER_VERSION,
            'dataset_token': self.dataset_token,
            'rowgroups': {key: self._entries[key]
                          for key in sorted(self._entries)},
        }

    @classmethod
    def from_dict(cls, doc: Mapping[str, Any]) -> 'CostLedger':
        """Rebuild a ledger from :meth:`to_dict` output (version-checked)."""
        if int(doc.get('version', -1)) != LEDGER_VERSION:
            raise ValueError('unsupported cost-ledger version {!r} '
                             '(this build reads version {})'.format(
                                 doc.get('version'), LEDGER_VERSION))
        ledger = cls(str(doc['dataset_token']))
        for key, entry in (doc.get('rowgroups') or {}).items():
            mine = ledger._entry(str(key))
            for stage, cell in (entry.get('stages') or {}).items():
                mine['stages'][str(stage)] = {
                    'count': int(cell['count']),
                    'sum_s': float(cell['sum_s']),
                    'max_s': float(cell['max_s'])}
            for field, cell in (entry.get('fields') or {}).items():
                mine['fields'][str(field)] = {
                    'count': int(cell['count']),
                    'sum_s': float(cell['sum_s'])}
            fetch = entry.get('fetch')
            if fetch:
                # optional additive cell (absent in pre-storage-engine
                # ledgers — same LEDGER_VERSION, purely additive schema)
                mine['fetch'] = {
                    'bytes': int(fetch.get('bytes', 0)),
                    'ranges': int(fetch.get('ranges', 0)),
                    'hedges_fired': int(fetch.get('hedges_fired', 0)),
                    'hedges_won': int(fetch.get('hedges_won', 0)),
                    'sum_s': float(fetch.get('sum_s', 0.0)),
                    'count': int(fetch.get('count', 0))}
        return ledger

    def save(self, path: str) -> str:
        """Atomically persist the ledger: write ``<path>.tmp.<pid>``, then
        ``os.replace`` — a reader or a crashed writer can never observe a
        half-written sidecar. Returns ``path``."""
        tmp = '{}.tmp.{}'.format(path, os.getpid())
        with open(tmp, 'w') as f:
            json.dump(self.to_dict(), f, sort_keys=True)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> 'CostLedger':
        """Read a persisted ledger (:meth:`save` format)."""
        with open(path) as f:
            return cls.from_dict(json.load(f))


def format_cost_report(ledger: CostLedger, top_n: int = 5) -> str:
    """Human-readable ledger summary: totals, top-N expensive rowgroups
    (with their dominant fields), and the what-if rows."""
    lines = ['per-rowgroup cost ledger (dataset token {}, {} rowgroup(s), '
             '{:.3f}s recorded)'.format(ledger.dataset_token, len(ledger),
                                        ledger.total_seconds())]
    for row in ledger.ranking(top_n):
        fields = ', '.join('{} {:.3f}s'.format(f['field'], f['seconds'])
                           for f in row['top_fields'])
        lines.append('  {:>6.1%}  {:>9.3f}s  {}{}'.format(
            row['share'], row['seconds'], row['rowgroup'],
            '  [{}]'.format(fields) if fields else ''))
    for row in ledger.what_if():
        lines.append('  [what-if] {}'.format(row['detail']))
    if len(ledger) == 0:
        lines.append('  (empty — run a trace-armed read first: '
                     'petastorm-tpu-throughput costs <dataset_url>)')
    return '\n'.join(lines)


def profile_dataset(dataset_url: str, workers: int = 2,
                    ledger_path: Optional[str] = None) -> Tuple[CostLedger,
                                                                str]:
    """One trace-armed epoch over ``dataset_url`` folded into the persisted
    ledger (created when absent): the ``costs`` CLI's engine. Returns
    ``(ledger, path)``. A user-armed flight capture
    (``PETASTORM_TPU_TRACE=1``) is left intact; otherwise the recorder is
    armed for just this read and restored after."""
    from petastorm_tpu.reader import make_reader
    from petastorm_tpu.telemetry import tracing
    was_enabled = tracing.trace_enabled()
    try:
        if not was_enabled:
            tracing.reset_tracing()
            tracing.set_trace_enabled(True)
        with make_reader(dataset_url, workers_count=workers, num_epochs=1,
                         shuffle_row_groups=False) as reader:
            for _ in reader.iter_columnar():
                pass
            ledger = reader.cost_ledger()
            token = reader.dataset_token
    finally:
        tracing.set_trace_enabled(was_enabled)
        if not was_enabled:
            tracing.reset_tracing()
    path = ledger_path or default_ledger_path(dataset_url, token)
    if path is None:
        raise ValueError(
            'no default ledger location for remote store {!r} — pass '
            '--ledger <path> (or configure a local disk cache)'.format(
                dataset_url))
    if os.path.exists(path):
        try:
            previous = CostLedger.load(path)
            ledger.merge(previous)
        except ValueError as exc:
            import logging
            logging.getLogger(__name__).warning(
                'existing cost ledger at %s is incompatible (%s); '
                'starting fresh', path, exc)
    ledger.save(path)
    return ledger, path


def main(argv: Optional[List[str]] = None) -> int:
    """``petastorm-tpu-throughput costs`` entry: profile one epoch (or just
    inspect an existing ledger with ``--no-read``), persist, print."""
    import argparse
    parser = argparse.ArgumentParser(
        description='Profile per-rowgroup read+decode costs into a '
                    'persistent ledger and rank the expensive rowgroups')
    parser.add_argument('dataset_url')
    parser.add_argument('--ledger', default=None,
                        help='ledger sidecar path (default: next to a local '
                             'dataset / the disk cache)')
    parser.add_argument('--workers', type=int, default=2,
                        help='reader workers for the profiling epoch')
    parser.add_argument('--top', type=int, default=5,
                        help='expensive rowgroups to print (default 5)')
    parser.add_argument('--no-read', action='store_true',
                        help='skip the profiling read; just load and print '
                             'the existing ledger (--ledger required)')
    parser.add_argument('--json', action='store_true',
                        help='print one machine-readable JSON line instead')
    args = parser.parse_args(argv)
    if args.no_read:
        if not args.ledger:
            parser.error('--no-read requires --ledger')
        ledger = CostLedger.load(args.ledger)
        path = args.ledger
    else:
        ledger, path = profile_dataset(args.dataset_url,
                                       workers=args.workers,
                                       ledger_path=args.ledger)
    if args.json:
        # schedule_preview: what the cost-aware scheduler WOULD do with this
        # ledger (planned interleave order + split decisions) so operators
        # can inspect the plan without running an epoch
        # (docs/performance.md "Cost-aware scheduling")
        from petastorm_tpu.schedule import plan_preview
        print(json.dumps({'ledger_path': path,
                          'dataset_token': ledger.dataset_token,
                          'rowgroups': len(ledger),
                          'total_seconds': round(ledger.total_seconds(), 6),
                          'ranking': ledger.ranking(args.top),
                          'what_if': ledger.what_if(),
                          'schedule_preview': plan_preview(ledger)}))
    else:
        print(format_cost_report(ledger, top_n=args.top))
        print('ledger: {}'.format(path))
    return 0


if __name__ == '__main__':
    import sys
    sys.exit(main())
