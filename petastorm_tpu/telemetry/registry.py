"""Low-overhead metrics registry: counters, gauges, and fixed-bucket latency
histograms (docs/observability.md).

Design constraints, in order:

1. **Hot-path cost**: ``Histogram.observe`` runs once per stage per rowgroup (and
   once per batch on the loader path), potentially from several worker threads at
   once. Each thread writes to its OWN shard (a plain list of ints plus three
   scalars) — no lock, no atomic, no allocation on the hot path. The overhead
   budget is enforced by ``tests/test_telemetry.py::test_observe_overhead_budget``.
2. **Snapshot while writing**: ``snapshot()`` merges the shards without stopping
   writers. Under CPython's int-assignment atomicity the merged view is *monotone
   but may lag* concurrent writes; the one invariant callers may rely on is
   ``sum(buckets) >= count`` (observe increments the bucket before the count), so
   a snapshot never shows phantom observations.
3. **Mergeable across processes**: a snapshot is a plain JSON-safe dict, and
   ``merge_histogram_snapshot`` folds one into a live histogram — this is how
   worker-process stage times, shipped on the results-channel sidecar, land in the
   consumer-side registry (one snapshot covers all processes).

Buckets are powers of two of a configurable base ``unit`` (1 µs for latencies,
1 byte for sizes): bucket ``i`` counts observations in ``(unit*2**(i-1),
unit*2**i]`` (bucket 0 is ``[0, unit]``, the last bucket absorbs everything
larger). 32 buckets span 1 µs .. ~36 min — wide enough that no data-plane stage
ever falls off the top in practice, and narrow enough that a histogram snapshot
stays a handful of sparse entries.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional

#: default bucket count: pow-2 buckets 0..31 over the base unit
DEFAULT_NUM_BUCKETS = 32
#: base unit for latency histograms: one microsecond
SECONDS_UNIT = 1e-6
#: base unit for size histograms: one byte
BYTES_UNIT = 1.0

_ENV_SWITCH = 'PETASTORM_TPU_TELEMETRY'

_enabled = os.environ.get(_ENV_SWITCH, '1') not in ('0', 'false', 'off')


def telemetry_enabled() -> bool:
    """True unless telemetry is globally disabled (``PETASTORM_TPU_TELEMETRY=0``
    or :func:`set_telemetry_enabled`). Disabled mode turns every span and observe
    into a near-no-op — the escape hatch if the measured overhead ever matters."""
    return _enabled


def set_telemetry_enabled(value: bool) -> None:
    """Override the env-derived telemetry switch (tests, embedding apps).

    Scope: this process, plus any process-pool workers spawned AFTER the call
    (the pool captures the switch into the worker environment at ``start()``).
    Workers already running keep their own setting — their sidecars are dropped
    consumer-side while the switch is off, so snapshots stay silent either way;
    set ``PETASTORM_TPU_TELEMETRY=0`` before launch to disable fleet-wide."""
    global _enabled
    _enabled = bool(value)


def bucket_index(value: float, unit: float,
                 num_buckets: int = DEFAULT_NUM_BUCKETS) -> int:
    """Power-of-two bucket for ``value``: 0 for ``value <= unit`` (including 0 and
    negatives), else ``ceil(log2(value/unit))`` clamped to ``num_buckets - 1``."""
    if value <= unit:
        return 0
    # ceil(log2(n)) for integer n >= 2 is (n-1).bit_length(); -(-a // b) is
    # integer ceil-divide, exact where float log2 would wobble at boundaries.
    n = -int(-value // unit)
    return min(num_buckets - 1, (n - 1).bit_length())


def bucket_upper_bound(index: int, unit: float,
                       num_buckets: int = DEFAULT_NUM_BUCKETS) -> float:
    """Inclusive upper bound of bucket ``index`` (``inf`` for the last bucket)."""
    if index >= num_buckets - 1:
        return float('inf')
    return unit * (1 << index)


class _Shard(object):
    """One thread's private histogram storage (no locks on the write path)."""

    __slots__ = ('buckets', 'count', 'total', 'max')

    def __init__(self, num_buckets: int) -> None:
        self.buckets: List[int] = [0] * num_buckets
        self.count = 0
        self.total = 0.0
        self.max = 0.0


class Histogram(object):
    """Fixed-bucket power-of-two histogram with lock-free per-thread write shards.

    ``observe`` touches only the calling thread's shard; ``snapshot`` merges every
    shard plus any cross-process snapshots folded in via ``merge_snapshot``. The
    only lock guards shard REGISTRATION (once per writing thread) and the merged
    cross-process accumulator — never the observe path."""

    __slots__ = ('name', 'unit', 'num_buckets', '_local', '_shards',
                 '_shards_lock', '_merged')

    def __init__(self, name: str, unit: float = SECONDS_UNIT,
                 num_buckets: int = DEFAULT_NUM_BUCKETS) -> None:
        self.name = name
        self.unit = unit
        self.num_buckets = num_buckets
        self._local = threading.local()
        self._shards: List[_Shard] = []
        self._shards_lock = threading.Lock()
        self._merged: Optional[_Shard] = None

    def _shard(self) -> _Shard:
        shard = getattr(self._local, 'shard', None)
        if shard is None:
            shard = _Shard(self.num_buckets)
            with self._shards_lock:
                self._shards.append(shard)
            self._local.shard = shard
        return shard

    def observe(self, value: float) -> None:
        """Record one observation (hot path — see module docstring ordering:
        bucket before count keeps snapshots free of phantom observations)."""
        shard = self._shard()
        shard.buckets[bucket_index(value, self.unit, self.num_buckets)] += 1
        shard.count += 1
        shard.total += value
        if value > shard.max:
            shard.max = value

    def merge_snapshot(self, snap: Dict[str, Any]) -> None:
        """Fold a histogram snapshot (same unit/bucketing — e.g. one produced in a
        worker process) into this histogram's cross-process accumulator."""
        with self._shards_lock:
            if self._merged is None:
                self._merged = _Shard(self.num_buckets)
            merged = self._merged
            for key, n in (snap.get('buckets') or {}).items():
                idx = min(int(key), self.num_buckets - 1)
                merged.buckets[idx] += int(n)
            merged.count += int(snap.get('count', 0))
            merged.total += float(snap.get('sum', 0.0))
            merged.max = max(merged.max, float(snap.get('max', 0.0)))

    def snapshot(self) -> Dict[str, Any]:
        """JSON-safe merged view: ``{'unit', 'count', 'sum', 'max', 'mean',
        'buckets': {str(index): n}}`` with only non-empty buckets listed."""
        buckets = [0] * self.num_buckets
        count = 0
        total = 0.0
        maximum = 0.0
        with self._shards_lock:
            shards = list(self._shards)
            if self._merged is not None:
                shards.append(self._merged)
        for shard in shards:
            # count first, buckets after: a concurrent observe between the two
            # reads can only make sum(buckets) exceed count, never undershoot
            count += shard.count
            total += shard.total
            maximum = max(maximum, shard.max)
            for i, n in enumerate(shard.buckets):
                buckets[i] += n
        return {
            'unit': self.unit,
            'count': count,
            'sum': total,
            'max': maximum,
            'mean': (total / count) if count else 0.0,
            'buckets': {str(i): n for i, n in enumerate(buckets) if n},
        }


class Counter(object):
    """Monotone counter with the same per-thread-shard discipline as
    :class:`Histogram` (observe-side lock freedom, merge on snapshot)."""

    __slots__ = ('name', '_local', '_cells', '_lock', '_merged')

    def __init__(self, name: str) -> None:
        self.name = name
        self._local = threading.local()
        self._cells: List[List[int]] = []
        self._lock = threading.Lock()
        self._merged = 0

    def inc(self, n: int = 1) -> None:
        """Add ``n`` to the calling thread's cell (no lock)."""
        cell = getattr(self._local, 'cell', None)
        if cell is None:
            cell = [0]
            with self._lock:
                self._cells.append(cell)
            self._local.cell = cell
        cell[0] += n

    def merge_value(self, n: int) -> None:
        """Fold a cross-process counter value into this counter."""
        with self._lock:
            self._merged += int(n)

    def value(self) -> int:
        """Merged total across every thread cell and cross-process merges."""
        with self._lock:
            cells = list(self._cells)
            merged = self._merged
        return merged + sum(cell[0] for cell in cells)


class Gauge(object):
    """Last-set value (non-monotone): queue depths, configured sizes."""

    __slots__ = ('name', '_value', '_lock')

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        """Replace the gauge's value."""
        with self._lock:
            self._value = float(value)

    def value(self) -> float:
        """Current value."""
        with self._lock:
            return self._value


class MetricsRegistry(object):
    """Named metrics with on-demand creation and one JSON-safe ``snapshot()``.

    Histogram names double as stage names across the data plane
    (docs/observability.md lists the catalog). ``merge_snapshot`` folds another
    registry's snapshot in — the cross-process merge primitive used for
    worker-sidecar stage times and for pool-level registries."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._histograms: Dict[str, Histogram] = {}
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}

    def histogram(self, name: str, unit: float = SECONDS_UNIT) -> Histogram:
        """Get or create the histogram ``name`` (``unit`` applies on creation)."""
        hist = self._histograms.get(name)
        if hist is None:
            with self._lock:
                hist = self._histograms.setdefault(name, Histogram(name, unit))
        return hist

    def counter(self, name: str) -> Counter:
        """Get or create the counter ``name``."""
        counter = self._counters.get(name)
        if counter is None:
            with self._lock:
                counter = self._counters.setdefault(name, Counter(name))
        return counter

    def gauge(self, name: str) -> Gauge:
        """Get or create the gauge ``name``."""
        gauge = self._gauges.get(name)
        if gauge is None:
            with self._lock:
                gauge = self._gauges.setdefault(name, Gauge(name))
        return gauge

    def observe(self, name: str, value: float,
                unit: float = SECONDS_UNIT) -> None:
        """``histogram(name, unit).observe(value)`` unless telemetry is disabled."""
        if _enabled:
            self.histogram(name, unit).observe(value)

    def inc(self, name: str, n: int = 1) -> None:
        """``counter(name).inc(n)`` unless telemetry is disabled."""
        if _enabled:
            self.counter(name).inc(n)

    def merge_stage_times(self, stage_times: Dict[str, Dict[str, Any]]) -> None:
        """Merge a worker-sidecar ``{stage: histogram_snapshot}`` dict (what
        :func:`petastorm_tpu.telemetry.spans.drain_stage_times` produced in the
        worker process) into this registry's latency histograms. No-op while
        telemetry is disabled, so sidecars from workers that predate a
        ``set_telemetry_enabled(False)`` are dropped rather than merged."""
        if not _enabled:
            return
        for stage, snap in (stage_times or {}).items():
            unit = float(snap.get('unit', SECONDS_UNIT))
            self.histogram(stage, unit).merge_snapshot(snap)

    def merge_snapshot(self, snapshot: Dict[str, Any]) -> None:
        """Merge a full registry snapshot (histograms + counters; gauges are
        last-writer-wins) — e.g. a pool-level registry into a reader's."""
        for name, snap in (snapshot.get('histograms') or {}).items():
            unit = float(snap.get('unit', SECONDS_UNIT))
            self.histogram(name, unit).merge_snapshot(snap)
        for name, value in (snapshot.get('counters') or {}).items():
            self.counter(name).merge_value(int(value))
        for name, value in (snapshot.get('gauges') or {}).items():
            self.gauge(name).set(float(value))

    def snapshot(self) -> Dict[str, Any]:
        """JSON-safe view of every metric: ``{'histograms': {name: hist_snap},
        'counters': {name: int}, 'gauges': {name: float}}``."""
        with self._lock:
            histograms = dict(self._histograms)
            counters = dict(self._counters)
            gauges = dict(self._gauges)
        return {
            'histograms': {name: h.snapshot() for name, h in histograms.items()},
            'counters': {name: c.value() for name, c in counters.items()},
            'gauges': {name: g.value() for name, g in gauges.items()},
        }


def merge_snapshots(*snapshots: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Combine registry snapshots (None entries skipped) into one snapshot dict —
    additive for histograms/counters, last-writer-wins for gauges."""
    merged = MetricsRegistry()
    for snap in snapshots:
        if snap:
            merged.merge_snapshot(snap)
    return merged.snapshot()
