"""Export surfaces for the flight recorder: Chrome-trace/Perfetto JSON and the
anomaly/trace summary (docs/observability.md "Flight recorder").

:func:`to_chrome_trace` renders a :func:`~petastorm_tpu.telemetry.tracing.
trace_snapshot` in the Chrome Trace Event format (the JSON dialect Perfetto's
https://ui.perfetto.dev loads directly): one track per process (worker
processes appear under their own pid with a ``petastorm_tpu worker`` label),
stage spans as complete ('X') slices, anomalies as instant ('i') markers, and
synthesized **flow arrows** (``s``/``f`` pairs) stitching each rowgroup's last
worker-side span to its first consumer-side event — the visual proof that one
``(epoch, rowgroup)``'s life crosses the process boundary.

:func:`summarize_trace` is the non-visual view the doctor and bench embed:
event counts by name, the dropped-event count (drops are counted, never
silent), every anomaly instant, and the top-N longest rowgroup traces (first
event to last event per ``(epoch, rowgroup)`` — the "what happened to THIS
rowgroup during THAT 2-second stall" ranking).

CLI: ``petastorm-tpu-throughput trace <dataset_url> -o trace.json`` captures a
flight recording of a real read and writes the Perfetto JSON (:func:`main`).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: Chrome-trace category for pipeline stage slices / anomaly instants / flows
_CAT_STAGE = 'stage'
_CAT_ANOMALY = 'anomaly'
_CAT_LIFECYCLE = 'lifecycle'
_CAT_FLOW = 'rowgroup'

#: instant names that mark a rowgroup's normal life, not an anomaly — they
#: stay on the timeline but out of the summary's anomaly list
LIFECYCLE_INSTANTS = frozenset({'ventilate', 'rowgroup_consumed'})


def _ctx_args(record: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    args = dict(record.get('args') or {})
    ctx = record.get('ctx')
    if ctx:
        args.update({'epoch': ctx[0], 'rowgroup': ctx[1], 'attempt': ctx[2]})
    return args or None


def to_chrome_trace(snapshot: Dict[str, Any]) -> Dict[str, Any]:
    """Render a trace snapshot as a Chrome-trace JSON dict (``{'traceEvents':
    [...], ...}``) loadable by Perfetto / ``chrome://tracing``.

    Emits per-process ``process_name`` metadata (the snapshot's own pid is the
    consumer; every other pid a worker), 'X' slices for stage spans, 'i'
    instants (process scope) for anomalies, and one ``s``→``f`` flow arrow per
    ``(epoch, rowgroup)`` whose events span more than one process — anchored at
    the end of the last producer-side event and the start of the first
    consumer-side event."""
    consumer_pid = int(snapshot.get('pid', 0))
    events: List[Dict[str, Any]] = []
    pids: Dict[int, int] = {}
    for record in snapshot.get('events') or []:
        pid = int(record['pid'])
        pids[pid] = pids.get(pid, 0) + 1
        entry: Dict[str, Any] = {
            'name': record['name'],
            'ph': record['ph'],
            'cat': (_CAT_STAGE if record['ph'] != 'i'
                    else _CAT_LIFECYCLE if record['name'] in LIFECYCLE_INSTANTS
                    else _CAT_ANOMALY),
            'pid': pid,
            'tid': int(record['tid']),
            'ts': round(float(record['ts_us']), 3),
        }
        if record['ph'] == 'X':
            entry['dur'] = round(float(record['dur_us']), 3)
        else:
            entry['s'] = 'p'  # instant scope: whole process track
        args = _ctx_args(record)
        if args:
            entry['args'] = args
        events.append(entry)
    events.extend(_flow_events(snapshot, consumer_pid))
    meta = [{'name': 'process_name', 'ph': 'M', 'pid': pid,
             'args': {'name': ('petastorm_tpu consumer (pid {})'.format(pid)
                               if pid == consumer_pid else
                               'petastorm_tpu worker (pid {})'.format(pid))}}
            for pid in sorted(pids)]
    return {'traceEvents': meta + sorted(events, key=lambda e: e.get('ts', 0)),
            'displayTimeUnit': 'ms',
            'otherData': {
                'producer': 'petastorm_tpu flight recorder',
                'dropped_events': int(snapshot.get('dropped_events', 0)),
            }}


def _flow_events(snapshot: Dict[str, Any],
                 consumer_pid: int) -> List[Dict[str, Any]]:
    """Synthesize one worker→consumer flow arrow per rowgroup whose events
    span two or more processes (binding by ``(epoch, rowgroup)`` — a
    re-ventilated attempt hands its flow to whichever attempt delivered)."""
    producer_last: Dict[Tuple[int, int], Dict[str, Any]] = {}
    consumer_events: Dict[Tuple[int, int], List[Dict[str, Any]]] = {}
    for record in snapshot.get('events') or []:
        ctx = record.get('ctx')
        if not ctx:
            continue
        key = (int(ctx[0]), int(ctx[1]))
        end_us = float(record['ts_us']) + float(record['dur_us'])
        if int(record['pid']) != consumer_pid:
            best = producer_last.get(key)
            if best is None or end_us > float(best['ts_us']) + float(best['dur_us']):
                producer_last[key] = record
        else:
            consumer_events.setdefault(key, []).append(record)
    flows: List[Dict[str, Any]] = []
    for key, producer in producer_last.items():
        handoff_us = float(producer['ts_us']) + float(producer['dur_us'])
        # the arrow lands on the first consumer-side event AFTER the worker
        # handed the rowgroup off (the ventilate instant precedes the worker's
        # spans and must not catch the arrow)
        arrivals = [record for record in consumer_events.get(key, ())
                    if float(record['ts_us']) >= handoff_us]
        if not arrivals:
            continue
        consumer = min(arrivals, key=lambda record: float(record['ts_us']))
        flow_id = 'rg-{}-{}'.format(key[0], key[1])
        flows.append({'name': _CAT_FLOW, 'cat': _CAT_FLOW, 'ph': 's',
                      'id': flow_id, 'pid': int(producer['pid']),
                      'tid': int(producer['tid']),
                      'ts': round(float(producer['ts_us'])
                                  + float(producer['dur_us']), 3)})
        flows.append({'name': _CAT_FLOW, 'cat': _CAT_FLOW, 'ph': 'f',
                      'bp': 'e', 'id': flow_id, 'pid': int(consumer['pid']),
                      'tid': int(consumer['tid']),
                      'ts': round(float(consumer['ts_us']), 3)})
    return flows


def write_chrome_trace(path: str, snapshot: Dict[str, Any]) -> Dict[str, Any]:
    """Write :func:`to_chrome_trace` JSON to ``path``; returns the trace dict."""
    trace = to_chrome_trace(snapshot)
    with open(path, 'w') as f:
        json.dump(trace, f)
    return trace


def summarize_trace(snapshot: Dict[str, Any], top_n: int = 5) -> Dict[str, Any]:
    """The doctor/bench view of a trace snapshot: ``{'events',
    'dropped_events', 'processes', 'by_name', 'anomaly_instants',
    'top_rowgroup_traces'}`` — all JSON-safe, never raises on an empty
    snapshot.

    ``top_rowgroup_traces`` ranks ``(epoch, rowgroup)`` groups by wall span
    (first event start to last event end) — the per-request tail-latency view
    aggregates cannot give; each entry lists the distinct delivery attempts
    seen, so a re-ventilation shows up as ``attempts: [0, 1]``."""
    records: Sequence[Dict[str, Any]] = snapshot.get('events') or []
    by_name: Dict[str, int] = {}
    instants: List[Dict[str, Any]] = []
    groups: Dict[Tuple[int, int], Dict[str, Any]] = {}
    pids = set()
    for record in records:
        pids.add(int(record['pid']))
        by_name[record['name']] = by_name.get(record['name'], 0) + 1
        if record['ph'] == 'i' and record['name'] not in LIFECYCLE_INSTANTS:
            instants.append({'name': record['name'],
                             'ts_us': round(float(record['ts_us']), 1),
                             'pid': int(record['pid']),
                             'ctx': record.get('ctx'),
                             'args': record.get('args')})
        ctx = record.get('ctx')
        if not ctx:
            continue
        key = (int(ctx[0]), int(ctx[1]))
        end_us = float(record['ts_us']) + float(record['dur_us'])
        group = groups.get(key)
        if group is None:
            group = {'start_us': float(record['ts_us']), 'end_us': end_us,
                     'events': 0, 'attempts': set(), 'pids': set()}
            groups[key] = group
        group['start_us'] = min(group['start_us'], float(record['ts_us']))
        group['end_us'] = max(group['end_us'], end_us)
        group['events'] += 1
        group['attempts'].add(int(ctx[2]))
        group['pids'].add(int(record['pid']))
    ranked = sorted(groups.items(),
                    key=lambda item: item[1]['end_us'] - item[1]['start_us'],
                    reverse=True)
    top = [{'epoch': key[0], 'rowgroup': key[1],
            'duration_ms': round((group['end_us'] - group['start_us']) / 1e3, 3),
            'events': group['events'],
            'attempts': sorted(group['attempts']),
            'processes': len(group['pids'])}
           for key, group in ranked[:max(top_n, 1)]]
    return {'events': len(records),
            'dropped_events': int(snapshot.get('dropped_events', 0)),
            'processes': sorted(pids),
            'rowgroups_traced': len(groups),
            'by_name': dict(sorted(by_name.items())),
            'anomaly_instants': instants,
            'top_rowgroup_traces': top if groups else []}


def format_trace_summary(summary: Dict[str, Any]) -> str:
    """Human-readable rendering of a :func:`summarize_trace` report."""
    lines = ['flight recorder: {} event(s) across {} process(es), '
             '{} rowgroup trace(s), {} dropped'.format(
                 summary.get('events', 0), len(summary.get('processes', [])),
                 summary.get('rowgroups_traced', 0),
                 summary.get('dropped_events', 0))]
    for instant in summary.get('anomaly_instants', [])[:10]:
        lines.append('  anomaly: {} ctx={} {}'.format(
            instant['name'], instant.get('ctx'), instant.get('args') or ''))
    for trace in summary.get('top_rowgroup_traces', []):
        lines.append('  slowest: epoch {} rowgroup {} — {} ms over {} event(s),'
                     ' attempts {}, {} process(es)'.format(
                         trace['epoch'], trace['rowgroup'],
                         trace['duration_ms'], trace['events'],
                         trace['attempts'], trace['processes']))
    return '\n'.join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    """``trace`` CLI entry (``petastorm-tpu-throughput trace``): capture a
    flight recording of a real read and write the Perfetto-loadable JSON."""
    import argparse
    parser = argparse.ArgumentParser(
        description='Capture a petastorm_tpu flight recording: read a dataset '
                    'with tracing on and export Chrome-trace/Perfetto JSON '
                    '(load it at https://ui.perfetto.dev)')
    parser.add_argument('dataset_url')
    parser.add_argument('-o', '--output', default='petastorm_tpu_trace.json',
                        help='output trace JSON path (default %(default)s)')
    parser.add_argument('-p', '--pool-type',
                        choices=['thread', 'process', 'dummy'],
                        default='process',
                        help='reader pool (process shows cross-process tracks)')
    parser.add_argument('-w', '--workers-count', type=int, default=2)
    parser.add_argument('-n', '--num-epochs', type=int, default=1)
    parser.add_argument('--batch-reader', action='store_true',
                        help='use make_batch_reader (plain Parquet stores)')
    parser.add_argument('--json', action='store_true',
                        help='print the summary as one JSON line instead')
    args = parser.parse_args(argv)

    from petastorm_tpu.telemetry import tracing
    tracing.reset_tracing()
    tracing.set_trace_enabled(True)
    try:
        from petastorm_tpu import make_batch_reader, make_reader
        factory = make_batch_reader if args.batch_reader else make_reader
        rows = 0
        with factory(args.dataset_url, reader_pool_type=args.pool_type,
                     workers_count=args.workers_count,
                     num_epochs=args.num_epochs) as reader:
            for batch in reader.iter_columnar():
                rows += batch.num_rows
            snapshot = tracing.trace_snapshot()
            write_chrome_trace(args.output, snapshot)
    finally:
        tracing.set_trace_enabled(False)
    summary = summarize_trace(snapshot)
    summary['rows'] = rows
    summary['output'] = args.output
    if args.json:
        print(json.dumps(summary))
    else:
        print(format_trace_summary(summary))
        print('wrote {} ({} rows read) — open it at https://ui.perfetto.dev'
              .format(args.output, rows))
    return 0


if __name__ == '__main__':
    import sys
    sys.exit(main())
