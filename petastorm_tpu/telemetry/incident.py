"""Incident autopsy plane: edge-triggered black-box capture, bounded bundle
retention, and root-cause-ranked postmortems (docs/observability.md
"Incident autopsy plane").

Every anomaly surface so far — breaker transitions, watchdog reaps, shm CRC
drops, quarantines, SLO breaches, lineage divergence, service poison items —
is *pull*-shaped: if nobody was scraping ``/metrics`` or dumping the trace
ring at the moment of failure, the evidence dies with the process. This
module gives the pipeline flight-recorder semantics: the
:class:`IncidentRecorder` subscribes to those edges and, on trigger,
atomically writes a self-contained **bundle** directory under the dataset's
state home:

- ``manifest.json`` — trigger kind, mapped cause class, ``(epoch, rowgroup,
  attempt)`` context, trigger args, capture timestamps;
- ``trace.json`` — the drained flight-recorder ring as Perfetto/Chrome JSON,
  cut to the *pre-trigger context window* so the bundle shows what led up to
  the edge, not just the aftermath;
- ``environment.json`` — interpreter/platform/pid/argv plus the
  pipeline-relevant environment variables;
- one ``<source>.json`` per attached evidence source (metrics snapshot,
  breaker board, quarantine ledger, cost-ledger slice, lineage digest,
  autotune state, config provenance, service state — whatever the owner
  wired via :meth:`IncidentRecorder.add_source`).

Captures are **rate-limited** by a token bucket per trigger kind (a breaker
flapping open cannot write a thousand bundles) and **retention-bounded**
(the N+1th bundle evicts the oldest). Both counters are first-class metrics:
``incidents_captured`` / ``incidents_rate_limited``.

Fleet wiring (docs/service.md): service workers capture locally and ship a
compact :func:`bundle_reference` — inlining the bundle's files under a size
cap — to the dispatcher as a ``w_incident`` heartbeat frame; the dispatcher
:meth:`IncidentRecorder.adopt`\\ s inline bundles into its own home and
correlates same-cause references across workers into one fleet incident.

The analyzer rides the ``petastorm-tpu-throughput autopsy <bundle>`` CLI
(:func:`main`): :func:`analyze_bundle` walks the captured evidence,
correlates trigger → trace context → breaker/quarantine/cost/lineage
records, and ranks probable cause classes; the process exit code names the
top cause (``hang`` 10 / ``corruption`` 11 / ``storage-path`` 12 /
``scheduling-skew`` 13 / ``divergence`` 14), so a babysitting script can
branch on the verdict without parsing the report.

Attach points: ``make_reader(incidents=True | IncidentPolicy)``,
``JaxDataLoader(incidents=...)``, ``Dispatcher(incidents=...)`` /
``ServiceFleet(incidents=...)`` / ``petastorm-tpu-throughput serve
--incidents``; the doctor surfaces recent bundles in
``report['incidents']``.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import platform
import shutil
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from petastorm_tpu.telemetry import registry as _registry
from petastorm_tpu.telemetry import tracing as _tracing
from petastorm_tpu.telemetry.registry import MetricsRegistry
from petastorm_tpu.telemetry.trace_export import to_chrome_trace

logger = logging.getLogger(__name__)

#: the trigger kinds a recorder accepts — every edge event the pipeline
#: already emits, by the name the autopsy report uses
TRIGGER_KINDS: Tuple[str, ...] = (
    'breaker_open',        # a circuit breaker transitioned closed->open
    'watchdog_reap',       # a hung worker was reaped (pool watchdog or
                           # dispatcher staleness sweep)
    'shm_crc_drop',        # a shm frame failed CRC and was dropped unread
    'quarantine',          # a rowgroup left the stream (error path)
    'slo_breach',          # input-efficiency fell below the SLO target
    'lineage_divergence',  # a delivered item broke the lineage stream
    'service_poison_item',  # a service item exhausted its attempt budget
    'reshard',             # the service re-split undelivered work after an
                           # elastic worker join/leave (service/dispatcher.py)
    'ledger_corrupt',      # the dispatcher's durable token ledger failed CRC
                           # replay and the fleet degraded to
                           # replay-from-clients (service/ledger.py)
    'perf_regression',     # the live regression sentinel's drift test fired
                           # on a mid-run goodput collapse or wait-share
                           # growth (telemetry/sentinel.py,
                           # docs/observability.md "Longitudinal
                           # observatory")
    'host_reshard',        # a reader came up as a host-reshard survivor —
                           # undelivered rowgroups were re-dealt after a
                           # host join/leave/lease expiry
                           # (parallel/topology.py, docs/robustness.md
                           # "Elastic pod-scale sharding")
)

#: ranked-cause classes the autopsy report can name, with their CLI exit
#: codes (distinct per class so scripts can branch on the verdict)
CAUSE_CLASSES: Tuple[str, ...] = ('hang', 'corruption', 'storage-path',
                                  'scheduling-skew', 'divergence')
EXIT_CODES: Dict[str, int] = {'hang': 10, 'corruption': 11,
                              'storage-path': 12, 'scheduling-skew': 13,
                              'divergence': 14}
#: autopsy exit for a bundle that carries no rankable evidence
EXIT_UNKNOWN = 1
#: autopsy exit for a missing / unreadable bundle
EXIT_BAD_BUNDLE = 2

#: static trigger -> cause-class mapping ('quarantine' is resolved
#: dynamically from the record's reason/error_type — see _trigger_cause)
_CAUSE_FOR_TRIGGER: Dict[str, str] = {
    'breaker_open': 'storage-path',
    'watchdog_reap': 'hang',
    'shm_crc_drop': 'corruption',
    'slo_breach': 'scheduling-skew',
    'lineage_divergence': 'divergence',
    'service_poison_item': 'hang',
    'reshard': 'scheduling-skew',
    'ledger_corrupt': 'corruption',
    'perf_regression': 'scheduling-skew',
    'host_reshard': 'scheduling-skew',
}

#: bundle directory name prefix (retention and the doctor scan key off it)
BUNDLE_PREFIX = 'incident-'

#: environment variable overriding every default bundle home
INCIDENT_HOME_ENV = 'PETASTORM_TPU_INCIDENT_HOME'

#: environment keys worth preserving in a bundle (pipeline + JAX wiring)
_ENV_PREFIXES = ('PETASTORM_TPU_', 'JAX_', 'BENCH_')


@dataclass(frozen=True)
class IncidentPolicy:
    """Capture policy for one :class:`IncidentRecorder` — the
    ``incidents=`` kwarg contract of ``make_reader`` / ``JaxDataLoader`` /
    ``Dispatcher`` / ``ServiceFleet`` (``True`` means this default policy).

    ``home`` overrides the bundle directory (default: the owner's
    dataset-state home, or the shared tempdir fallback). ``max_bundles``
    bounds retention; the token bucket allows ``bucket_capacity`` captures
    per trigger kind, refilling one token every ``refill_interval_s``.
    ``pre_trigger_window_s`` cuts the trace ring to the window leading up to
    the edge; ``ship_bytes_cap`` bounds what a service worker inlines into
    its ``w_incident`` frame (larger bundles ship as references only)."""

    home: Optional[str] = None
    max_bundles: int = 8
    bucket_capacity: int = 1
    refill_interval_s: float = 60.0
    pre_trigger_window_s: float = 30.0
    ship_bytes_cap: int = 256 * 1024
    triggers: Tuple[str, ...] = field(default_factory=lambda: TRIGGER_KINDS)

    def __post_init__(self) -> None:
        """Validate bounds and trigger names at construction time."""
        if self.max_bundles < 1:
            raise ValueError('max_bundles must be >= 1, got {!r}'
                             .format(self.max_bundles))
        if self.bucket_capacity < 1:
            raise ValueError('bucket_capacity must be >= 1, got {!r}'
                             .format(self.bucket_capacity))
        if self.refill_interval_s <= 0:
            raise ValueError('refill_interval_s must be > 0, got {!r}'
                             .format(self.refill_interval_s))
        unknown = set(self.triggers) - set(TRIGGER_KINDS)
        if unknown:
            raise ValueError('unknown trigger kind(s) {}; known: {}'
                             .format(sorted(unknown), TRIGGER_KINDS))


def resolve_incident_policy(value: Any) -> Optional[IncidentPolicy]:
    """Accept ``None``/``False`` (disabled), ``True`` (default policy) or an
    :class:`IncidentPolicy` — the ``incidents=`` kwarg contract."""
    if value is None or value is False:
        return None
    if value is True:
        return IncidentPolicy()
    if isinstance(value, IncidentPolicy):
        return value
    raise ValueError('incidents must be None, a bool, or an IncidentPolicy, '
                     'got {!r}'.format(value))


def default_incident_home(state_home: Optional[str] = None) -> str:
    """The bundle directory for an owner whose dataset-state home is
    ``state_home``: ``$PETASTORM_TPU_INCIDENT_HOME`` when set, else
    ``<state_home>/incidents``, else a shared per-user tempdir fallback
    (read-only stores / service dispatchers have no dataset-state home)."""
    env = os.environ.get(INCIDENT_HOME_ENV)
    if env:
        return env
    if state_home:
        return os.path.join(state_home, 'incidents')
    return os.path.join(tempfile.gettempdir(),
                        'petastorm-tpu-incidents-{}'.format(os.getuid()
                                                            if hasattr(os, 'getuid')
                                                            else 'any'))


class _TokenBucket(object):
    """Per-trigger-kind capture budget: ``capacity`` tokens, one refilled
    every ``refill_interval_s`` on the injected clock."""

    __slots__ = ('_capacity', '_refill_interval_s', '_clock', '_tokens',
                 '_last_refill')

    def __init__(self, capacity: int, refill_interval_s: float,
                 clock: Callable[[], float]) -> None:
        self._capacity = capacity
        self._refill_interval_s = refill_interval_s
        self._clock = clock
        self._tokens = float(capacity)
        self._last_refill = clock()

    def take(self) -> bool:
        """Spend one token if available (refilling lazily first)."""
        now = self._clock()
        elapsed = max(now - self._last_refill, 0.0)
        if elapsed > 0:
            self._tokens = min(float(self._capacity),
                               self._tokens + elapsed / self._refill_interval_s)
            self._last_refill = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False


def _json_default(value: Any) -> Any:
    """Last-resort JSON encoder for evidence payloads (numpy scalars,
    tuples-in-sets, exception objects...)."""
    try:
        return value.item()  # numpy scalar
    except AttributeError:
        return repr(value)


def _write_json(path: str, payload: Any) -> None:
    """Write one evidence document (sorted keys, lenient encoding)."""
    with open(path, 'w') as f:
        json.dump(payload, f, indent=1, sort_keys=True, default=_json_default)


def _environment_doc() -> Dict[str, Any]:
    """The ``environment.json`` payload: enough to reproduce the process
    shape without leaking the whole environ."""
    env = {key: value for key, value in os.environ.items()
           if key.startswith(_ENV_PREFIXES)}
    return {'python': sys.version.split()[0],
            'platform': platform.platform(),
            'pid': os.getpid(),
            'argv': list(sys.argv),
            'cwd': os.getcwd(),
            'env': env}


def _windowed_trace_snapshot(window_s: float) -> Dict[str, Any]:
    """The live trace-ring snapshot cut to the pre-trigger context window:
    events whose timestamp falls within ``window_s`` of the newest recorded
    event (clock-independent — the ring's own timestamps decide)."""
    snapshot = _tracing.trace_snapshot()
    events = snapshot.get('events') or []
    if events and window_s > 0:
        newest = max(float(e.get('ts_us', 0.0)) + float(e.get('dur_us') or 0.0)
                     for e in events)
        floor = newest - window_s * 1e6
        events = [e for e in events if float(e.get('ts_us', 0.0)) >= floor]
    return {'pid': snapshot.get('pid'), 'events': events,
            'dropped_events': snapshot.get('dropped_events', 0),
            'capacity': snapshot.get('capacity', 0),
            'pre_trigger_window_s': window_s}


def _trigger_cause(kind: str, args: Optional[Dict[str, Any]]) -> str:
    """Map a trigger kind (plus its args) to the cause class the autopsy
    ranks first. ``quarantine`` is resolved from the record itself: a hang
    reason is a hang, a transient/IO error type is a storage-path failure,
    anything else is data corruption."""
    if kind == 'quarantine':
        args = args or {}
        if args.get('reason') == 'hang':
            return 'hang'
        error_type = str(args.get('error_type', ''))
        if any(marker in error_type for marker in
               ('Transient', 'IOError', 'OSError', 'Timeout', 'Connection')):
            return 'storage-path'
        return 'corruption'
    return _CAUSE_FOR_TRIGGER.get(kind, 'hang')


class IncidentRecorder(object):
    """Edge-triggered black-box capture into bounded bundle retention
    (module docstring). Thread-safe: triggers can arrive from the consumer
    thread, a scrape thread and breaker callbacks concurrently; the clock is
    injectable so rate-limit tests never sleep."""

    def __init__(self, home: str, policy: Optional[IncidentPolicy] = None,
                 registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.policy = policy if policy is not None else IncidentPolicy()
        self.home = self.policy.home or home
        self._registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        self._sources: Dict[str, Callable[[], Any]] = {}
        self._buckets: Dict[str, _TokenBucket] = {}
        self._captured = 0
        self._rate_limited = 0
        self._bundles: List[str] = []
        self._pending_refs: List[Dict[str, Any]] = []
        self._seq = self._next_seq()
        self._closed = False

    # ------------------------------------------------------------ wiring

    def add_source(self, name: str, fn: Callable[[], Any]) -> None:
        """Attach one evidence source: ``fn()`` is evaluated at capture time
        and written as ``<name>.json`` into every bundle. A raising source
        records its error in place of the payload — evidence gathering must
        never kill a capture."""
        with self._lock:
            self._sources[str(name)] = fn

    def on_breaker_transition(self, name: str, old_state: str,
                              new_state: str) -> None:
        """A :meth:`CircuitBreaker.observe_transitions` /
        :meth:`BreakerBoard.observe_transitions` observer: captures on every
        closed→open edge (half-open→open re-trips ride the rate limiter)."""
        if new_state == 'open':
            self.trigger('breaker_open',
                         args={'breaker': name, 'from_state': old_state,
                               'to_state': new_state})

    # ------------------------------------------------------------ capture

    def trigger(self, kind: str,
                ctx: Optional[Tuple[int, int, int]] = None,
                args: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """One edge event: rate-limit, gather evidence, write the bundle
        atomically, enforce retention. Returns the bundle path, or ``None``
        when the trigger was filtered, rate-limited, or the write failed
        (captures must never take down the data plane)."""
        if self._closed or kind not in self.policy.triggers:
            return None
        with self._lock:
            bucket = self._buckets.get(kind)
            if bucket is None:
                bucket = _TokenBucket(self.policy.bucket_capacity,
                                      self.policy.refill_interval_s,
                                      self._clock)
                self._buckets[kind] = bucket
            allowed = bucket.take()
            if allowed:
                seq = self._seq
                self._seq += 1
        if not allowed:
            with self._lock:
                self._rate_limited += 1
            if self._registry is not None and _registry.telemetry_enabled():
                self._registry.inc('incidents_rate_limited')
            return None
        try:
            path = self._capture(seq, kind, ctx, args)
        except Exception:  # noqa: BLE001 - capture is best-effort by contract
            logger.exception('incident capture failed (kind=%s)', kind)
            return None
        with self._lock:
            self._captured += 1
            self._bundles.append(path)
            self._pending_refs.append(
                bundle_reference(path, ship_bytes_cap=self.policy.ship_bytes_cap))
        if self._registry is not None and _registry.telemetry_enabled():
            self._registry.inc('incidents_captured')
        _tracing.trace_instant('incident_captured', ctx=ctx,
                               args={'kind': kind,
                                     'bundle': os.path.basename(path)})
        logger.warning('incident captured (kind=%s, cause=%s): %s',
                       kind, _trigger_cause(kind, args), path)
        return path

    def _capture(self, seq: int, kind: str,
                 ctx: Optional[Tuple[int, int, int]],
                 args: Optional[Dict[str, Any]]) -> str:
        name = '{}{:05d}-{}'.format(BUNDLE_PREFIX, seq, kind)
        final = os.path.join(self.home, name)
        staging = os.path.join(self.home, '.tmp-{}'.format(name))
        if os.path.isdir(staging):
            shutil.rmtree(staging, ignore_errors=True)
        os.makedirs(staging, exist_ok=True)
        manifest = {'schema': 1, 'kind': kind,
                    'cause': _trigger_cause(kind, args),
                    'ctx': list(ctx) if ctx is not None else None,
                    'args': args or {},
                    'captured_unix_s': time.time(),
                    'captured_monotonic_s': self._clock(),
                    'pid': os.getpid()}
        _write_json(os.path.join(staging, 'manifest.json'), manifest)
        _write_json(os.path.join(staging, 'environment.json'),
                    _environment_doc())
        trace = _windowed_trace_snapshot(self.policy.pre_trigger_window_s)
        _write_json(os.path.join(staging, 'trace.json'),
                    to_chrome_trace(trace))
        with self._lock:
            sources = dict(self._sources)
        for source_name, fn in sources.items():
            try:
                payload = fn()
            except Exception as exc:  # noqa: BLE001 - evidence must not kill capture
                payload = {'error': repr(exc)}
            _write_json(os.path.join(staging,
                                     '{}.json'.format(source_name)), payload)
        os.replace(staging, final)
        self._enforce_retention()
        return final

    def _next_seq(self) -> int:
        """Resume the bundle sequence past anything already retained, so a
        restarted owner never reuses (and silently clobbers) a name."""
        try:
            os.makedirs(self.home, exist_ok=True)
            existing = [entry for entry in os.listdir(self.home)
                        if entry.startswith(BUNDLE_PREFIX)]
        except OSError:
            return 0
        top = 0
        for entry in existing:
            part = entry[len(BUNDLE_PREFIX):].split('-', 1)[0]
            try:
                top = max(top, int(part) + 1)
            except ValueError:
                continue
        return top

    def _enforce_retention(self) -> None:
        """Evict oldest bundles beyond ``max_bundles`` (name order == seq
        order — the N+1th capture deletes the oldest)."""
        try:
            bundles = sorted(entry for entry in os.listdir(self.home)
                             if entry.startswith(BUNDLE_PREFIX))
        except OSError:
            return
        for entry in bundles[:-self.policy.max_bundles]:
            shutil.rmtree(os.path.join(self.home, entry), ignore_errors=True)

    # ------------------------------------------------------------ fleet

    def drain_references(self) -> List[Dict[str, Any]]:
        """Hand off (and clear) the compact references of bundles captured
        since the last drain — the service worker's ``w_incident`` shipping
        queue (drained from the heartbeat thread)."""
        with self._lock:
            refs = self._pending_refs
            self._pending_refs = []
        return refs

    def adopt(self, reference: Dict[str, Any]) -> Optional[str]:
        """Materialize a worker-shipped reference into this recorder's home
        (dispatcher side). Inline bundles are written as first-class local
        bundles (joining retention); reference-only ships are recorded but
        leave the files on the worker. Not rate-limited — the shipping side
        already was."""
        if self._closed:
            return None
        inline = reference.get('inline')
        if not inline:
            return None
        kind = str(reference.get('kind', 'unknown'))
        with self._lock:
            seq = self._seq
            self._seq += 1
        name = '{}{:05d}-{}'.format(BUNDLE_PREFIX, seq, kind)
        final = os.path.join(self.home, name)
        staging = os.path.join(self.home, '.tmp-{}'.format(name))
        try:
            if os.path.isdir(staging):
                shutil.rmtree(staging, ignore_errors=True)
            os.makedirs(staging, exist_ok=True)
            for filename, text in inline.items():
                safe = os.path.basename(str(filename))
                with open(os.path.join(staging, safe), 'w') as f:
                    f.write(str(text))
            os.replace(staging, final)
            self._enforce_retention()
        except OSError:
            logger.exception('incident adopt failed (kind=%s)', kind)
            return None
        with self._lock:
            self._captured += 1
            self._bundles.append(final)
        if self._registry is not None and _registry.telemetry_enabled():
            self._registry.inc('incidents_captured')
        return final

    # ------------------------------------------------------------ surfaces

    @property
    def captured(self) -> int:
        """Bundles written (including adopted fleet ships)."""
        with self._lock:
            return self._captured

    @property
    def rate_limited(self) -> int:
        """Triggers dropped by the per-kind token bucket."""
        with self._lock:
            return self._rate_limited

    def report(self) -> Dict[str, Any]:
        """JSON-safe summary for diagnostics / ``state()`` surfaces:
        ``{'home', 'captured', 'rate_limited', 'retained', 'bundles'}``."""
        with self._lock:
            captured = self._captured
            rate_limited = self._rate_limited
        retained = scan_bundles(self.home)
        return {'home': self.home, 'captured': captured,
                'rate_limited': rate_limited, 'retained': len(retained),
                'bundles': [entry['bundle'] for entry in retained]}

    def close(self) -> None:
        """Stop accepting triggers (idempotent; retained bundles stay)."""
        self._closed = True


def bundle_reference(path: str, ship_bytes_cap: int = 0) -> Dict[str, Any]:
    """The compact fleet-shipping form of one bundle: manifest summary plus
    total size; when the bundle fits under ``ship_bytes_cap`` its files are
    inlined so the dispatcher can materialize a first-class copy."""
    manifest: Dict[str, Any] = {}
    try:
        with open(os.path.join(path, 'manifest.json')) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        pass
    files: Dict[str, str] = {}
    total = 0
    try:
        for entry in sorted(os.listdir(path)):
            full = os.path.join(path, entry)
            if os.path.isfile(full):
                total += os.path.getsize(full)
    except OSError:
        pass
    reference: Dict[str, Any] = {
        'bundle': path, 'kind': manifest.get('kind', 'unknown'),
        'cause': manifest.get('cause'), 'ctx': manifest.get('ctx'),
        'captured_unix_s': manifest.get('captured_unix_s'),
        'size_bytes': total}
    if 0 < total <= ship_bytes_cap:
        try:
            for entry in sorted(os.listdir(path)):
                full = os.path.join(path, entry)
                if os.path.isfile(full):
                    with open(full) as f:
                        files[entry] = f.read()
            reference['inline'] = files
        except OSError:
            reference.pop('inline', None)
    return reference


def scan_bundles(home: Optional[str],
                 limit: int = 0) -> List[Dict[str, Any]]:
    """Manifest summaries of the bundles retained under ``home``, newest
    first (``limit`` > 0 truncates) — the doctor's and ``report()``'s shared
    scan."""
    if not home or not os.path.isdir(home):
        return []
    try:
        names = sorted((entry for entry in os.listdir(home)
                        if entry.startswith(BUNDLE_PREFIX)), reverse=True)
    except OSError:
        return []
    out: List[Dict[str, Any]] = []
    for name in names:
        if limit and len(out) >= limit:
            break
        path = os.path.join(home, name)
        manifest: Dict[str, Any] = {}
        try:
            with open(os.path.join(path, 'manifest.json')) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            pass
        out.append({'bundle': name, 'path': path,
                    'kind': manifest.get('kind', 'unknown'),
                    'cause': manifest.get('cause'),
                    'ctx': manifest.get('ctx'),
                    'captured_unix_s': manifest.get('captured_unix_s')})
    return out


# ---------------------------------------------------------------- autopsy


def _load_evidence(bundle: str) -> Dict[str, Any]:
    """Every ``*.json`` document in the bundle, keyed by stem. Raises
    ``OSError``/``ValueError`` only for a missing/corrupt manifest — other
    evidence files degrade to an ``{'error': ...}`` placeholder."""
    with open(os.path.join(bundle, 'manifest.json')) as f:
        manifest = json.load(f)
    evidence: Dict[str, Any] = {'manifest': manifest}
    for entry in sorted(os.listdir(bundle)):
        if not entry.endswith('.json') or entry == 'manifest.json':
            continue
        stem = entry[:-len('.json')]
        try:
            with open(os.path.join(bundle, entry)) as f:
                evidence[stem] = json.load(f)
        except (OSError, ValueError) as exc:
            evidence[stem] = {'error': repr(exc)}
    return evidence


def _trace_events(evidence: Dict[str, Any]) -> List[Dict[str, Any]]:
    trace = evidence.get('trace') or {}
    events = trace.get('traceEvents') if isinstance(trace, dict) else None
    return [e for e in events or [] if isinstance(e, dict)]


def _instant_count(events: List[Dict[str, Any]], name: str) -> int:
    return sum(1 for e in events
               if e.get('ph') == 'i' and e.get('name') == name)


def _counter_value(evidence: Dict[str, Any], name: str) -> int:
    metrics = evidence.get('metrics') or {}
    counters = metrics.get('counters') if isinstance(metrics, dict) else None
    try:
        return int((counters or {}).get(name, 0))
    except (TypeError, ValueError):
        return 0


def analyze_bundle(bundle: str) -> Dict[str, Any]:
    """Walk one bundle's evidence and rank probable cause classes.

    Returns ``{'bundle', 'trigger', 'cause', 'ctx', 'causes': [{'cause',
    'score', 'evidence': [...]}...], 'top_cause', 'exit_code',
    'trace_events'}`` — causes sorted by descending score, the
    trigger-mapped class seeded with a base score so corroborating evidence
    reorders but an evidence-free bundle still names its trigger."""
    evidence = _load_evidence(bundle)
    manifest = evidence['manifest']
    kind = str(manifest.get('kind', 'unknown'))
    args = manifest.get('args') or {}
    trigger_cause = str(manifest.get('cause')
                        or _trigger_cause(kind, args))
    events = _trace_events(evidence)
    scores: Dict[str, float] = {cause: 0.0 for cause in CAUSE_CLASSES}
    clues: Dict[str, List[str]] = {cause: [] for cause in CAUSE_CLASSES}

    def score(cause: str, points: float, clue: str) -> None:
        scores[cause] += points
        clues[cause].append(clue)

    if trigger_cause in scores:
        score(trigger_cause, 3.0,
              'trigger {!r} maps to this cause class'.format(kind))

    # hang: reaped workers, hang-reason quarantines, stale departures
    quarantine = evidence.get('quarantine')
    records = quarantine if isinstance(quarantine, list) else []
    hang_records = [r for r in records
                    if isinstance(r, dict) and r.get('reason') == 'hang']
    if hang_records:
        score('hang', 2.0, '{} hang-reason quarantine record(s)'
              .format(len(hang_records)))
    reaps = _counter_value(evidence, 'watchdog_reap')
    if reaps:
        score('hang', 1.0, 'watchdog_reap counter = {}'.format(reaps))
    n = _instant_count(events, 'watchdog_reap')
    if n:
        score('hang', 1.0, '{} watchdog_reap instant(s) in the pre-trigger '
                           'trace window'.format(n))
    service = evidence.get('service_state')
    if isinstance(service, dict):
        departed = int(service.get('workers_departed', 0) or 0)
        if departed:
            score('hang', 1.0, '{} service worker(s) departed'
                  .format(departed))
        failed = int(service.get('items_failed', 0) or 0)
        if failed:
            score('hang', 0.5, '{} service item(s) failed their attempt '
                               'budget'.format(failed))

    # corruption: CRC drops, corrupt cache entries, decode-error quarantines
    crc = _counter_value(evidence, 'shm_crc_fail')
    if crc:
        score('corruption', 2.0, 'shm_crc_fail counter = {}'.format(crc))
    n = _instant_count(events, 'shm_crc_drop')
    if n:
        score('corruption', 1.0, '{} shm_crc_drop instant(s) in the trace '
                                 'window'.format(n))
    corrupt_records = [
        r for r in records if isinstance(r, dict)
        and r.get('reason') == 'error'
        and not any(marker in str(r.get('error_type', ''))
                    for marker in ('Transient', 'IOError', 'OSError',
                                   'Timeout', 'Connection'))]
    if corrupt_records:
        score('corruption', 1.0, '{} non-transient error quarantine '
                                 'record(s)'.format(len(corrupt_records)))

    # storage-path: open breakers, transient-IO quarantines
    breakers = evidence.get('breakers')
    open_breakers = [name for name, state in (breakers or {}).items()
                     if isinstance(state, dict)
                     and state.get('state') == 'open'] \
        if isinstance(breakers, dict) else []
    if open_breakers:
        score('storage-path', 2.0, 'open breaker(s): {}'
              .format(', '.join(sorted(open_breakers))))
    n = _instant_count(events, 'breaker_transition')
    if n:
        score('storage-path', 0.5, '{} breaker_transition instant(s) in the '
                                   'trace window'.format(n))
    transient_records = [
        r for r in records if isinstance(r, dict)
        and r.get('reason') == 'error'
        and any(marker in str(r.get('error_type', ''))
                for marker in ('Transient', 'IOError', 'OSError', 'Timeout',
                               'Connection'))]
    if transient_records:
        score('storage-path', 1.0, '{} transient-IO quarantine record(s)'
              .format(len(transient_records)))

    # scheduling-skew: SLO breach state, cost-ledger skew
    slo = evidence.get('slo')
    if isinstance(slo, dict) and slo.get('breached'):
        score('scheduling-skew', 2.0,
              'SLO breached: efficiency {} < target {}'
              .format(slo.get('efficiency'), slo.get('target_efficiency')))
    costs = evidence.get('costs')
    if isinstance(costs, dict):
        skew = costs.get('skew_p95_over_median')
        try:
            if skew is not None and float(skew) > 2.0:
                score('scheduling-skew', 1.0,
                      'rowgroup cost skew p95/median = {:.2f}'
                      .format(float(skew)))
        except (TypeError, ValueError):
            pass
    n = _instant_count(events, 'slo_breach')
    if n:
        score('scheduling-skew', 0.5, '{} slo_breach instant(s) in the '
                                      'trace window'.format(n))
    n = _instant_count(events, 'perf_regression')
    if n:
        score('scheduling-skew', 1.0, '{} perf_regression instant(s) in the '
                                      'trace window'.format(n))
    sentinel = evidence.get('sentinel')
    if isinstance(sentinel, dict) and sentinel.get('alarms'):
        evidence_doc = sentinel.get('last_alarm') or {}
        score('scheduling-skew', 1.0,
              'regression sentinel fired {} time(s); last: {} {} -> {}'
              .format(sentinel.get('alarms'),
                      evidence_doc.get('series', 'rate'),
                      evidence_doc.get('pre_rate_rows_per_sec'),
                      evidence_doc.get('post_rate_rows_per_sec')))

    # divergence: lineage report, divergence instants
    lineage = evidence.get('lineage')
    if isinstance(lineage, dict):
        div = int(lineage.get('divergence', 0) or 0)
        if div:
            score('divergence', 2.0, 'lineage divergence count = {}'
                  .format(div))
    n = _instant_count(events, 'lineage_divergence')
    if n:
        score('divergence', 1.0, '{} lineage_divergence instant(s) in the '
                                 'trace window'.format(n))

    ranked = sorted(({'cause': cause, 'score': round(scores[cause], 2),
                      'evidence': clues[cause]}
                     for cause in CAUSE_CLASSES if scores[cause] > 0),
                    key=lambda entry: -float(entry['score']))  # type: ignore[arg-type]
    top = str(ranked[0]['cause']) if ranked else None
    return {'bundle': os.path.abspath(bundle),
            'trigger': kind,
            'cause': trigger_cause,
            'ctx': manifest.get('ctx'),
            'args': args,
            'captured_unix_s': manifest.get('captured_unix_s'),
            'causes': ranked,
            'top_cause': top,
            'exit_code': EXIT_CODES.get(top or '', EXIT_UNKNOWN),
            'trace_events': len(events)}


def format_autopsy(report: Dict[str, Any]) -> str:
    """Human rendering of one :func:`analyze_bundle` report."""
    lines = ['incident autopsy: {}'.format(report['bundle']),
             '  trigger: {} (cause class: {})'.format(report['trigger'],
                                                      report['cause'])]
    ctx = report.get('ctx')
    if ctx:
        lines.append('  context: epoch={} rowgroup={} attempt={}'
                     .format(*(list(ctx) + [0, 0, 0])[:3]))
    lines.append('  trace: {} event(s) in the pre-trigger window'
                 .format(report.get('trace_events', 0)))
    causes = report.get('causes') or []
    if not causes:
        lines.append('  no rankable evidence — bundle carries the trigger '
                     'only')
    else:
        lines.append('  probable causes (ranked):')
        for i, entry in enumerate(causes):
            lines.append('    {}. {} (score {})'.format(
                i + 1, entry['cause'], entry['score']))
            for clue in entry['evidence']:
                lines.append('       - {}'.format(clue))
    top = report.get('top_cause')
    lines.append('  verdict: {} (exit {})'.format(
        top or 'unknown', report['exit_code']))
    return '\n'.join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    """``petastorm-tpu-throughput autopsy <bundle>``: rank probable causes
    from one captured bundle; the exit code names the top cause class
    (hang 10 / corruption 11 / storage-path 12 / scheduling-skew 13 /
    divergence 14; 1 = no rankable evidence, 2 = unreadable bundle)."""
    parser = argparse.ArgumentParser(
        prog='petastorm-tpu-throughput autopsy',
        description='Root-cause-ranked postmortem over one incident bundle '
                    '(docs/observability.md "Incident autopsy plane").')
    parser.add_argument('bundle',
                        help='bundle directory (or a home directory — the '
                             'newest bundle inside is analyzed)')
    parser.add_argument('--json', action='store_true',
                        help='emit the report as JSON instead of text')
    args = parser.parse_args(argv)
    bundle = args.bundle
    if os.path.isdir(bundle) and not os.path.isfile(
            os.path.join(bundle, 'manifest.json')):
        retained = scan_bundles(bundle, limit=1)
        if retained:
            bundle = retained[0]['path']
    try:
        report = analyze_bundle(bundle)
    except (OSError, ValueError) as exc:
        print('autopsy: cannot read bundle {!r}: {}'.format(args.bundle, exc),
              file=sys.stderr)
        return EXIT_BAD_BUNDLE
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True,
                         default=_json_default))
    else:
        print(format_autopsy(report))
    return int(report['exit_code'])


if __name__ == '__main__':  # pragma: no cover
    sys.exit(main())
