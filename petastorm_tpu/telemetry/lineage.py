"""Sample-lineage audit plane: provable determinism, batch provenance, and
first-divergence diagnosis (docs/observability.md "Sample lineage &
determinism audit").

The pipeline PROMISES "same seed + any topology => same sample order"; this
module is the instrument that proves it and pinpoints where two runs diverge
("Optimizing High-Throughput Distributed Data Pipelines for Reproducible
Deep Learning at Scale", PAPERS.md). Three cooperating pieces:

- :class:`LineageRecorder` — rides every reader
  (``make_reader(lineage=...)``): a **chained order digest** (blake2b folded
  over each delivered item's ``(epoch, fragment, rowgroup, row_range,
  drop_partition, rows_delivered)`` identity, folded in VENTILATION order so
  the digest is identical on every pool/transport and invariant under worker
  respawns and redeliveries — attempts are deliberately NOT part of the
  identity); optional **sampled content fingerprints** (CRC-32 over column
  buffers, every Nth piece, off by default) catching silent data corruption
  the order digest cannot; and a bounded, rotating **batch-manifest JSONL**
  (training step -> ordered item identities + running digest) written
  through the existing :class:`~petastorm_tpu.telemetry.export.JsonlEventLogger`
  machinery. Digest state checkpoints with the reader (``state_dict``), so a
  save/resume run folds to the same digest as an uninterrupted one.

- a **dry replay verifier** — ``petastorm-tpu-throughput lineage verify`` —
  re-derives the expected item stream purely from (seed, shard config,
  schedule plan, quarantine ledger) recorded in the manifest header, without
  reading any data, and compares it against the recorded stream: the
  ventilator's seeded shuffle, the cost-aware scheduler's interleave and the
  split plan are all replayed as pure functions.

- a **differ** — ``lineage diff <a> <b>`` — pinpoints the first divergent
  step between two recorded runs and attributes it to the responsible
  subsystem (seed change, schedule-plan delta such as a cost-ledger
  reordering the interleave or a split-plan change, quarantine skip, shard
  config, or content corruption), with a distinct exit code per attribution
  so scripts can branch on the diagnosis.

Divergence observed LIVE (an item delivered that was never expected, a
duplicate delivery, a resume whose stream no longer matches its checkpoint)
increments the ``lineage_divergence`` counter, emits a matching trace
instant, and surfaces in ``Reader.diagnostics['lineage']`` / the ``/metrics``
gauges / the doctor report.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass
from typing import (Any, Callable, Deque, Dict, Iterator, List, Mapping,
                    Optional, Sequence, Tuple)

import numpy as np

from petastorm_tpu.telemetry.export import (JsonlEventLogger,
                                            env_rotation_settings)
from petastorm_tpu.telemetry.registry import MetricsRegistry
from petastorm_tpu.telemetry.tracing import trace_instant

logger = logging.getLogger(__name__)

#: manifest format version (bumped on incompatible record-schema changes)
MANIFEST_VERSION = 1

#: default manifest basename in the dataset's local state home
#: (``petastorm_tpu.dataset_state.sidecar_path``)
MANIFEST_BASENAME = '_petastorm_tpu_lineage_{token}.jsonl'

#: chained-digest width (blake2b digest_size)
DIGEST_BYTES = 16

#: manifest JSONL event names (one header per reader run, then manifest
#: records carrying the folded item stream)
HEADER_EVENT = 'lineage_header'
MANIFEST_EVENT = 'lineage_manifest'

#: CLI exit codes — distinct per diagnosis so scripts can branch on them
EXIT_OK = 0
EXIT_DIVERGED = 1
EXIT_ERROR = 2
EXIT_SEED = 3
EXIT_SHARD_CONFIG = 4
EXIT_SCHEDULE_PLAN = 5
EXIT_QUARANTINE = 6
EXIT_CONTENT = 7
EXIT_TOPOLOGY = 8

#: ``lineage diff`` attribution -> exit code (documented in docs/api.md)
ATTRIBUTION_EXIT_CODES: Dict[str, int] = {
    'identical': EXIT_OK,
    'seed': EXIT_SEED,
    'shard_config': EXIT_SHARD_CONFIG,
    'schedule_plan': EXIT_SCHEDULE_PLAN,
    'quarantine': EXIT_QUARANTINE,
    'content': EXIT_CONTENT,
    'topology': EXIT_TOPOLOGY,
    'unknown': EXIT_DIVERGED,
}


# --------------------------------------------------------------- identities

def canonical_identity(epoch: int, fragment_path: str, row_group_id: Any,
                       row_range: Optional[Sequence[int]],
                       drop: int) -> List[Any]:
    """The JSON-stable identity of one delivered work item. Deliberately
    attempt-free: a respawned worker's redelivery of the same item folds to
    the same bytes. ``row_range`` is the cost-aware scheduler's sub-range
    coordinate (None for whole-rowgroup items)."""
    if row_group_id is None:
        rowgroup: Any = None
    else:
        try:
            rowgroup = int(row_group_id)  # numpy ints are not JSON-safe
        except (TypeError, ValueError):
            rowgroup = str(row_group_id)
    return [int(epoch), str(fragment_path), rowgroup,
            [int(row_range[0]), int(row_range[1])]
            if row_range is not None else None,
            int(drop)]


def genesis_digest(dataset_token: str) -> bytes:
    """The chain's starting value: derived from the dataset token so digests
    of different (dataset, read-config) identities can never collide at
    item 0."""
    return hashlib.blake2b(dataset_token.encode('utf-8'),
                           digest_size=DIGEST_BYTES).digest()


def fold_digest(prev: bytes, identity: Sequence[Any], rows: int) -> bytes:
    """One chain step: ``H_{i+1} = blake2b(H_i || canonical_json(identity,
    rows))``. The chain value is itself the resumable digest state — a
    checkpointed reader continues folding from the saved bytes."""
    payload = json.dumps([list(identity), int(rows)], sort_keys=True,
                         separators=(',', ':')).encode('utf-8')
    return hashlib.blake2b(prev + payload,
                           digest_size=DIGEST_BYTES).digest()


def default_manifest_path(dataset_url_or_path: str, dataset_token: str,
                          cache_location: Optional[str] = None
                          ) -> Optional[str]:
    """Where the manifest sidecar lives by default: the dataset's local
    state home (shared derivation with the cost ledger —
    :func:`petastorm_tpu.dataset_state.sidecar_path`); None for remote
    stores with no cache (pass an explicit
    ``LineagePolicy(manifest_path=...)``)."""
    from petastorm_tpu.dataset_state import sidecar_path
    return sidecar_path(dataset_url_or_path,
                        MANIFEST_BASENAME.format(token=dataset_token),
                        cache_location)


# ------------------------------------------------------------- fingerprints

def _crc_cell(crc: int, value: Any) -> int:
    """Fold one decoded cell into a CRC-32: raw buffer bytes (plus dtype and
    shape) for array-likes, a stable text repr for object cells."""
    arr = np.asarray(value)
    if arr.dtype == object:
        # object cells (Decimal, str rows off the object path): the repr is
        # process-stable where the object's buffer address is not
        return zlib.crc32(repr(value).encode('utf-8', 'backslashreplace'),
                          crc)
    crc = zlib.crc32('{}|{}'.format(arr.dtype.str, arr.shape).encode(), crc)
    return zlib.crc32(np.ascontiguousarray(arr).tobytes(), crc)


def content_fingerprint(columns: Mapping[str, Any]) -> Dict[str, Any]:
    """CRC-32 content fingerprint of one delivered batch's column buffers:
    ``{'crc32': combined, 'fields': {name: crc}}``. Computed where the batch
    is PRODUCED (the worker — in-process, spawned, or service-fleet) and
    shipped on the batch's ``lineage`` sidecar, so a bit flipped anywhere
    between decode and the training loop shows up as a cross-run fingerprint
    mismatch the order digest alone cannot see. Sampled (every Nth piece,
    ``LineagePolicy.fingerprint_every``) because hashing every buffer of
    every batch is measurable work."""
    fields: Dict[str, int] = {}
    for name in sorted(columns):
        column = columns[name]
        crc = 0
        if isinstance(column, np.ndarray) and column.dtype != object:
            crc = _crc_cell(crc, column)
        else:
            for value in column:
                crc = _crc_cell(crc, value)
        fields[name] = crc & 0xFFFFFFFF
    combined = zlib.crc32(
        json.dumps(fields, sort_keys=True).encode('utf-8')) & 0xFFFFFFFF
    return {'crc32': combined, 'fields': fields}


# ------------------------------------------------------------------- policy

@dataclass(frozen=True)
class LineagePolicy:
    """Frozen lineage-audit policy (``make_reader(lineage=...)``).

    ``manifest_path`` overrides where the batch-manifest JSONL is written
    (default: the dataset's local state home); ``manifest=False`` keeps the
    in-memory digest without writing any file. ``fingerprint_every`` samples
    worker-side content CRCs every Nth piece (0 = off, the default — order
    integrity is free, content hashing is not). ``manifest_every`` batches
    folded items per manifest record. ``max_bytes`` / ``max_rotations``
    bound the manifest on disk (``max_rotations=None`` defers to
    ``PETASTORM_TPU_TELEMETRY_JSONL_ROTATIONS``, default 1)."""

    manifest_path: Optional[str] = None
    manifest: bool = True
    fingerprint_every: int = 0
    manifest_every: int = 32
    max_bytes: Optional[int] = 8 << 20
    max_rotations: Optional[int] = None

    def __post_init__(self) -> None:
        if self.fingerprint_every < 0:
            raise ValueError('fingerprint_every must be >= 0, got {!r}'
                             .format(self.fingerprint_every))
        if self.manifest_every < 1:
            raise ValueError('manifest_every must be >= 1, got {!r}'
                             .format(self.manifest_every))


def resolve_lineage_policy(value: Any) -> Optional[LineagePolicy]:
    """Normalize the ``make_reader(lineage=...)`` knob: ``None``/``False``
    -> no recorder (the byte-identical default path), ``True`` -> the
    default :class:`LineagePolicy`, a path string -> default policy writing
    its manifest there, a policy instance -> itself."""
    if value is None or value is False:
        return None
    if value is True:
        return LineagePolicy()
    if isinstance(value, LineagePolicy):
        return value
    if isinstance(value, str):
        return LineagePolicy(manifest_path=value)
    raise TypeError('lineage must be None/False, True, a manifest path, or '
                    'a LineagePolicy; got {!r}'.format(value))


def build_manifest_logger(policy: LineagePolicy, dataset_url_or_path: str,
                          dataset_token: str,
                          cache_location: Optional[str] = None
                          ) -> Tuple[Optional[JsonlEventLogger],
                                     Optional[str]]:
    """The recorder's manifest logger + resolved path for one reader:
    ``(None, None)`` when the policy disables the manifest or no local
    state home exists (the digest still runs in memory)."""
    if not policy.manifest:
        return None, None
    path = policy.manifest_path or default_manifest_path(
        dataset_url_or_path, dataset_token, cache_location)
    if path is None:
        return None, None
    env_bytes, env_rotations = env_rotation_settings()
    rotations = (policy.max_rotations if policy.max_rotations is not None
                 else env_rotations)
    max_bytes = policy.max_bytes if policy.max_bytes is not None \
        else env_bytes
    return JsonlEventLogger(path, interval_s=0.0, max_bytes=max_bytes,
                            max_rotations=rotations), path


# ----------------------------------------------------------------- recorder

class _Entry(object):
    """One expected work item: ventilation-ordered, folded once delivered."""

    __slots__ = ('key', 'identity', 'rows', 'delivered', 'fingerprint',
                 'quarantined')

    def __init__(self, key: Tuple[int, int, int], identity: List[Any],
                 rows: Optional[int] = None, delivered: bool = False,
                 fingerprint: Optional[Mapping[str, Any]] = None,
                 quarantined: bool = False) -> None:
        self.key = key
        self.identity = identity
        self.rows = rows
        self.delivered = delivered
        self.fingerprint = fingerprint
        self.quarantined = quarantined


class LineageRecorder(object):
    """One reader's lineage state (module docstring).

    Thread model: :meth:`expect` runs on the ventilator thread (strictly in
    ventilation order — that ordering IS the digest's fold order),
    :meth:`deliver` on the consuming thread(s), :meth:`report` /
    :meth:`order_digest` from anywhere; one internal lock guards the small
    mutable surface. Manifest JSONL writes happen OUTSIDE the lock (slow
    disks must not stall delivery accounting).

    Deliveries arrive in completion order — a thread pool's second worker
    can finish piece 7 before piece 3 — so delivered items wait in a reorder
    buffer and fold strictly in expected (ventilation) order; the buffer is
    bounded by the ventilator's in-flight window by construction."""

    def __init__(self, dataset_token: str, policy: LineagePolicy,
                 jsonl: Optional[JsonlEventLogger] = None,
                 manifest_path: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None,
                 clock: Optional[Callable[[], float]] = None,
                 resume_state: Optional[Mapping[str, Any]] = None) -> None:
        self.dataset_token = dataset_token
        self.policy = policy
        self.manifest_path = manifest_path
        self._jsonl = jsonl
        self._registry = registry
        self._clock: Callable[[], float] = clock or time.monotonic
        self._lock = threading.Lock()
        self._digest = genesis_digest(dataset_token)
        self._folded = 0
        self._entries: Deque[_Entry] = deque()
        self._by_key: Dict[Tuple[int, int, int], _Entry] = {}
        #: restored-but-undelivered checkpoint entries awaiting their
        #: re-ventilation (matched head-of-line in :meth:`expect`)
        self._restore_entries: List[_Entry] = []
        self._restore_cursor = 0
        self._unflushed: List[List[Any]] = []
        self._unflushed_first_seq = 0
        self._unflushed_prev_digest = self._digest
        self._step = 0
        self._rows_folded = 0
        self._divergence = 0
        self._last_divergence: Optional[Dict[str, Any]] = None
        self._closed = False
        if resume_state is not None:
            self._restore(resume_state)
            self._unflushed_first_seq = self._folded
            self._unflushed_prev_digest = self._digest

    # ------------------------------------------------------------- restore

    def _restore(self, state: Mapping[str, Any]) -> None:
        if int(state.get('version', -1)) != 1:
            raise ValueError('unrecognized lineage resume state {!r}'
                             .format(state))
        self._digest = bytes.fromhex(str(state['digest']))
        self._folded = int(state['folded'])
        self._rows_folded = int(state.get('rows_folded', 0))
        for row in state.get('pending') or []:
            key_list, identity, rows, delivered, quarantined = row
            entry = _Entry(
                (int(key_list[0]), int(key_list[1]), int(key_list[2])),
                _normalize_identity(identity),
                int(rows) if rows is not None else None,
                bool(delivered), None, bool(quarantined))
            self._entries.append(entry)
            self._by_key[entry.key] = entry
            if not entry.delivered:
                self._restore_entries.append(entry)

    # ------------------------------------------------------------ pipeline

    def expect(self, epoch: int, piece: int, drop: int, fragment_path: str,
               row_group_id: Any,
               row_range: Optional[Sequence[int]] = None) -> None:
        """Record one ventilated work item (called in ventilation order —
        the fold order of the chain)."""
        key = (int(epoch), int(piece), int(drop))
        identity = canonical_identity(epoch, fragment_path, row_group_id,
                                      row_range, drop)
        divergence: Optional[Tuple[str, str]] = None
        with self._lock:
            if self._restore_cursor < len(self._restore_entries):
                entry = self._restore_entries[self._restore_cursor]
                self._restore_cursor += 1
                if entry.key == key and entry.identity == identity:
                    return
                # the resumed construction no longer produces the stream the
                # checkpoint came from — flag it, then trust the live run
                divergence = ('resume_mismatch',
                              'expected {} at resume, ventilator produced {}'
                              .format(entry.identity, identity))
                del self._by_key[entry.key]
                entry.key = key
                entry.identity = identity
                self._by_key[key] = entry
            elif key in self._by_key:
                divergence = ('duplicate_expect',
                              'item {} ventilated twice'.format(key))
            else:
                entry = _Entry(key, identity)
                self._entries.append(entry)
                self._by_key[key] = entry
        if divergence is not None:
            self._note_divergence(*divergence)

    def deliver(self, item_id: Sequence[int], rows: int,
                fingerprint: Optional[Mapping[str, Any]] = None,
                quarantined: bool = False) -> None:
        """Record one delivered batch (exactly once per work item on every
        pool — duplicates and unknowns are divergence). Folds the contiguous
        delivered prefix into the chain."""
        key = (int(item_id[0]), int(item_id[1]), int(item_id[2]))
        divergence: Optional[Tuple[str, str]] = None
        flush: Optional[Dict[str, Any]] = None
        with self._lock:
            entry = self._by_key.get(key)
            if entry is None:
                divergence = ('unexpected_delivery',
                              'item {} delivered but never ventilated'
                              .format(key))
            elif entry.delivered:
                divergence = ('duplicate_delivery',
                              'item {} delivered twice'.format(key))
            else:
                entry.delivered = True
                entry.rows = int(rows)
                entry.fingerprint = dict(fingerprint) if fingerprint else None
                entry.quarantined = bool(quarantined)
                flush = self._fold_ready_locked()
        if divergence is not None:
            self._note_divergence(*divergence)
        if flush is not None:
            self._emit_manifest(flush)

    def _fold_ready_locked(self) -> Optional[Dict[str, Any]]:
        """Fold every head-of-line delivered entry; returns a manifest
        payload to emit (outside the lock) once ``manifest_every`` items
        accumulated."""
        folded_any = False
        while self._entries and self._entries[0].delivered:
            entry = self._entries.popleft()
            del self._by_key[entry.key]
            rows = int(entry.rows or 0)
            self._digest = fold_digest(self._digest, entry.identity, rows)
            self._folded += 1
            self._rows_folded += rows
            folded_any = True
            row = list(entry.identity) + [
                rows,
                int(entry.fingerprint['crc32'])
                if entry.fingerprint else None,
                1 if entry.quarantined else 0]
            self._unflushed.append(row)
        if folded_any and len(self._unflushed) >= self.policy.manifest_every:
            return self._take_manifest_locked()
        return None

    def _take_manifest_locked(self) -> Optional[Dict[str, Any]]:
        if not self._unflushed:
            return None
        payload = {'version': MANIFEST_VERSION,
                   'step': self._step,
                   'first_seq': self._unflushed_first_seq,
                   'prev_digest': self._unflushed_prev_digest.hex(),
                   'digest': self._digest.hex(),
                   'items': self._unflushed}
        self._unflushed = []
        self._unflushed_first_seq = self._folded
        self._unflushed_prev_digest = self._digest
        return payload

    def _emit_manifest(self, payload: Dict[str, Any]) -> None:
        if self._jsonl is not None:
            self._jsonl.emit({}, event=MANIFEST_EVENT, **payload)

    def _note_divergence(self, reason: str, detail: str) -> None:
        with self._lock:
            self._divergence += 1
            self._last_divergence = {'reason': reason, 'detail': detail,
                                     'at_mono': self._clock()}
        if self._registry is not None:
            self._registry.inc('lineage_divergence')
        trace_instant('lineage_divergence',
                      args={'reason': reason, 'detail': detail})
        logger.warning('lineage divergence (%s): %s', reason, detail)

    # ------------------------------------------------------------ surfaces

    def write_header(self, config: Mapping[str, Any]) -> None:
        """Emit the run's reproduction header (seed, shard config, schedule
        plan, quarantine ledger, item list) — everything ``lineage verify``
        replays the expected stream from."""
        if self._jsonl is None:
            return
        record = dict(config)
        record.setdefault('version', MANIFEST_VERSION)
        record.setdefault('dataset_token', self.dataset_token)
        record.setdefault('genesis', genesis_digest(self.dataset_token).hex())
        self._jsonl.emit({}, event=HEADER_EVENT, **record)

    def stamp_step(self, step: int) -> None:
        """Stamp the consuming loop's training-step counter
        (:class:`~petastorm_tpu.parallel.loader.JaxDataLoader` calls this
        once per yielded batch) — manifest records carry the latest stamp,
        tying item provenance to training steps."""
        with self._lock:
            self._step = int(step)

    def order_digest(self) -> str:
        """Hex digest of the chain over every folded item so far: the
        provable order identity. Two runs with the same seed, shard config
        and schedule plan fold to the same value on every pool/transport."""
        with self._lock:
            return self._digest.hex()

    @property
    def divergence_count(self) -> int:
        """Total live-divergence events observed."""
        with self._lock:
            return self._divergence

    def state_dict(self) -> Dict[str, Any]:
        """Resumable digest state: the chain value, fold count, and the
        pending (expected-but-unfolded) suffix with delivery flags — a
        resumed reader seeded with this continues folding to the exact
        digest of an uninterrupted run. Checkpoint with
        ``Reader.state_dict()`` (which embeds this under ``'lineage'``)."""
        with self._lock:
            pending = [[list(e.key), list(e.identity),
                        e.rows if e.delivered else None,
                        bool(e.delivered), bool(e.quarantined)]
                       for e in self._entries]
            return {'version': 1,
                    'digest': self._digest.hex(),
                    'folded': self._folded,
                    'rows_folded': self._rows_folded,
                    'pending': pending}

    def report(self) -> Dict[str, Any]:
        """JSON-safe lineage view for ``Reader.diagnostics['lineage']``."""
        with self._lock:
            pending = len(self._entries)
            return {'enabled': True,
                    'order_digest': self._digest.hex(),
                    'items_folded': self._folded,
                    'rows_folded': self._rows_folded,
                    'pending_items': pending,
                    'divergence': self._divergence,
                    'last_divergence': dict(self._last_divergence)
                    if self._last_divergence else None,
                    'fingerprint_every': self.policy.fingerprint_every,
                    'manifest_path': self.manifest_path,
                    'step': self._step}

    def close(self) -> None:
        """Flush the remaining folded items as a final manifest record
        (idempotent — ``Reader.stop`` may run more than once)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            payload = self._take_manifest_locked()
        if payload is not None:
            payload['final'] = True
            self._emit_manifest(payload)


def _normalize_identity(identity: Sequence[Any]) -> List[Any]:
    """JSON-roundtrip an identity so in-memory and deserialized forms
    compare equal (tuples -> lists, numpy ints -> ints)."""
    return json.loads(json.dumps(list(identity)))


# ------------------------------------------------------------ manifest I/O

def _manifest_chain(path: str) -> List[str]:
    """The manifest file chain oldest-first: ``path.N ... path.1, path``."""
    generations: List[Tuple[int, str]] = []
    directory = os.path.dirname(path) or '.'
    base = os.path.basename(path)
    try:
        names = os.listdir(directory)
    except OSError:
        names = []
    for name in names:
        if name.startswith(base + '.'):
            suffix = name[len(base) + 1:]
            if suffix.isdigit():
                generations.append((int(suffix),
                                    os.path.join(directory, name)))
    chain = [p for _n, p in sorted(generations, reverse=True)]
    if os.path.exists(path):
        chain.append(path)
    return chain


def load_manifest(path: str) -> List[Dict[str, Any]]:
    """Parse a manifest (rotated generations included, oldest first) into
    run *segments*: ``[{'header': ..., 'records': [...]}]`` — one segment
    per recorded reader run (each run writes its own header). Records keep
    their file order, which is fold order."""
    segments: List[Dict[str, Any]] = []
    for file_path in _manifest_chain(path):
        with open(file_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    record = json.loads(line)
                except ValueError:
                    continue  # torn tail line of a rotated file
                event = record.get('event')
                if event == HEADER_EVENT:
                    segments.append({'header': record, 'records': []})
                elif event == MANIFEST_EVENT:
                    if not segments:
                        # rotation dropped the header generation: keep the
                        # records under a headerless segment so chain
                        # verification still runs
                        segments.append({'header': None, 'records': []})
                    segments[-1]['records'].append(record)
    if not segments:
        raise ValueError('{} holds no lineage records'.format(path))
    return segments


def manifest_items(segment: Mapping[str, Any]) -> List[List[Any]]:
    """The segment's folded item rows, concatenated in fold order. Row
    layout: ``[epoch, fragment, rowgroup, row_range, drop, rows, crc, q]``."""
    items: List[List[Any]] = []
    for record in segment['records']:
        items.extend(record.get('items') or [])
    return items


# ------------------------------------------------------------- dry replay

def replay_expected_stream(header: Mapping[str, Any]) -> Iterator[List[Any]]:
    """Re-derive the expected item-identity stream purely from a recorded
    header — no data read, no reader built: the ventilator's seeded shuffle,
    the cost-aware interleave (replayed through the scheduler's own
    ``_interleave_order`` so the two can never drift), resume skip sets and
    epoch tagging are all modeled as pure functions. Yields canonical
    identities in fold order, indefinitely for ``num_epochs=None`` readers
    (callers zip against the recorded stream)."""
    items = [list(item) for item in header.get('items') or []]
    if not items:
        return
    seed = header.get('seed')
    shuffle = bool(header.get('shuffle_row_groups', True))
    num_epochs = header.get('num_epochs')
    pre_shuffles = int(header.get('pre_shuffles', 0))
    skip_by_iteration = {
        int(k): {(int(i[0]), int(i[1])) for i in v}
        for k, v in (header.get('skip_by_iteration') or {}).items()}
    schedule = header.get('schedule') or None
    order_fn: Optional[Callable[[List[List[Any]]], List[List[Any]]]] = None
    if schedule and not schedule.get('cold_start') \
            and schedule.get('interleave'):
        from petastorm_tpu.schedule.cost_schedule import _interleave_order
        costs = {int(k): float(v)
                 for k, v in (schedule.get('piece_costs') or {}).items()}
        heavy_skew = float(schedule['heavy_skew'])
        prestage = bool(schedule['prestage'])

        def order_fn(ordered: List[List[Any]]) -> List[List[Any]]:
            entries = [(item, costs.get(int(item[0]), 1.0))
                       for item in ordered]
            if len(entries) < 2:
                return ordered
            return _interleave_order(entries, heavy_skew, prestage)

    rng = np.random.RandomState(seed)
    current = list(items)

    def reorder() -> None:
        nonlocal current
        # same RNG consumption as ConcurrentVentilator._reorder: one
        # shuffle per reorder point, interleave applied on top
        rng.shuffle(current)
        if order_fn is not None:
            current = order_fn(current)

    if shuffle:
        for _ in range(pre_shuffles):
            reorder()
    passes = 0
    while num_epochs is None or passes < int(num_epochs) - pre_shuffles:
        if shuffle:
            reorder()
        epoch = pre_shuffles + passes
        skip = skip_by_iteration.get(passes, set())
        for item in current:
            piece, fragment, row_group, row_range, drop = item
            if (int(piece), int(drop)) in skip:
                continue
            yield canonical_identity(epoch, fragment, row_group, row_range,
                                     drop)
        passes += 1


def _shard_config(header: Mapping[str, Any]) -> Dict[str, Any]:
    return {'cur_shard': header.get('cur_shard'),
            'shard_count': header.get('shard_count'),
            'shard_seed': header.get('shard_seed'),
            'drop_partitions': header.get('drop_partitions', 1)}


def _topology_of(header: Mapping[str, Any]) -> Any:
    """JSON-normalized negotiated-topology block (process count / index /
    shard map / reshard generation — parallel/topology.py); absent for a
    static-shard recording, so static-vs-static runs never attribute here."""
    topology = header.get('topology')
    if topology is None:
        return None
    return json.loads(json.dumps(topology, sort_keys=True))


def verify_manifest(manifest_path: str,
                    dataset_url: Optional[str] = None) -> Dict[str, Any]:
    """The dry replay verifier: prove a recorded run's order digest from
    first principles, reading zero data.

    Three checks over the manifest's LAST run segment: (1) the recorded
    digest chain recomputes exactly from the recorded identities (a torn
    manifest or recorder bug cannot hide); (2) the recorded identity stream
    equals the replay of (seed, shard config, schedule plan, quarantine
    ledger) from the header; (3) when ``dataset_url`` is given, the
    header's sharded rowgroup map still matches the store's footer metadata
    (fragment paths, rowgroup ids, row counts — metadata only). Returns a
    JSON-safe result with ``exit_code`` (0 ok / 1 diverged / 2 error)."""
    segments = load_manifest(manifest_path)
    segment = segments[-1]
    header = segment['header']
    if header is None:
        return {'ok': False, 'reason': 'no_header',
                'detail': 'manifest holds records but no header (rotated '
                          'away?) — cannot replay without the run config',
                'exit_code': EXIT_ERROR}
    if header.get('resumed'):
        return {'ok': False, 'reason': 'resumed_run',
                'detail': 'this segment was recorded by a resumed reader; '
                          'replay verification needs a fresh-run manifest '
                          '(digest continuity is checkpoint-verified '
                          'instead)', 'exit_code': EXIT_ERROR}
    records = segment['records']
    items = manifest_items(segment)
    checked = 0
    # (1) chain integrity
    if records and int(records[0]['first_seq']) == 0 \
            and records[0]['prev_digest'] != header.get('genesis'):
        return {'ok': False, 'reason': 'chain_mismatch', 'divergent_step': 0,
                'detail': 'first record does not chain from the genesis '
                          'digest', 'exit_code': EXIT_DIVERGED}
    prev_hex: Optional[str] = None
    for record in records:
        digest = bytes.fromhex(str(record['prev_digest']))
        if prev_hex is not None and record['prev_digest'] != prev_hex:
            return {'ok': False, 'reason': 'chain_gap',
                    'divergent_step': int(record['first_seq']),
                    'detail': 'record at seq {} does not chain from the '
                              'previous record (rotation gap or tamper)'
                              .format(record['first_seq']),
                    'exit_code': EXIT_DIVERGED}
        for row in record.get('items') or []:
            digest = fold_digest(digest, row[:5], int(row[5]))
            checked += 1
        if digest.hex() != record['digest']:
            return {'ok': False, 'reason': 'chain_mismatch',
                    'divergent_step': int(record['first_seq']),
                    'detail': 'recomputed digest {} != recorded {} for the '
                              'record starting at seq {}'.format(
                                  digest.hex(), record['digest'],
                                  record['first_seq']),
                    'exit_code': EXIT_DIVERGED}
        prev_hex = str(record['digest'])
    # (2) replay the expected stream
    if header.get('shuffle_row_groups', True) and header.get('seed') is None:
        # RandomState(None) draws fresh OS entropy: the recorded order was
        # real but is not RE-DERIVABLE — an unverifiable recording, not a
        # divergence (record with an explicit seed to get replay coverage)
        return {'ok': False, 'reason': 'seedless_shuffle',
                'detail': 'this run shuffled rowgroups with seed=None — the '
                          'order cannot be replayed; record with an explicit '
                          'seed (the digest chain itself checked out)',
                'exit_code': EXIT_ERROR}
    first_seq = int(records[0]['first_seq']) if records else 0
    expected = replay_expected_stream(header)
    for _ in range(first_seq):  # rotation-truncated prefix: advance silently
        next(expected, None)
    for offset, row in enumerate(items):
        derived = next(expected, None)
        if derived is None:
            return {'ok': False, 'reason': 'order_divergence',
                    'divergent_step': first_seq + offset,
                    'detail': 'recorded stream is longer than the replay '
                              '(item {})'.format(row[:5]),
                    'exit_code': EXIT_DIVERGED}
        if _normalize_identity(row[:5]) != derived:
            return {'ok': False, 'reason': 'order_divergence',
                    'divergent_step': first_seq + offset,
                    'detail': 'recorded item {} but the replay derives {}'
                              .format(row[:5], derived),
                    'exit_code': EXIT_DIVERGED}
    # (3) dataset metadata cross-check (zero data read)
    if dataset_url and header.get('shard_rowgroups'):
        mismatch = _check_dataset_rowgroups(dataset_url, header)
        if mismatch is not None:
            return {'ok': False, 'reason': 'dataset_mismatch',
                    'divergent_step': None, 'detail': mismatch,
                    'exit_code': EXIT_DIVERGED}
    final = records[-1]['digest'] if records else header.get('genesis')
    return {'ok': True, 'reason': 'verified',
            'items_checked': checked, 'order_digest': final,
            'detail': 'digest chain + replayed order match over {} item(s)'
                      .format(checked),
            'exit_code': EXIT_OK}


def _check_dataset_rowgroups(dataset_url: str,
                             header: Mapping[str, Any]) -> Optional[str]:
    """Re-enumerate the store's rowgroups (footer metadata only) under the
    header's shard config and compare with the recorded map; returns a
    mismatch description or None."""
    from petastorm_tpu.etl import dataset_metadata
    from petastorm_tpu.fs_utils import normalize_dataset_url_or_urls
    from petastorm_tpu.reader import Reader
    handle = dataset_metadata.open_dataset(
        normalize_dataset_url_or_urls(dataset_url))
    row_groups = dataset_metadata.load_row_groups(handle)
    shard = _shard_config(header)
    sharded = Reader._partition_row_groups(
        row_groups, shard['cur_shard'], shard['shard_count'],
        shard['shard_seed'])
    live = [[rg.fragment_path, rg.row_group_id, rg.row_group_num_rows]
            for rg in sharded]
    recorded = [list(row) for row in header['shard_rowgroups']]
    if _normalize_identity(live) != _normalize_identity(recorded):
        return ('the store\'s sharded rowgroup enumeration no longer '
                'matches the recording ({} vs {} rowgroup(s)) — dataset '
                'contents or shard config changed'
                .format(len(live), len(recorded)))
    return None


# ------------------------------------------------------------------- differ

def _schedule_plan_of(header: Mapping[str, Any]) -> Any:
    schedule = header.get('schedule')
    if not schedule:
        return None
    return json.loads(json.dumps(schedule, sort_keys=True))


def diff_manifests(path_a: str, path_b: str) -> Dict[str, Any]:
    """First-divergence diagnosis between two recorded runs: walks both
    streams to the first step whose identity (or rows / content
    fingerprint / quarantine flag) differs and attributes the divergence to
    the responsible subsystem by comparing the run headers — ``seed``,
    ``shard_config``, ``schedule_plan`` (a cost-ledger delta reordering the
    interleave, a split-plan change), ``topology`` (a negotiated shard map
    / reshard generation changed — parallel/topology.py), ``quarantine``,
    or ``content`` (identical stream, different bytes). ``exit_code`` is
    distinct per attribution (:data:`ATTRIBUTION_EXIT_CODES`)."""
    seg_a = load_manifest(path_a)[-1]
    seg_b = load_manifest(path_b)[-1]
    header_a = seg_a['header'] or {}
    header_b = seg_b['header'] or {}
    items_a = manifest_items(seg_a)
    items_b = manifest_items(seg_b)

    causes: List[str] = []
    if header_a.get('seed') != header_b.get('seed'):
        causes.append('seed')
    if _shard_config(header_a) != _shard_config(header_b):
        causes.append('shard_config')
    if _topology_of(header_a) != _topology_of(header_b):
        causes.append('topology')
    if _schedule_plan_of(header_a) != _schedule_plan_of(header_b):
        causes.append('schedule_plan')
    if sorted(header_a.get('quarantined_fragments') or []) != \
            sorted(header_b.get('quarantined_fragments') or []):
        causes.append('quarantine')

    divergent_step: Optional[int] = None
    kind = None
    detail = ''
    for step, (row_a, row_b) in enumerate(zip(items_a, items_b)):
        if _normalize_identity(row_a[:5]) != _normalize_identity(row_b[:5]):
            divergent_step, kind = step, 'identity'
            detail = '{} vs {}'.format(row_a[:5], row_b[:5])
            break
        if int(row_a[5]) != int(row_b[5]):
            divergent_step, kind = step, 'rows'
            detail = 'item {} delivered {} vs {} rows'.format(
                row_a[:5], row_a[5], row_b[5])
            break
        if bool(row_a[7]) != bool(row_b[7]):
            divergent_step, kind = step, 'quarantine'
            detail = 'item {} quarantined in one run only'.format(row_a[:5])
            break
        if row_a[6] is not None and row_b[6] is not None \
                and int(row_a[6]) != int(row_b[6]):
            divergent_step, kind = step, 'content'
            detail = ('item {} content fingerprint {:#010x} vs {:#010x} — '
                      'same order, different bytes'.format(
                          row_a[:5], int(row_a[6]), int(row_b[6])))
            break
    if divergent_step is None and len(items_a) != len(items_b):
        divergent_step = min(len(items_a), len(items_b))
        kind = 'length'
        detail = '{} vs {} recorded item(s)'.format(len(items_a),
                                                    len(items_b))

    if divergent_step is None and not causes:
        return {'identical': True, 'attribution': 'identical',
                'first_divergent_step': None,
                'detail': 'streams identical over {} item(s)'
                          .format(len(items_a)),
                'exit_code': EXIT_OK}

    if kind == 'content':
        attribution = 'content'
    elif kind in ('quarantine', 'rows') and 'quarantine' in causes:
        attribution = 'quarantine'
    elif kind == 'quarantine':
        attribution = 'quarantine'
    elif causes:
        attribution = causes[0]
    elif kind == 'rows':
        attribution = 'content'
    else:
        attribution = 'unknown'
    return {'identical': False,
            'attribution': attribution,
            'header_deltas': causes,
            'first_divergent_step': divergent_step,
            'divergence_kind': kind,
            'detail': detail or ('headers differ ({}) but the recorded '
                                 'streams never reached the reordered '
                                 'region'.format(causes)),
            'exit_code': ATTRIBUTION_EXIT_CODES.get(attribution,
                                                    EXIT_DIVERGED)}


# ---------------------------------------------------------------------- CLI

def _record_run(dataset_url: str, manifest: Optional[str], workers: int,
                seed: Optional[int], epochs: int, fingerprint_every: int,
                cost_schedule: bool) -> Dict[str, Any]:
    """One lineage-armed epoch (the ``lineage record`` engine): returns the
    digest + manifest path."""
    from petastorm_tpu.reader import make_reader
    policy = LineagePolicy(manifest_path=manifest,
                           fingerprint_every=fingerprint_every)
    with make_reader(dataset_url, workers_count=workers, seed=seed,
                     num_epochs=epochs,
                     cost_schedule=True if cost_schedule else None,
                     lineage=policy) as reader:
        rows = 0
        for batch in reader.iter_columnar(include_empty=True):
            rows += batch.num_rows
        report = reader.diagnostics['lineage']
    return {'order_digest': report['order_digest'],
            'items': report['items_folded'], 'rows': rows,
            'divergence': report['divergence'],
            'manifest': report['manifest_path']}


def _find_default_manifest(dataset_url: str) -> Optional[str]:
    """The single lineage manifest in a local dataset's state home, or None
    when absent/ambiguous (the caller then requires ``--manifest``)."""
    from petastorm_tpu.dataset_state import local_state_home
    home = local_state_home(dataset_url)
    if home is None or not os.path.isdir(home):
        return None
    prefix, suffix = MANIFEST_BASENAME.split('{token}')
    found = [os.path.join(home, name) for name in sorted(os.listdir(home))
             if name.startswith(prefix) and name.endswith(suffix)]
    return found[0] if len(found) == 1 else None


def main(argv: Optional[List[str]] = None) -> int:
    """``petastorm-tpu-throughput lineage`` entry: ``record`` a lineage-armed
    epoch, ``verify`` a recorded manifest by dry replay, or ``diff`` two
    recorded runs to the first divergent step (module docstring; exit codes
    in :data:`ATTRIBUTION_EXIT_CODES`)."""
    import argparse
    parser = argparse.ArgumentParser(
        description='Sample-lineage audit: record, verify and diff '
                    'deterministic sample streams')
    sub = parser.add_subparsers(dest='command', required=True)
    p_record = sub.add_parser('record', help='run one lineage-armed epoch '
                                            'and write its manifest')
    p_record.add_argument('dataset_url')
    p_record.add_argument('--manifest', default=None)
    p_record.add_argument('--workers', type=int, default=2)
    p_record.add_argument('--seed', type=int, default=None)
    p_record.add_argument('--epochs', type=int, default=1)
    p_record.add_argument('--fingerprint-every', type=int, default=0)
    p_record.add_argument('--cost-schedule', action='store_true')
    p_verify = sub.add_parser('verify', help='dry-replay a recorded '
                                             'manifest — zero data read')
    p_verify.add_argument('dataset_url')
    p_verify.add_argument('--manifest', default=None)
    p_verify.add_argument('--no-dataset', action='store_true',
                          help='skip the store metadata cross-check')
    p_verify.add_argument('--json', action='store_true')
    p_diff = sub.add_parser('diff', help='first-divergence diagnosis '
                                         'between two recorded manifests')
    p_diff.add_argument('manifest_a')
    p_diff.add_argument('manifest_b')
    p_diff.add_argument('--json', action='store_true')
    args = parser.parse_args(argv)

    if args.command == 'record':
        result = _record_run(args.dataset_url, args.manifest, args.workers,
                             args.seed, args.epochs, args.fingerprint_every,
                             args.cost_schedule)
        print(json.dumps(result))
        return EXIT_OK if not result['divergence'] else EXIT_DIVERGED
    if args.command == 'verify':
        manifest = args.manifest or _find_default_manifest(args.dataset_url)
        if manifest is None:
            parser.error('no manifest found next to {} — pass --manifest'
                         .format(args.dataset_url))
        try:
            result = verify_manifest(
                manifest,
                dataset_url=None if args.no_dataset else args.dataset_url)
        except (OSError, ValueError) as exc:
            print('lineage verify: {}'.format(exc))
            return EXIT_ERROR
        if args.json:
            print(json.dumps(result))
        else:
            print('lineage verify: {} — {}'.format(
                'OK' if result['ok'] else
                'DIVERGED ({})'.format(result['reason']), result['detail']))
        return int(result['exit_code'])
    # diff
    try:
        result = diff_manifests(args.manifest_a, args.manifest_b)
    except (OSError, ValueError) as exc:
        print('lineage diff: {}'.format(exc))
        return EXIT_ERROR
    if args.json:
        print(json.dumps(result))
    elif result['identical']:
        print('lineage diff: identical — {}'.format(result['detail']))
    else:
        print('lineage diff: first divergence at step {} — attributed to '
              '{} ({})'.format(result['first_divergent_step'],
                               result['attribution'], result['detail']))
    return int(result['exit_code'])


if __name__ == '__main__':
    import sys
    sys.exit(main())
