"""Live metrics plane: a stdlib HTTP scrape endpoint over telemetry snapshots
(docs/observability.md "Live metrics plane").

Every telemetry surface so far was pull-at-end-of-run (snapshot dicts, JSONL
logs, doctor reports); this module makes the SAME snapshots scrapeable while
the pipeline runs, with zero new dependencies — ``http.server`` only:

- ``GET /metrics`` — Prometheus text exposition
  (:func:`~petastorm_tpu.telemetry.export.to_prometheus_text` over the live
  ``snapshot_fn()``), plus the optional per-label block
  (:func:`~petastorm_tpu.telemetry.export.to_prometheus_text_labeled`) and
  any extra pre-rendered exposition text (``extra_text_fn`` — the
  dispatcher's per-client/per-worker state gauges ride here);
- ``GET /healthz`` — one small JSON liveness document (``health_fn()`` merged
  over ``{"status": "ok"}``);
- ``GET /vars`` — the raw JSON snapshot (the debug view: exactly what the
  Prometheus rendering was derived from).

Attach points: ``make_reader(..., metrics_port=0)`` /
``JaxDataLoader(..., metrics_port=0)`` serve their own pipeline snapshot;
``Dispatcher(metrics_port=...)`` / ``petastorm-tpu-throughput serve
--metrics-port`` serve the FLEET-wide merge of every worker's heartbeat
metric snapshots (docs/service.md). Port 0 binds an ephemeral port —
``start()`` returns the bound one and ``url`` names the scrape target.

The server runs on one daemon thread (``ThreadingHTTPServer``, so a slow
scraper cannot wedge ``/healthz``); a ``snapshot_fn`` that raises turns into
a 500 response, never into a dead endpoint or a broken pipeline — the scrape
plane observes the data plane, it must not be able to take it down.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

from petastorm_tpu.telemetry.export import (to_prometheus_text,
                                            to_prometheus_text_labeled)

logger = logging.getLogger(__name__)

#: the content type Prometheus scrapers expect for the text exposition
PROMETHEUS_CONTENT_TYPE = 'text/plain; version=0.0.4; charset=utf-8'

SnapshotFn = Callable[[], Dict[str, Any]]
LabeledFn = Callable[[], Dict[str, Dict[str, Any]]]
TextFn = Callable[[], str]


class _MetricsRequestHandler(BaseHTTPRequestHandler):
    """Routes ``/metrics`` / ``/healthz`` / ``/vars`` against the owning
    :class:`MetricsHttpServer` (stored on the HTTP server instance)."""

    #: silence the default stderr access log — scrapes are periodic
    def log_message(self, format: str, *args: Any) -> None:
        pass

    def do_GET(self) -> None:
        """Serve one scrape; handler errors answer 500, never propagate."""
        owner: 'MetricsHttpServer' = self.server.owner  # type: ignore[attr-defined]
        path = self.path.split('?', 1)[0]
        try:
            if path == '/metrics':
                body = owner.render_metrics().encode('utf-8')
                content_type = PROMETHEUS_CONTENT_TYPE
            elif path == '/healthz':
                body = json.dumps(owner.render_health()).encode('utf-8')
                content_type = 'application/json'
            elif path == '/vars':
                body = json.dumps(owner.render_vars()).encode('utf-8')
                content_type = 'application/json'
            else:
                self.send_error(404, 'unknown path (serving /metrics, '
                                     '/healthz, /vars)')
                return
        except Exception:  # noqa: BLE001 - a broken snapshot_fn must answer 500, not kill the serving thread
            logger.exception('metrics endpoint: snapshot rendering failed')
            self.send_error(500, 'snapshot rendering failed')
            return
        self.send_response(200)
        self.send_header('Content-Type', content_type)
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class _ReusableThreadingHTTPServer(ThreadingHTTPServer):
    """The scrape listener with ``SO_REUSEADDR`` pinned on: a reader that
    restarts onto the same fixed ``metrics_port`` within the previous
    socket's TIME_WAIT must bind, not crash the new pipeline. (Ephemeral
    ``port=0`` binds never collide — ``start()`` returns the kernel's pick
    and ``url`` names it.)"""

    allow_reuse_address = True


class MetricsHttpServer(object):
    """One scrape endpoint over live telemetry callables (module docstring).

    ``snapshot_fn`` returns the registry snapshot rendered at each scrape
    (evaluated fresh per request — attach the SLO-refresh side effects
    there). ``labeled_fn`` (optional) returns ``{label_value: snapshot}``
    rendered as a per-``label`` exposition block under
    ``prefix + '_' + label`` (e.g. ``petastorm_tpu_worker_decode_*``
    series carrying ``{worker="3"}``) — aggregate and per-member series use
    DISTINCT metric namespaces so PromQL ``sum()`` over the labeled family
    never double-counts the aggregate. ``extra_text_fn`` appends
    pre-rendered exposition text (the dispatcher's state gauges);
    ``health_fn`` extends the ``/healthz`` document."""

    def __init__(self, snapshot_fn: SnapshotFn, port: int = 0,
                 host: str = '127.0.0.1',
                 prefix: str = 'petastorm_tpu',
                 labeled_fn: Optional[LabeledFn] = None,
                 label: str = 'worker',
                 extra_text_fn: Optional[TextFn] = None,
                 health_fn: Optional[SnapshotFn] = None) -> None:
        self._snapshot_fn = snapshot_fn
        self._requested_port = int(port)
        self._host = host
        self._prefix = prefix
        self._labeled_fn = labeled_fn
        self._label = label
        self._extra_text_fn = extra_text_fn
        self._health_fn = health_fn
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> int:
        """Bind and start serving on a daemon thread; returns the bound port
        (the requested one, or the ephemeral pick for port 0)."""
        if self._server is not None:
            return self.port
        server = _ReusableThreadingHTTPServer(
            (self._host, self._requested_port), _MetricsRequestHandler)
        server.daemon_threads = True
        server.owner = self  # type: ignore[attr-defined]
        self._server = server
        self._thread = threading.Thread(target=server.serve_forever,
                                        daemon=True,
                                        name='petastorm-tpu-metrics-http')
        self._thread.start()
        return self.port

    @property
    def port(self) -> int:
        """The bound port (0 until :meth:`start`)."""
        if self._server is None:
            return 0
        return int(self._server.server_address[1])

    @property
    def url(self) -> str:
        """The scrape base URL, e.g. ``http://127.0.0.1:9400``."""
        return 'http://{}:{}'.format(self._host, self.port)

    def stop(self) -> None:
        """Stop serving and release the socket (idempotent)."""
        server = self._server
        if server is None:
            return
        self._server = None
        server.shutdown()
        server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # ------------------------------------------------------------ rendering

    def render_metrics(self) -> str:
        """The full ``/metrics`` body: aggregate exposition + optional
        per-label block + optional extra pre-rendered text."""
        text = to_prometheus_text(self._snapshot_fn(), prefix=self._prefix)
        if self._labeled_fn is not None:
            labeled = self._labeled_fn()
            if labeled:
                text += to_prometheus_text_labeled(
                    labeled, self._label,
                    prefix='{}_{}'.format(self._prefix, self._label))
        if self._extra_text_fn is not None:
            text += self._extra_text_fn()
        return text

    def render_health(self) -> Dict[str, Any]:
        """The ``/healthz`` document: ``{"status": "ok"}`` merged with the
        owner's ``health_fn`` fields."""
        doc: Dict[str, Any] = {'status': 'ok'}
        if self._health_fn is not None:
            doc.update(self._health_fn())
        return doc

    def render_vars(self) -> Dict[str, Any]:
        """The ``/vars`` document: the raw aggregate snapshot plus the
        per-label snapshots when a ``labeled_fn`` is attached."""
        doc: Dict[str, Any] = {'snapshot': self._snapshot_fn()}
        if self._labeled_fn is not None:
            doc['labeled'] = {self._label: self._labeled_fn()}
        return doc

    def __enter__(self) -> 'MetricsHttpServer':
        self.start()
        return self

    def __exit__(self, exc_type: Any, exc_val: Any, exc_tb: Any) -> None:
        self.stop()


def service_state_text(state: Dict[str, Any],
                       prefix: str = 'petastorm_tpu') -> str:
    """Render a dispatcher ``state`` snapshot as per-client / per-worker
    labeled gauge series (docs/service.md): queue depth, in-flight and served
    counts per ``{client="<name>"}``, assigned items and heartbeat age per
    ``{worker="<id>"}`` — the scheduling-plane half of the fleet scrape
    surface (the decode-plane half is the workers' heartbeat metric
    snapshots)."""
    from petastorm_tpu.telemetry.export import escape_label_value
    lines = []

    def gauge(metric: str, label: str, key: str, value: float) -> None:
        name = '{}_{}'.format(prefix, metric)
        if not any(line.startswith('# TYPE {} '.format(name))
                   for line in lines):
            lines.append('# HELP {} petastorm_tpu service state gauge '
                         '(docs/service.md)'.format(name))
            lines.append('# TYPE {} gauge'.format(name))
        lines.append('{}{{{}="{}"}} {}'.format(
            name, label, escape_label_value(key),
            int(value) if float(value).is_integer() else value))

    for client in state.get('clients') or []:
        key = str(client.get('name', ''))
        gauge('service_client_queued', 'client', key,
              float(client.get('queued', 0)))
        gauge('service_client_in_flight', 'client', key,
              float(client.get('in_flight', 0)))
        gauge('service_client_served', 'client', key,
              float(client.get('served', 0)))
        gauge('service_client_window_size', 'client', key,
              float(client.get('window', 0)))
    for worker in state.get('workers') or []:
        key = str(worker.get('worker_id', ''))
        gauge('service_worker_assigned', 'worker', key,
              float(worker.get('assigned', 0)))
        gauge('service_worker_heartbeat_age_seconds', 'worker', key,
              float(worker.get('heartbeat_age_s', 0.0)))
    return '\n'.join(lines) + '\n' if lines else ''
