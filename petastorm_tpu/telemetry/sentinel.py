"""Live regression sentinel: streaming EWMA + Page–Hinkley drift detection
over the rows/s and primary-wait-share series the SLO tracker already
computes (docs/observability.md "Longitudinal observatory").

The static SLO edge (``telemetry/slo.py``) only fires when efficiency
crosses an absolute target — a run that collapses from 50k rows/s to 20k
while staying above the target is invisible to it, and a slow decay never
crosses anything sharply. The sentinel watches *this run against its own
recent past*: each observation closes a window (cumulative rows / wait
deltas over at least ``min_window_s`` of the owner's elapsed-time series —
the sentinel reads NO clock of its own, so tests drive it with synthetic
time) and feeds two one-sided Page–Hinkley drift tests:

- **rate drop** — relative deviations of the window rows/s below the running
  mean, so the test is scale-free (a 50k->35k collapse and a 500->350 one
  score the same);
- **wait-share growth** — absolute deviations of the window's
  primary-wait-share above its running mean (shares live in [0, 1]).

Each test accumulates ``m += dev - delta`` and alarms when ``m - min(m)``
exceeds its threshold: a step drop overwhelms the slack in one or two
windows, a slow drift outruns the lagging running mean and accumulates, and
zero-mean noise carries the built-in ``-delta`` down-drift so a stationary
series never rings. An alarm fully resets the detector — the new level
becomes the new baseline — which is what makes the ``perf_regression``
anomaly edge-triggered: one count per collapse, not one per window spent
collapsed.

On alarm the sentinel fires the ``perf_regression`` counter + trace instant
and triggers the incident plane (``telemetry/incident.py``), so the autopsy
bundle's manifest carries the detector's evidence: pre/post window rates,
the grown (primary-wait) stage, and the window geometry. Armed on readers,
loaders, and the dispatcher pump whenever run history is on
(``history=True`` / :class:`~petastorm_tpu.telemetry.history.HistoryPolicy`
with ``sentinel`` set).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from petastorm_tpu.telemetry import registry as _registry
from petastorm_tpu.telemetry import tracing as _tracing
from petastorm_tpu.telemetry.registry import MetricsRegistry

logger = logging.getLogger(__name__)

_EPS = 1e-9


@dataclass(frozen=True)
class SentinelPolicy:
    """Regression-sentinel tuning — the ``sentinel`` field of a
    :class:`~petastorm_tpu.telemetry.history.HistoryPolicy`.

    A window closes once ``min_window_s`` of owner-elapsed time has passed
    since the last one; the first ``warmup_windows`` windows only seed the
    running means (startup ramp must not read as drift). ``rate_delta`` /
    ``rate_threshold`` tune the scale-free rate-drop test (defaults: ignore
    sustained dips under ~5%, alarm when the accumulated excess drop reaches
    ~60% of a window); ``wait_delta`` / ``wait_threshold`` tune the absolute
    wait-share-growth test. ``ewma_alpha`` smooths the evidence/gauge series
    only — detection runs on the Page–Hinkley statistics. ``max_alarms``
    caps fires per run (a pathological series must not flood the incident
    plane past its own rate limiter)."""

    min_window_s: float = 2.0
    warmup_windows: int = 3
    ewma_alpha: float = 0.3
    rate_delta: float = 0.05
    rate_threshold: float = 0.6
    wait_delta: float = 0.03
    wait_threshold: float = 0.4
    max_alarms: int = 8

    def __post_init__(self) -> None:
        """Validate bounds at construction time."""
        if self.min_window_s <= 0:
            raise ValueError('min_window_s must be > 0, got {!r}'
                             .format(self.min_window_s))
        if self.warmup_windows < 1:
            raise ValueError('warmup_windows must be >= 1, got {!r}'
                             .format(self.warmup_windows))
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ValueError('ewma_alpha must be in (0, 1], got {!r}'
                             .format(self.ewma_alpha))
        if self.rate_threshold <= 0 or self.wait_threshold <= 0:
            raise ValueError('thresholds must be > 0')
        if self.max_alarms < 1:
            raise ValueError('max_alarms must be >= 1, got {!r}'
                             .format(self.max_alarms))


def resolve_sentinel_policy(value: Any) -> Optional[SentinelPolicy]:
    """Accept ``None``/``False`` (disarmed), ``True`` (defaults), or a
    :class:`SentinelPolicy` — the ``HistoryPolicy.sentinel`` field
    contract."""
    if value is None or value is False:
        return None
    if value is True:
        return SentinelPolicy()
    if isinstance(value, SentinelPolicy):
        return value
    raise ValueError('sentinel must be None, a bool, or a SentinelPolicy, '
                     'got {!r}'.format(value))


class DriftDetector(object):
    """One-sided Page–Hinkley drift test over a sample series.

    Deviations are measured against the running mean of all samples *before*
    the current one (so a collapsing sample is judged against the
    pre-collapse baseline), optionally normalized by that mean
    (``relative=True`` — scale-free), with ``direction`` selecting which
    side alarms ('drop': samples below the mean; 'rise': above). The test
    statistic ``m`` accumulates ``dev - delta`` and alarms when it rises
    ``threshold`` above its running minimum; an alarm fully resets the
    detector, so a level shift fires exactly once. Not thread-safe — the
    owning sentinel serializes updates."""

    def __init__(self, delta: float, threshold: float, warmup: int,
                 relative: bool = True, direction: str = 'drop') -> None:
        if direction not in ('drop', 'rise'):
            raise ValueError("direction must be 'drop' or 'rise', got {!r}"
                             .format(direction))
        self.delta = delta
        self.threshold = threshold
        self.warmup = warmup
        self.relative = relative
        self.direction = direction
        self._n = 0
        self._mean = 0.0
        self._m = 0.0
        self._m_min = 0.0

    def reset(self) -> None:
        """Forget the baseline — the next sample seeds a fresh running mean
        (called after every alarm: the post-shift level becomes normal)."""
        self._n = 0
        self._mean = 0.0
        self._m = 0.0
        self._m_min = 0.0

    @property
    def mean(self) -> float:
        """Running mean of every sample since the last reset — the alarm
        evidence's 'pre' level."""
        return self._mean

    @property
    def samples(self) -> int:
        """Samples absorbed since the last reset."""
        return self._n

    def update(self, x: float) -> bool:
        """Absorb one sample; True exactly when the drift test alarms."""
        if self._n == 0:
            self._n = 1
            self._mean = x
            return False
        dev = (self._mean - x) if self.direction == 'drop' \
            else (x - self._mean)
        if self.relative:
            dev /= max(abs(self._mean), _EPS)
        self._n += 1
        self._mean += (x - self._mean) / self._n
        if self._n <= self.warmup:
            return False
        self._m += dev - self.delta
        self._m_min = min(self._m_min, self._m)
        if self._m - self._m_min > self.threshold:
            self.reset()
            return True
        return False


class RegressionSentinel(object):
    """The armed, streaming side: windows a cumulative (elapsed, rows,
    wait) series, runs both drift tests, and fires the ``perf_regression``
    anomaly on an alarm edge.

    Clock-free by construction: every entry point takes the owner's
    ``elapsed_s`` (the SLO report already carries it), so detector tests
    drive synthetic time and an armed owner adds no clock reads of its own.
    :meth:`due` is the cheap gate — owners skip building a telemetry
    snapshot entirely until a window is ready to close. Thread-safe (a
    consumer thread and ``diagnostics`` may observe concurrently)."""

    def __init__(self, policy: Optional[SentinelPolicy] = None,
                 owner: str = 'reader',
                 registry: Optional[MetricsRegistry] = None,
                 incidents: Optional[Any] = None,
                 dataset_token: Optional[str] = None,
                 on_alarm: Optional[
                     Callable[[Dict[str, Any]], None]] = None) -> None:
        self.policy = policy if policy is not None else SentinelPolicy()
        self.owner = owner
        self.dataset_token = dataset_token
        self._registry = registry
        self._incidents = incidents
        self._on_alarm = on_alarm
        self._lock = threading.Lock()
        self._rate = DriftDetector(self.policy.rate_delta,
                                   self.policy.rate_threshold,
                                   self.policy.warmup_windows,
                                   relative=True, direction='drop')
        self._wait = DriftDetector(self.policy.wait_delta,
                                   self.policy.wait_threshold,
                                   self.policy.warmup_windows,
                                   relative=False, direction='rise')
        self._last_elapsed: Optional[float] = None
        self._last_rows = 0
        self._last_wait: Optional[float] = None
        self._windows = 0
        self._alarms = 0
        self._last_alarm: Optional[Dict[str, Any]] = None
        self._rate_ewma: Optional[float] = None
        self._wait_ewma: Optional[float] = None

    def attach_incidents(self, incidents: Optional[Any]) -> None:
        """Late-bind the incident recorder (owners build the sentinel before
        the recorder during ``__init__`` ordering)."""
        self._incidents = incidents

    def due(self, elapsed_s: float) -> bool:
        """True when enough owner time has passed to close a window — the
        pre-snapshot gate, so arming costs one float compare per item batch
        between windows."""
        with self._lock:
            if self._alarms >= self.policy.max_alarms:
                return False
            if self._last_elapsed is None:
                return True
            return (elapsed_s - self._last_elapsed
                    >= self.policy.min_window_s)

    def observe(self, report: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Feed one SLO evaluation report (``telemetry/slo.py`` — carries
        ``elapsed_s``, ``rows``, ``wait_seconds``, ``primary_wait_stage``).
        Returns the alarm evidence when this window fired, else None."""
        return self.observe_sample(
            float(report.get('elapsed_s', 0.0) or 0.0),
            int(report.get('rows', 0) or 0),
            wait_seconds=report.get('wait_seconds'),
            primary_wait_stage=report.get('primary_wait_stage'))

    def observe_sample(self, elapsed_s: float, rows: int,
                       wait_seconds: Optional[float] = None,
                       primary_wait_stage: Optional[str] = None
                       ) -> Optional[Dict[str, Any]]:
        """Feed one cumulative (elapsed, rows[, wait]) sample; closes a
        window when ``min_window_s`` has passed since the last one. The
        dispatcher pump calls this directly with items-retired as ``rows``
        and no wait series. Returns alarm evidence or None."""
        with self._lock:
            evidence = self._observe_locked(elapsed_s, rows, wait_seconds,
                                            primary_wait_stage)
        if evidence is None:
            return None
        self._fire(evidence)
        return evidence

    def _observe_locked(self, elapsed_s: float, rows: int,
                        wait_seconds: Optional[float],
                        primary_wait_stage: Optional[str]
                        ) -> Optional[Dict[str, Any]]:
        if self._alarms >= self.policy.max_alarms:
            return None
        if self._last_elapsed is None:
            # first sample anchors the series; no window to close yet
            self._last_elapsed = elapsed_s
            self._last_rows = rows
            self._last_wait = wait_seconds
            return None
        window_s = elapsed_s - self._last_elapsed
        if window_s < self.policy.min_window_s:
            return None
        rate = max(rows - self._last_rows, 0) / window_s
        wait_share: Optional[float] = None
        if wait_seconds is not None and self._last_wait is not None:
            wait_share = min(max(
                (float(wait_seconds) - float(self._last_wait)) / window_s,
                0.0), 1.0)
        self._last_elapsed = elapsed_s
        self._last_rows = rows
        self._last_wait = wait_seconds
        self._windows += 1
        alpha = self.policy.ewma_alpha
        self._rate_ewma = (rate if self._rate_ewma is None
                           else alpha * rate + (1 - alpha) * self._rate_ewma)
        if wait_share is not None:
            self._wait_ewma = (wait_share if self._wait_ewma is None
                               else alpha * wait_share
                               + (1 - alpha) * self._wait_ewma)
        pre_rate = self._rate.mean
        pre_wait = self._wait.mean
        series: Optional[str] = None
        if self._rate.update(rate):
            series = 'rate'
            self._wait.reset()  # one collapse must not double-fire via its
            # wait-side shadow in the very next window
        elif wait_share is not None and self._wait.update(wait_share):
            series = 'wait_share'
            self._rate.reset()
        if series is None:
            return None
        self._alarms += 1
        evidence: Dict[str, Any] = {
            'series': series,
            'owner': self.owner,
            'dataset_token': self.dataset_token,
            'elapsed_s': round(elapsed_s, 6),
            'window_s': round(window_s, 6),
            'windows': self._windows,
            'alarm': self._alarms,
            'pre_rate_rows_per_sec': round(pre_rate, 3),
            'post_rate_rows_per_sec': round(rate, 3),
            'pre_wait_share': round(pre_wait, 6),
            'post_wait_share': (round(wait_share, 6)
                                if wait_share is not None else None),
            'grown_stage': primary_wait_stage,
        }
        self._last_alarm = evidence
        return evidence

    def _fire(self, evidence: Dict[str, Any]) -> None:
        # outside the lock: counter + instant + incident trigger + observer
        if self._registry is not None and _registry.telemetry_enabled():
            self._registry.inc('perf_regression')
        _tracing.trace_instant('perf_regression', args=evidence)
        logger.warning(
            'perf_regression: %s %s collapsed (%s %.1f -> %.1f rows/s, '
            'grown stage %s)', self.owner, evidence['series'],
            self.dataset_token or '-', evidence['pre_rate_rows_per_sec'],
            evidence['post_rate_rows_per_sec'], evidence['grown_stage'])
        if self._incidents is not None:
            try:
                self._incidents.trigger('perf_regression', args=evidence)
            except Exception:  # noqa: BLE001 - capture must not break the run
                logger.exception('perf_regression incident capture failed')
        if self._on_alarm is not None:
            try:
                self._on_alarm(dict(evidence))
            except Exception:  # noqa: BLE001 - observer must not break the run
                logger.exception('perf_regression alarm observer failed')

    def gauges(self) -> Dict[str, float]:
        """The smoothed series for a metrics scrape (``sentinel_rate_ewma``
        / ``sentinel_wait_share_ewma``) — only keys with data so a wait-less
        owner (dispatcher) never exports a misleading 0.0 share."""
        with self._lock:
            out: Dict[str, float] = {}
            if self._rate_ewma is not None:
                out['sentinel_rate_ewma'] = round(self._rate_ewma, 3)
            if self._wait_ewma is not None:
                out['sentinel_wait_share_ewma'] = round(self._wait_ewma, 6)
            return out

    def export_gauges(self) -> None:
        """Refresh the registry gauges from :meth:`gauges` (called by owners
        next to their SLO gauge refresh)."""
        if self._registry is None or not _registry.telemetry_enabled():
            return
        for name, value in self.gauges().items():
            self._registry.gauge(name).set(value)

    @property
    def alarms(self) -> int:
        """Alarm edges fired so far this run."""
        with self._lock:
            return self._alarms

    def report(self) -> Dict[str, Any]:
        """JSON-safe sentinel state — the incident plane's ``sentinel``
        evidence source (``add_source('sentinel', sentinel.report)``) and
        the diagnostics block; ``analyze_bundle`` reads ``alarms`` and
        ``last_alarm`` from exactly this shape."""
        with self._lock:
            return {
                'armed': True,
                'owner': self.owner,
                'dataset_token': self.dataset_token,
                'windows': self._windows,
                'alarms': self._alarms,
                'last_alarm': (dict(self._last_alarm)
                               if self._last_alarm else None),
                'rate_ewma': (round(self._rate_ewma, 3)
                              if self._rate_ewma is not None else None),
                'wait_share_ewma': (round(self._wait_ewma, 6)
                                    if self._wait_ewma is not None else None),
                'policy': {
                    'min_window_s': self.policy.min_window_s,
                    'warmup_windows': self.policy.warmup_windows,
                    'rate_threshold': self.policy.rate_threshold,
                    'wait_threshold': self.policy.wait_threshold,
                },
            }
