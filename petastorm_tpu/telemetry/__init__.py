"""Pipeline telemetry: per-stage latency histograms, cross-process span merging,
exportable snapshots, and bottleneck attribution (docs/observability.md).

The subsystem has four layers:

- :mod:`~petastorm_tpu.telemetry.registry` — the metric primitives: counters,
  gauges, power-of-two-bucket histograms with lock-free per-thread write shards
  merged on ``snapshot()``, and snapshot-level merge (the cross-process
  primitive).
- :mod:`~petastorm_tpu.telemetry.spans` — stage spans over the data plane
  (``fs_open`` .. ``h2d``); worker-process spans ride each published batch's
  ``telemetry`` sidecar on the results channel (like ``cache_hit``) and merge
  into the consumer-side registry, so ONE snapshot covers every process.
- :mod:`~petastorm_tpu.telemetry.export` — Prometheus text exposition and a
  periodic JSONL event log (dual-clock ``ts_unix``/``ts_mono`` stamps).
- :mod:`~petastorm_tpu.telemetry.http_exporter` — the live metrics plane: a
  stdlib HTTP scrape endpoint (``/metrics`` Prometheus text, ``/healthz``,
  ``/vars``) attachable to readers, loaders and the service dispatcher
  (``make_reader(metrics_port=)``, ``serve --metrics-port``).
- :mod:`~petastorm_tpu.telemetry.slo` — input-efficiency SLOs: starvation
  fraction / goodput-vs-ideal from the recorded wait-stage spans, with
  edge-triggered ``slo_breach`` accounting.
- :mod:`~petastorm_tpu.telemetry.cost_model` — the persistent per-rowgroup /
  per-field cost profiler fed by the flight recorder
  (``petastorm-tpu-throughput costs``).
- :mod:`~petastorm_tpu.telemetry.tracing` /
  :mod:`~petastorm_tpu.telemetry.trace_export` — the flight recorder: a
  bounded per-process ring buffer of span/instant events tagged with the
  causal ``(epoch, rowgroup, attempt)`` context, exported as
  Chrome-trace/Perfetto JSON with worker→consumer flow arrows
  (``PETASTORM_TPU_TRACE=1`` / ``make_reader(..., trace=True)`` /
  ``Reader.dump_trace()``).
- :mod:`~petastorm_tpu.telemetry.analyze` — bottleneck attribution: rank stages
  by time share, map the top stage to the knob that moves it
  (``petastorm-tpu-throughput analyze``).

Entry points on the pipeline objects: ``Reader.telemetry_snapshot()`` /
``Reader.diagnostics['telemetry']`` and ``JaxDataLoader.telemetry_snapshot()``.
``PETASTORM_TPU_TELEMETRY=0`` disables all instrumentation;
``PETASTORM_TPU_TELEMETRY_JSONL=<path>`` streams periodic snapshots from the
device loader.
"""

from petastorm_tpu.telemetry.registry import (Counter, Gauge,  # noqa: F401
                                              Histogram, MetricsRegistry,
                                              merge_snapshots,
                                              set_telemetry_enabled,
                                              telemetry_enabled)
from petastorm_tpu.telemetry.spans import (STAGES, TRACE_INSTANTS,  # noqa: F401
                                           StageRecorder, drain_stage_times,
                                           record_stage, stage_span)
from petastorm_tpu.telemetry.tracing import (TraceRecorder,  # noqa: F401
                                             reset_tracing, set_trace_enabled,
                                             trace_complete, trace_enabled,
                                             trace_instant, trace_snapshot)
