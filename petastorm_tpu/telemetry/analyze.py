"""Bottleneck attribution: rank pipeline stages by time share and map the top
stage to the knob that moves it (docs/observability.md "Reading an analyze
report"; the analysis-tooling spirit of tf.data, arXiv 2101.12127).

The input is any telemetry snapshot (``Reader.diagnostics['telemetry']``, a
JSONL event log, a doctor ``--json`` report). Shares are computed over the LEAF
latency stages only — envelope stages like ``cache_miss`` (which wraps
``rowgroup_read`` + ``decode``) are reported but excluded from the denominator,
so the shares of independent work sum sensibly. Stage seconds are summed across
every process and thread that contributed, so a share is "fraction of all
pipeline CPU/IO time", not wall-clock — with N parallel workers a 0.9 share can
still hide behind prefetch, which is why the report pairs the ranking with the
consumer-side ``shuffle_wait``/``pool_wait`` stages: those measure time the
TRAINING side actually sat idle.

CLI: ``petastorm-tpu-throughput analyze <snapshot.json|events.jsonl>`` (also
``python -m petastorm_tpu.telemetry.analyze``).
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional

from petastorm_tpu.telemetry.registry import SECONDS_UNIT
from petastorm_tpu.telemetry.spans import ENVELOPE_STAGES

#: knob advice per dominant stage: (headline, detail) — the tuning map the
#: tentpole exists to make mechanical (docs/observability.md)
_KNOBS: Dict[str, Any] = {
    'fs_open': ('check storage connectivity / keep filesystems warm',
                'Filesystem construction dominates: remote stores with flaky '
                'connections reconnect per retry — check on_error/retry_policy '
                'counters and the storage backend before touching pool knobs.'),
    'rowgroup_read': ('raise workers_count (IO-bound read)',
                      'Parquet rowgroup IO dominates: more parallel readers '
                      'overlap more IO (workers_count), and a local-disk cache '
                      '(cache_type="local-disk", cache_format="arrow-ipc") '
                      'removes the re-read on warm epochs entirely.'),
    'decode': ('raise workers_count or cache decoded rowgroups',
               'Codec decode dominates: decode parallelizes across workers '
               '(workers_count; reader_pool_type="process" escapes the GIL for '
               'pure-python codecs), and cache_format="arrow-ipc" makes warm '
               'epochs skip decode via zero-copy mmap hits.'),
    'shuffle': ('lower shuffle cost (shuffle_rows=False or smaller rowgroups)',
                'In-rowgroup shuffling dominates — unusual; consider '
                'shuffle_rows=False plus a loader shuffling buffer.'),
    'transform': ('vectorize the TransformSpec or move it on-device',
                  'TransformSpec dominates: batched (make_batch_reader) '
                  'transforms amortize per-row Python cost; device-side ops '
                  '(petastorm_tpu.ops) remove it from the host entirely.'),
    'cache_hit': ('cache serving dominates — use cache_format="arrow-ipc"',
                  'Cache hits dominate and are slow: the pickle cache format '
                  'pays a full unpickle per hit; arrow-ipc serves zero-copy '
                  'mmap views.'),
    'cache_store': ('cache writes dominate — put cache_location on faster disk',
                    'Filling the rowgroup cache dominates: first-epoch-only '
                    'cost; if it persists, the cache disk is too slow or the '
                    'size limit is forcing eviction churn.'),
    'serialize': ('shrink the wire payload (arrow-ipc serializer, fewer fields)',
                  'Worker-side result serialization dominates: ensure the '
                  'ArrowIpcSerializer is in use (sidecar_column_names shows '
                  'columns falling off the Arrow path) and trim schema_fields.'),
    'shm_slot_wait': ('raise shm_slot_bytes / shm_slots_per_worker',
                      'Workers block waiting for free shm ring slots: the '
                      'consumer is not releasing slots fast enough for the '
                      'configured ring — more/bigger slots '
                      '(shm_slots_per_worker, shm_slot_bytes) or a faster '
                      'consumer loop.'),
    'shm_map': ('payload deserialize dominates — check sidecar columns',
                'Mapping shm results dominates consumer time: columns falling '
                'into the pickled sidecar (ragged/object dtypes) copy on every '
                'batch; keep columns numeric/uniform for zero-copy receive.'),
    'shm_release': ('slot release dominates — raise shm_slots_per_worker',
                    'Releasing shm slots dominates — ROUTER backpressure; more '
                    'slots per worker decouple the ack path.'),
    'pool_wait': ('raise workers_count (consumer starved)',
                  'The consumer sits idle in pool.get_results: the worker pool '
                  'cannot keep up — raise workers_count, or remove the '
                  'bottleneck the worker-side ranking names.'),
    'shuffle_wait': ('raise workers_count / prefetch (input-bound training)',
                     'The training loop blocks on the input pipeline: raise '
                     'workers_count and loader prefetch; if worker stages are '
                     'cheap, the host->device link is the limit (see h2d).'),
    'collate': ('batch assembly dominates — larger batches / fewer ragged pads',
                'Host batch assembly (sanitize/pad) dominates: bigger '
                'batch_size amortizes per-batch cost; pad_ragged fields copy '
                'every row — pack or pre-pad in the store.'),
    'h2d': ('coalesce uploads / raise batch size (link-bound)',
            'Host->device transfer dominates: coalesce_fields=True collapses '
            'per-field transfers to one; a larger batch_size amortizes '
            'per-transfer dispatch RTT; scan_stream uploads whole chunks.'),
    'cache_miss': ('first-epoch fills — see rowgroup_read/decode',
                   'cache_miss envelopes the fill work; the leaf ranking names '
                   'the actual cost.'),
    'device_decode': ('decode-tail host half dominates — check inflate share',
                      'The device decode tail spends host time packing or '
                      'inflating raw payloads before upload: stored-block '
                      'frames inflate on chip for free — re-encode stores at '
                      'zlib level 0, or move huffman-heavy fields back to '
                      'host decode (docs/performance.md).'),
    'd2d_wait': ('raise device_buffer_depth (decode-bound device tail)',
                 'The producer blocks on the prefetch-to-device ring: device '
                 'decode programs finish slower than batches arrive — raise '
                 'JaxDataLoader device_buffer_depth so more decode work '
                 'overlaps the train step, or shrink the augment chain.'),
    # ------------------------------------------------- input service (PR 8)
    # Service-backed readers surface their pressure as COUNTERS/GAUGES, not
    # stage histograms — these entries feed the counter advisories below
    # (docs/service.md; the service autotuner turns the same knobs live).
    'service_busy': ('raise the admission window or add decode workers',
                     'The dispatcher rejected submits with busy: the '
                     'per-client in-flight window is full. If the queue is '
                     'shallow, raise the admission window (serve CLI '
                     '--admission-window, or Dispatcher(autotune=True) to '
                     'retune it live); if deep, the fleet is saturated — add '
                     'workers (ServiceFleet.spawn_worker).'),
    'service_resubmit': ('co-located shm delivery is flaky — check /dev/shm',
                         'Items were re-requested after shm segment '
                         'attach/verify failures: false co-location or an '
                         'exhausted /dev/shm. Redeliveries are wire-pinned, '
                         'so throughput degrades to TCP — fix the segment '
                         'store or run the clients truly co-located.'),
    'service_queue_depth': ('queue depth exceeds the fleet — add workers',
                            'Accepted items sit queued behind a saturated '
                            'worker fleet: admission is not the limit, decode '
                            'capacity is — add service workers or lower '
                            'client demand.'),
}

_DEFAULT_ADVICE = ('inspect the stage histogram',
                   'No canned knob for this stage; inspect its histogram in the '
                   'snapshot and docs/observability.md.')

#: counter names that trigger a service advisory when non-zero in the
#: snapshot (the service's pressure signals have no latency histogram).
#: NOTE the semantics follow the snapshot handed in: a cumulative snapshot
#: (diagnostics dump, the analyze CLI) advises on totals since process start,
#: a window delta (the autotune controller's snapshot_delta) on fresh
#: movement only — the 'value' field says how much either way.
_ADVISORY_COUNTERS = ('service_busy', 'service_resubmit')
#: gauge names that trigger an advisory when non-zero
_ADVISORY_GAUGES = ('service_queue_depth',)


def _service_advisories(snapshot: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Counter/gauge-driven advice rows for service-backed readers: each
    non-zero advisory signal yields ``{'signal', 'value', 'recommendation',
    'detail'}`` from the ``_KNOBS`` map — the canned advice the stage ranking
    cannot provide for non-histogram pressure."""
    advisories = []
    counters = snapshot.get('counters') or {}
    gauges = snapshot.get('gauges') or {}
    for name in _ADVISORY_COUNTERS:
        value = int(counters.get(name, 0) or 0)
        if value > 0:
            headline, detail = _KNOBS[name]
            advisories.append({'signal': name, 'value': value,
                               'recommendation': headline, 'detail': detail})
    for name in _ADVISORY_GAUGES:
        value = float(gauges.get(name, 0) or 0)
        if value > 0:
            headline, detail = _KNOBS[name]
            advisories.append({'signal': name, 'value': value,
                               'recommendation': headline, 'detail': detail})
    return advisories


def attribute_bottleneck(snapshot: Dict[str, Any],
                         top_n: int = 5,
                         cost_ledger: Any = None) -> Dict[str, Any]:
    """Rank leaf stages by total-time share and name the knob for the top one.

    Returns ``{'total_stage_seconds', 'ranked': [{'stage', 'seconds', 'share',
    'count', 'mean_s'}], 'top_stage', 'top_share', 'recommendation', 'detail',
    'envelopes': {stage: seconds}, 'advisories': [...]}`` — all JSON-safe.
    ``advisories`` carries the counter/gauge-driven service advice rows
    (``service_busy``/``service_resubmit``/``service_queue_depth`` — pressure
    that has no latency histogram to rank, docs/service.md). An empty snapshot
    yields ``top_stage=None`` with a no-data recommendation (never raises).

    ``cost_ledger`` (a
    :class:`~petastorm_tpu.telemetry.cost_model.CostLedger`, optional) adds
    ``what_if`` rows — "if every rowgroup above the p95 cost dropped to the
    median, total <scope> time −X%": the per-rowgroup skew exposure the stage
    ranking cannot see (docs/observability.md "Cost profiler")."""
    histograms = snapshot.get('histograms') or {}
    leaves = []
    envelopes = {}
    for name, hist in histograms.items():
        if float(hist.get('unit', SECONDS_UNIT)) != SECONDS_UNIT:
            continue  # size histograms (bytes) are not time shares
        total = float(hist.get('sum', 0.0))
        if total <= 0:
            continue
        if name in ENVELOPE_STAGES:
            envelopes[name] = round(total, 6)
        else:
            leaves.append((name, total, int(hist.get('count', 0))))
    leaves.sort(key=lambda item: item[1], reverse=True)
    total_s = sum(total for _, total, _ in leaves)
    ranked = [{'stage': name,
               'seconds': round(total, 6),
               'share': round(total / total_s, 4) if total_s else 0.0,
               'count': count,
               'mean_s': round(total / count, 6) if count else 0.0}
              for name, total, count in leaves[:max(top_n, 1)]]
    advisories = _service_advisories(snapshot)
    what_if = list(cost_ledger.what_if()) if cost_ledger is not None else []
    for row in what_if:
        # exploitable per-rowgroup skew: the stage ranking cannot see it, and
        # the fix is a knob, not a code change — say so
        # (docs/performance.md "Cost-aware scheduling")
        if (row.get('scope') == 'total'
                and float(row.get('skew_p95_over_median', 1.0)) >= 2.0
                and float(row.get('saving_fraction', 0.0)) >= 0.05):
            advisories.append({
                'signal': 'cost_skew_p95_over_median',
                'value': float(row['skew_p95_over_median']),
                'recommendation': 'enable cost-aware scheduling '
                                  '(make_reader(cost_schedule=True))',
                'detail': 'Per-rowgroup decode cost is skewed {}x '
                          '(p95/median); the cost-aware scheduler would '
                          'interleave, split and pre-stage the heavy '
                          'rowgroups from this ledger — preview with '
                          'petastorm-tpu-throughput costs --json.'
                          .format(row['skew_p95_over_median'])})
            break
    if not ranked:
        return {'total_stage_seconds': 0.0, 'ranked': [], 'envelopes': envelopes,
                'top_stage': None, 'top_share': 0.0,
                'advisories': advisories,
                'what_if': what_if,
                'recommendation': 'no stage timings recorded',
                'detail': 'The snapshot holds no latency histograms — run an '
                          'instrumented read first (telemetry is on by default; '
                          'PETASTORM_TPU_TELEMETRY=0 disables it).'}
    top = ranked[0]
    headline, detail = _KNOBS.get(top['stage'], _DEFAULT_ADVICE)
    return {'total_stage_seconds': round(total_s, 6),
            'ranked': ranked,
            'envelopes': envelopes,
            'top_stage': top['stage'],
            'top_share': top['share'],
            'advisories': advisories,
            'what_if': what_if,
            'recommendation': headline,
            'detail': detail}


def format_report(report: Dict[str, Any]) -> str:
    """Human-readable rendering of an :func:`attribute_bottleneck` report."""
    lines = ['pipeline stage attribution '
             '(total {:.3f}s of stage time across all processes)'.format(
                 report.get('total_stage_seconds', 0.0))]
    for entry in report.get('ranked', []):
        lines.append('  {:>6.1%}  {:<14} {:>10.3f}s  ({} spans, mean {:.3f}ms)'
                     .format(entry['share'], entry['stage'], entry['seconds'],
                             entry['count'], entry['mean_s'] * 1e3))
    for stage, seconds in sorted((report.get('envelopes') or {}).items()):
        lines.append('  [envelope] {:<14} {:>7.3f}s (wraps leaf stages above)'
                     .format(stage, seconds))
    if report.get('top_stage'):
        lines.append('  bottleneck: {} ({:.1%}) -> {}'.format(
            report['top_stage'], report['top_share'],
            report['recommendation']))
        lines.append('  {}'.format(report.get('detail', '')))
    else:
        lines.append('  ' + report.get('recommendation', 'no data'))
    for advisory in report.get('advisories') or []:
        lines.append('  [service] {}={:g} -> {}'.format(
            advisory['signal'], advisory['value'],
            advisory['recommendation']))
    for row in report.get('what_if') or []:
        lines.append('  [what-if] {}'.format(row['detail']))
    return '\n'.join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    """``analyze`` CLI entry: load a snapshot file, print the attribution report
    (or ``--json`` one machine-readable line)."""
    import argparse
    parser = argparse.ArgumentParser(
        description='Rank petastorm_tpu pipeline stages by time share and name '
                    'the knob that moves the top one')
    parser.add_argument('snapshot_path',
                        help='telemetry snapshot: a JSON snapshot/report file or '
                             'a JSONL event log (last line wins)')
    parser.add_argument('--json', action='store_true',
                        help='print one machine-readable JSON line instead')
    parser.add_argument('--top', type=int, default=5,
                        help='stages to rank (default 5)')
    parser.add_argument('--costs', default=None, metavar='LEDGER',
                        help='a persisted cost ledger '
                             '(petastorm-tpu-throughput costs) to derive '
                             'what-if rows from')
    args = parser.parse_args(argv)
    from petastorm_tpu.telemetry.export import load_snapshot
    snapshot = load_snapshot(args.snapshot_path)
    cost_ledger = None
    if args.costs:
        from petastorm_tpu.telemetry.cost_model import CostLedger
        cost_ledger = CostLedger.load(args.costs)
    report = attribute_bottleneck(snapshot, top_n=args.top,
                                  cost_ledger=cost_ledger)
    if args.json:
        print(json.dumps(report))
    else:
        print(format_report(report))
    return 0


if __name__ == '__main__':
    sys.exit(main())
