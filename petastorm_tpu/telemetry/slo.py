"""Input-efficiency SLOs: starvation fraction and goodput-vs-ideal derived
from the wait-stage spans the pipeline already records
(docs/observability.md "Efficiency SLOs").

The mission line this measures against: the input pipeline should keep the
accelerator >= 90% busy (``SloPolicy(target_efficiency=0.9)``). The signal
already exists — ``shuffle_wait`` (training loop blocked on the loader's
prefetch queue), ``pool_wait`` (consumer blocked in ``pool.get_results``) and
``d2d_wait`` (blocked on the prefetch-to-device ring) are exactly the seconds
the CONSUMER side sat starved — this module just divides it by wall time:

    starvation_fraction = consumer_wait_seconds / elapsed_seconds
    efficiency          = 1 - starvation_fraction          (clamped to [0, 1])

``shuffle_wait`` and ``pool_wait`` measure the same starvation one layer
apart (the loader's producer blocks in ``pool_wait`` while the training loop
blocks in ``shuffle_wait``), so summing both would double-count a single
stall: the PRIMARY wait stage is ``shuffle_wait`` when present (a loader is
consuming), else ``pool_wait``; ``d2d_wait`` (a distinct, device-tail block
on the consumer path) is added on top. ``h2d`` seconds are reported
informationally — upload time is work, not starvation, but it bounds what
overlap can still hide.

:class:`SloTracker` holds the breach accounting: ``evaluate()`` computes the
report, refreshes the ``slo_efficiency`` / ``slo_target_efficiency`` gauges
in the supplied registry, and — EDGE-TRIGGERED, once per ok→breach
transition, so a dashboard polling ``diagnostics`` cannot inflate the count —
increments the ``slo_breach`` counter, emits an ``slo_breach`` JSONL event
(when a :class:`~petastorm_tpu.telemetry.export.JsonlEventLogger` is
attached) and drops an ``slo_breach`` instant on the flight-recorder
timeline. Surfaces: ``Reader.efficiency_report()`` /
``diagnostics['slo']``, ``JaxDataLoader.efficiency_report()``, the doctor's
WARNING line, bench.py's ``observability`` section, and every ``/metrics``
scrape (the gauges refresh per scrape).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, Optional

from petastorm_tpu.telemetry import registry as _registry
from petastorm_tpu.telemetry import tracing as _tracing
from petastorm_tpu.telemetry.export import JsonlEventLogger
from petastorm_tpu.telemetry.registry import SECONDS_UNIT, MetricsRegistry

#: consumer-facing wait stages, in PRIMARY preference order: the first one
#: present in the snapshot is the starvation measure (they observe the same
#: stall one layer apart — see module docstring); ``d2d_wait`` adds on top
PRIMARY_WAIT_STAGES = ('shuffle_wait', 'pool_wait')
#: device-tail wait added on top of the primary stage
EXTRA_WAIT_STAGES = ('d2d_wait',)
#: informational (upload is work, not starvation)
UPLOAD_STAGE = 'h2d'


@dataclass(frozen=True)
class SloPolicy:
    """Input-efficiency target: breach below ``target_efficiency``; windows
    shorter than ``min_elapsed_s`` are reported but never counted as breaches
    (construction/warmup noise would otherwise page on every startup)."""

    target_efficiency: float = 0.9
    min_elapsed_s: float = 1.0

    def __post_init__(self) -> None:
        """Validate the target is a sane fraction."""
        if not 0.0 < self.target_efficiency <= 1.0:
            raise ValueError('target_efficiency must be in (0, 1], got {!r}'
                             .format(self.target_efficiency))


def resolve_slo_policy(policy: Any) -> SloPolicy:
    """Accept ``None`` (the default 0.9 policy), a float target, or an
    :class:`SloPolicy` — the ``slo_policy=`` kwarg contract of
    ``make_reader`` and ``JaxDataLoader``."""
    if policy is None:
        return SloPolicy()
    if isinstance(policy, SloPolicy):
        return policy
    if isinstance(policy, (int, float)):
        return SloPolicy(target_efficiency=float(policy))
    raise ValueError('slo_policy must be None, a float target, or an '
                     'SloPolicy, got {!r}'.format(policy))


def _stage_seconds(snapshot: Dict[str, Any], stage: str) -> float:
    hist = (snapshot.get('histograms') or {}).get(stage)
    if not hist:
        return 0.0
    if float(hist.get('unit', SECONDS_UNIT)) != SECONDS_UNIT:
        return 0.0
    return float(hist.get('sum', 0.0))


def efficiency_from_snapshot(snapshot: Dict[str, Any],
                             elapsed_s: float,
                             rows: int = 0) -> Dict[str, Any]:
    """Pure efficiency math over one telemetry snapshot (no breach state).

    Returns ``{'efficiency', 'starvation_fraction', 'wait_seconds',
    'wait_stage_seconds', 'primary_wait_stage', 'h2d_seconds', 'elapsed_s',
    'rows', 'goodput_rows_per_sec', 'ideal_rows_per_sec'}`` — all JSON-safe.
    ``ideal_rows_per_sec`` is the rate the same read would have achieved with
    the recorded starvation removed (``rows / (elapsed - wait)``), so
    ``goodput / ideal == efficiency``: the goodput-vs-ideal framing of the
    same number."""
    elapsed_s = max(float(elapsed_s), 0.0)
    primary: Optional[str] = None
    for stage in PRIMARY_WAIT_STAGES:
        if _stage_seconds(snapshot, stage) > 0.0:
            primary = stage
            break
    wait_stage_seconds: Dict[str, float] = {}
    for stage in PRIMARY_WAIT_STAGES + EXTRA_WAIT_STAGES:
        seconds = _stage_seconds(snapshot, stage)
        if seconds:
            wait_stage_seconds[stage] = round(seconds, 6)
    wait = _stage_seconds(snapshot, primary) if primary else 0.0
    wait += sum(_stage_seconds(snapshot, stage)
                for stage in EXTRA_WAIT_STAGES)
    starvation = min(wait / elapsed_s, 1.0) if elapsed_s > 0 else 0.0
    efficiency = max(0.0, 1.0 - starvation)
    goodput = rows / elapsed_s if elapsed_s > 0 else 0.0
    productive = max(elapsed_s - wait, 1e-12)
    ideal = rows / productive if rows else 0.0
    return {
        'efficiency': round(efficiency, 6),
        'starvation_fraction': round(starvation, 6),
        'wait_seconds': round(wait, 6),
        'wait_stage_seconds': wait_stage_seconds,
        'primary_wait_stage': primary,
        'h2d_seconds': round(_stage_seconds(snapshot, UPLOAD_STAGE), 6),
        'elapsed_s': round(elapsed_s, 6),
        'rows': int(rows),
        'goodput_rows_per_sec': round(goodput, 3),
        'ideal_rows_per_sec': round(ideal, 3),
    }


class SloTracker(object):
    """Breach accounting around :func:`efficiency_from_snapshot` (module
    docstring): edge-triggered breach events, cumulative counters, gauge
    refresh. Thread-safe — ``diagnostics`` and a scrape thread may evaluate
    concurrently."""

    #: evaluation points the in-process ring buffer retains (the short
    #: longitudinal tail ``efficiency_report()['history']`` / ``/vars``
    #: expose — docs/observability.md "Longitudinal observatory")
    HISTORY_SIZE = 32

    def __init__(self, policy: Optional[SloPolicy] = None,
                 jsonl: Optional[JsonlEventLogger] = None,
                 on_breach: Optional[Callable[[Dict[str, Any]], None]] = None,
                 history_size: int = HISTORY_SIZE) -> None:
        self.policy = policy if policy is not None else SloPolicy()
        self._jsonl = jsonl
        self._on_breach = on_breach
        self._lock = threading.Lock()
        self._breaches = 0
        self._evaluations = 0
        self._in_breach = False
        self._history: Deque[Dict[str, Any]] = collections.deque(
            maxlen=max(int(history_size), 1))

    def observe_breaches(self, callback: Callable[[Dict[str, Any]], None]) -> None:
        """Attach (or replace) the ok→breach edge observer: called once per
        transition with the full evaluation report, outside the tracker lock
        — the incident recorder's ``slo_breach`` subscription point
        (telemetry/incident.py)."""
        self._on_breach = callback

    @property
    def breaches(self) -> int:
        """Cumulative ok→breach transitions observed by :meth:`evaluate`."""
        with self._lock:
            return self._breaches

    def history(self) -> list:
        """The trailing evaluated points (oldest first, bounded by
        ``history_size``): ``{'elapsed_s', 'efficiency',
        'goodput_rows_per_sec', 'wait_seconds', 'breached'}`` each — the
        in-process tail of the longitudinal series the run historian
        persists across runs (telemetry/history.py). Also carried on every
        :meth:`evaluate` report as ``report['history']`` and in the
        ``/vars`` document as ``slo_history``."""
        with self._lock:
            return [dict(point) for point in self._history]

    def evaluate(self, snapshot: Dict[str, Any], elapsed_s: float,
                 rows: int = 0,
                 registry: Optional[MetricsRegistry] = None) -> Dict[str, Any]:
        """One SLO evaluation: the efficiency report plus breach state.

        Adds ``{'target_efficiency', 'met', 'breached', 'evaluated',
        'breaches', 'evaluations', 'history'}`` to the
        :func:`efficiency_from_snapshot` fields (``history`` is the
        tracker's trailing ring buffer — :meth:`history`). ``evaluated`` is False below ``min_elapsed_s``: the report
        then carries the explicit not-enough-data shape — ``efficiency``
        (and ``starvation_fraction``) are ``None``, ``reason`` says
        ``'not_enough_data'``, no breach is counted and no gauge is set, so
        a warmup window can never read as a spurious 0.0 efficiency or trip
        a breach edge. On an ok→breach transition: ``slo_breach`` counter
        (in ``registry``), ``slo_breach`` JSONL event, ``slo_breach`` trace
        instant, and the attached breach observer — once, until the
        efficiency recovers to the target."""
        report = efficiency_from_snapshot(snapshot, elapsed_s, rows=rows)
        target = self.policy.target_efficiency
        evaluated = elapsed_s >= self.policy.min_elapsed_s
        if not evaluated:
            report['efficiency'] = None
            report['starvation_fraction'] = None
            report['reason'] = 'not_enough_data'
        breached = bool(evaluated and report['efficiency'] < target)
        with self._lock:
            self._evaluations += 1
            is_transition = breached and not self._in_breach
            if evaluated:
                self._in_breach = breached
                # ring-buffer tail of evaluated points (warmup windows carry
                # no efficiency and would only pad the series with Nones)
                self._history.append({
                    'elapsed_s': report['elapsed_s'],
                    'efficiency': report['efficiency'],
                    'goodput_rows_per_sec': report['goodput_rows_per_sec'],
                    'wait_seconds': report['wait_seconds'],
                    'breached': breached,
                })
            if is_transition:
                self._breaches += 1
            breaches = self._breaches
            evaluations = self._evaluations
            history = [dict(point) for point in self._history]
        report.update({
            'target_efficiency': target,
            'met': not breached,
            'breached': breached,
            'evaluated': evaluated,
            'breaches': breaches,
            'evaluations': evaluations,
            'history': history,
        })
        if registry is not None and _registry.telemetry_enabled():
            if evaluated:
                registry.gauge('slo_efficiency').set(report['efficiency'])
            registry.gauge('slo_target_efficiency').set(target)
            if is_transition:
                registry.inc('slo_breach')
        if is_transition:
            _tracing.trace_instant(
                'slo_breach',
                args={'efficiency': report['efficiency'],
                      'target': target,
                      'wait_seconds': report['wait_seconds']})
            if self._jsonl is not None:
                self._jsonl.emit(snapshot, event='slo_breach',
                                 slo={'efficiency': report['efficiency'],
                                      'target': target,
                                      'wait_seconds': report['wait_seconds'],
                                      'elapsed_s': report['elapsed_s']})
            if self._on_breach is not None:
                try:
                    self._on_breach(dict(report))
                except Exception:  # noqa: BLE001 - an observer must not break evaluation
                    logging.getLogger(__name__).exception(
                        'slo breach observer failed')
        return report


def slo_clock() -> float:
    """The monotonic timebase efficiency windows are measured on
    (``time.perf_counter`` — the same clock the stage spans use), exposed so
    owners stamp their construction time consistently."""
    return time.perf_counter()
