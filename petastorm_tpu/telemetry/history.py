"""Longitudinal run historian: cross-run goodput records, robust trailing
baselines, and change-point attribution (docs/observability.md
"Longitudinal observatory").

Every observability plane so far observes a single instant or a single run —
the metrics/SLO plane, the cost ledger, lineage, incidents. This module is
the memory layer over all of them: at ``stop()`` each armed owner (reader /
loader / service dispatcher) appends ONE structured **run record** to an
append-only CRC-framed store keyed by dataset token under the shared
``dataset_state`` home — the same journal discipline as the dispatcher's
durable token ledger (``service/ledger.py``): flush-per-append durability,
atomic compacting rotation (temp file + ``os.replace``), and replay that
stops at the FIRST bad frame (a torn tail is counted in
``history_frames_dropped``, never guessed past).

A run record carries what the next run needs to judge itself against:
config / knob / storage-policy / schedule-plan fingerprints, headline rows/s
and goodput efficiency, per-stage time shares from the telemetry snapshot,
cost-ledger skew, storage counters (footer-cache hit rate, hedge win rate)
and incident/quarantine counts.

The **compare engine** builds a robust trailing baseline — median/MAD over
the last N same-token, same-platform records — and the
``petastorm-tpu-throughput history list|show|compare`` CLI diffs two runs or
a run against its trailing baseline, *attributing* a regression by naming
the stage whose time share grew and any fingerprint/knob that changed
("decode share +18%, knob decode_threads 4 -> 2"). Distinct exit codes per
verdict (:data:`COMPARE_EXIT_CODES`) let a babysitting script branch without
parsing the report.

Attach points: ``make_reader/make_batch_reader(history=True|path|
HistoryPolicy)``, ``JaxDataLoader(history=...)``, ``Dispatcher/ServiceFleet
(history=...)`` / ``serve --history``. ``history=True`` also arms the live
regression sentinel (``telemetry/sentinel.py``) on the same owner. The
autotuner's warm start (``AutotunePolicy(warm_start=True)``) seeds its
knobs from the last-good record's knob fingerprint.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import platform as _platform_mod
import struct
import sys
import tempfile
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from petastorm_tpu.telemetry import registry as _registry
from petastorm_tpu.telemetry.registry import SECONDS_UNIT, MetricsRegistry

logger = logging.getLogger(__name__)

#: store basename inside a dataset's local state home (underscore prefix
#: keeps it out of Parquet directory listings, like every other sidecar)
HISTORY_BASENAME = '_petastorm_tpu_run_history.bin'

#: run-record schema version (bump on incompatible shape changes; replay
#: skips newer-schema records instead of misreading them)
RUN_RECORD_SCHEMA = 1

#: the closed registry of recording layers: every ``build_run_record('x',
#: ...)`` call site must name one of these, and baseline/attribution
#: filtering groups by them — an undeclared owner would write records no
#: comparison ever selects (pipecheck journal-discipline,
#: docs/static-analysis.md)
RUN_RECORD_OWNERS: Tuple[str, ...] = ('reader', 'loader', 'dispatcher')

#: frame header: payload length + CRC32(payload) — the ledger.py discipline
_FRAME_HEADER = struct.Struct('>II')

#: store size that triggers a compacting rotation (runs are one record each,
#: so this bound is generous)
DEFAULT_ROTATE_BYTES = 1 << 20

#: the verdicts ``compare_records`` can return, each with its own CLI exit
#: code so scripts branch on the comparison without parsing the report
COMPARE_VERDICTS: Tuple[str, ...] = ('within-noise', 'improved', 'regressed',
                                     'insufficient-history')
COMPARE_EXIT_CODES: Dict[str, int] = {'within-noise': 0, 'improved': 5,
                                      'regressed': 6,
                                      'insufficient-history': 7}
#: CLI exit for a missing / unreadable store
EXIT_BAD_STORE = 2

#: MAD -> sigma scale for a normal distribution (the robust noise band)
_MAD_SIGMA = 1.4826


@dataclass(frozen=True)
class HistoryPolicy:
    """Run-historian policy — the ``history=`` kwarg contract of
    ``make_reader`` / ``JaxDataLoader`` / ``Dispatcher`` / ``ServiceFleet``
    (``True`` means this default policy; a path string sets ``path``).

    ``path`` overrides the store location (default: the dataset's local
    state home). ``max_records`` bounds the store — a compacting rotation
    keeps the newest N. The trailing baseline is median/MAD over the last
    ``baseline_window`` same-token, same-platform records and needs at least
    ``min_baseline_runs`` of them; a delta is signal only beyond
    ``noise_mads`` robust sigmas AND ``min_rel_delta`` relative change, but
    the band is capped at ``max_rel_delta`` of the baseline median — a
    short noisy history (one cold-start outlier can blow the MAD up past
    the median itself) must never swallow a halved throughput as noise.
    ``sentinel`` arms the live regression sentinel on the same owner
    (``True``/``False`` or a
    :class:`~petastorm_tpu.telemetry.sentinel.SentinelPolicy`)."""

    path: Optional[str] = None
    max_records: int = 128
    baseline_window: int = 8
    min_baseline_runs: int = 3
    noise_mads: float = 3.0
    min_rel_delta: float = 0.05
    max_rel_delta: float = 0.5
    sentinel: Any = True

    def __post_init__(self) -> None:
        """Validate bounds at construction time."""
        if self.max_records < 1:
            raise ValueError('max_records must be >= 1, got {!r}'
                             .format(self.max_records))
        if self.baseline_window < 1:
            raise ValueError('baseline_window must be >= 1, got {!r}'
                             .format(self.baseline_window))
        if self.min_baseline_runs < 1:
            raise ValueError('min_baseline_runs must be >= 1, got {!r}'
                             .format(self.min_baseline_runs))
        if self.noise_mads < 0 or self.min_rel_delta < 0:
            raise ValueError('noise_mads and min_rel_delta must be >= 0')
        if self.max_rel_delta < self.min_rel_delta:
            raise ValueError('max_rel_delta must be >= min_rel_delta, got '
                             '{!r} < {!r}'.format(self.max_rel_delta,
                                                  self.min_rel_delta))


def resolve_history_policy(value: Any) -> Optional[HistoryPolicy]:
    """Accept ``None``/``False`` (disabled — the off path builds nothing),
    ``True`` (default policy), a store/dataset path string, or a
    :class:`HistoryPolicy` — the ``history=`` kwarg contract."""
    if value is None or value is False:
        return None
    if value is True:
        return HistoryPolicy()
    if isinstance(value, str):
        return HistoryPolicy(path=value)
    if isinstance(value, HistoryPolicy):
        return value
    raise ValueError('history must be None, a bool, a path string, or a '
                     'HistoryPolicy, got {!r}'.format(value))


def default_history_path(dataset_url_or_path: str,
                         cache_location: Optional[str] = None
                         ) -> Optional[str]:
    """The store path for a dataset's local state home
    (``dataset_state.sidecar_path`` — the same placement the cost ledger,
    lineage manifest and dispatcher ledger use); None when the dataset has
    no local home."""
    from petastorm_tpu.dataset_state import sidecar_path
    return sidecar_path(dataset_url_or_path, HISTORY_BASENAME,
                        cache_location)


def run_platform() -> str:
    """The platform tag stamped on every record — baselines only ever
    compare same-platform runs (a TPU round against a CPU fallback round
    would shift every number by an order of magnitude)."""
    return _platform_mod.platform()


def fingerprint(payload: Any) -> str:
    """Stable 12-hex-char fingerprint of one JSON-safe payload (sorted keys,
    so dict ordering never flips the hash)."""
    import hashlib
    text = json.dumps(payload, sort_keys=True, default=repr)
    return hashlib.md5(text.encode('utf-8')).hexdigest()[:12]


def stage_time_shares(snapshot: Dict[str, Any],
                      elapsed_s: float) -> Dict[str, float]:
    """Per-stage share of wall time from one cumulative telemetry snapshot:
    ``{stage: seconds/elapsed}`` for every recorded leaf stage (envelope
    stages excluded so shares sum sensibly — same exclusion
    ``telemetry/analyze.py`` applies)."""
    from petastorm_tpu.telemetry.spans import ENVELOPE_STAGES
    shares: Dict[str, float] = {}
    if elapsed_s <= 0:
        return shares
    for stage, hist in (snapshot.get('histograms') or {}).items():
        if stage in ENVELOPE_STAGES or not isinstance(hist, dict):
            continue
        if float(hist.get('unit', SECONDS_UNIT)) != SECONDS_UNIT:
            continue
        seconds = float(hist.get('sum', 0.0))
        if seconds > 0:
            shares[stage] = round(seconds / elapsed_s, 6)
    return shares


def _counter(snapshot: Dict[str, Any], name: str) -> int:
    try:
        return int((snapshot.get('counters') or {}).get(name, 0))
    except (TypeError, ValueError):
        return 0


def _hit_rate(hits: int, misses: int) -> Optional[float]:
    total = hits + misses
    if total <= 0:
        return None
    return round(hits / total, 6)


def build_run_record(owner: str,
                     dataset_token: str,
                     elapsed_s: float,
                     rows: int,
                     snapshot: Optional[Dict[str, Any]] = None,
                     slo_report: Optional[Dict[str, Any]] = None,
                     fingerprints: Optional[Dict[str, Optional[str]]] = None,
                     knobs: Optional[Dict[str, float]] = None,
                     incidents: Optional[Dict[str, Any]] = None,
                     quarantined: int = 0,
                     cost_skew: Optional[float] = None,
                     platform: Optional[str] = None,
                     recorded_unix_s: Optional[float] = None
                     ) -> Dict[str, Any]:
    """Assemble one JSON-safe run record from an owner's end-of-run state.

    ``owner`` names the recording layer (``reader`` / ``loader`` /
    ``dispatcher``); ``fingerprints`` carries the config / knob / storage /
    schedule identity hashes; ``knobs`` the raw knob values the attribution
    engine diffs ("decode_threads 4 -> 2"). ``recorded_unix_s`` is
    injectable so record-identity tests never read the wall clock."""
    snapshot = snapshot or {}
    slo_report = slo_report or {}
    elapsed_s = max(float(elapsed_s), 0.0)
    rows = int(rows)
    record: Dict[str, Any] = {
        'schema': RUN_RECORD_SCHEMA,
        'kind': 'run',
        'owner': str(owner),
        'dataset_token': str(dataset_token),
        'platform': platform if platform is not None else run_platform(),
        'recorded_unix_s': (float(recorded_unix_s)
                            if recorded_unix_s is not None else time.time()),
        'elapsed_s': round(elapsed_s, 6),
        'rows': rows,
        'rows_per_sec': round(rows / elapsed_s, 3) if elapsed_s > 0 else 0.0,
        'efficiency': slo_report.get('efficiency'),
        'wait_seconds': slo_report.get('wait_seconds'),
        'primary_wait_stage': slo_report.get('primary_wait_stage'),
        'stage_shares': stage_time_shares(snapshot, elapsed_s),
        'fingerprints': dict(fingerprints or {}),
        'knobs': {str(k): v for k, v in (knobs or {}).items()},
        'quarantined': int(quarantined),
    }
    footer_rate = _hit_rate(_counter(snapshot, 'storage_footer_cache_hit'),
                            _counter(snapshot, 'storage_footer_cache_miss'))
    hedge_rate = _hit_rate(_counter(snapshot, 'storage_hedge_won'),
                           max(_counter(snapshot, 'storage_hedge_fired')
                               - _counter(snapshot, 'storage_hedge_won'), 0))
    record['storage'] = {'footer_cache_hit_rate': footer_rate,
                         'hedge_win_rate': hedge_rate}
    if incidents:
        record['incidents'] = {
            'captured': int(incidents.get('captured', 0) or 0),
            'rate_limited': int(incidents.get('rate_limited', 0) or 0)}
    else:
        record['incidents'] = {'captured': 0, 'rate_limited': 0}
    if cost_skew is not None:
        record['cost_skew_p95_over_median'] = round(float(cost_skew), 4)
    return record


# ----------------------------------------------------------------- journal


def read_history(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Every CRC-verified run record in store order, plus the dropped-frame
    count. Stops at the FIRST bad frame (short header, short payload, CRC
    mismatch, non-JSON payload) — framing after an unreadable frame cannot
    be trusted, so the suffix is abandoned: counted, never guessed at.
    Records with a schema newer than this build understands are skipped
    (counted as records, not as drops)."""
    records: List[Dict[str, Any]] = []
    dropped = 0
    with open(path, 'rb') as f:
        while True:
            header = f.read(_FRAME_HEADER.size)
            if not header:
                break
            if len(header) < _FRAME_HEADER.size:
                dropped += 1
                break
            length, crc = _FRAME_HEADER.unpack(header)
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                dropped += 1
                break
            try:
                record = json.loads(payload.decode('utf-8'))
            except (UnicodeDecodeError, ValueError):
                dropped += 1
                break
            if (isinstance(record, dict)
                    and int(record.get('schema', 0)) <= RUN_RECORD_SCHEMA):
                records.append(record)
    return records, dropped


def load_records(path: Optional[str]) -> Tuple[List[Dict[str, Any]], int]:
    """:func:`read_history` tolerant of a missing store (first run: no
    records, no drops) and of an unreadable one (no records, one drop — the
    caller degrades loudly, like the ledger's replay)."""
    if not path or not os.path.exists(path):
        return [], 0
    try:
        return read_history(path)
    except OSError as exc:
        logger.error('history: store %s is unreadable (%s)', path, exc)
        return [], 1


def _frame(record: Dict[str, Any]) -> bytes:
    payload = json.dumps(record, sort_keys=True).encode('utf-8')
    return _FRAME_HEADER.pack(len(payload), zlib.crc32(payload)) + payload


class RunHistorian(object):
    """Append-only CRC-framed run-record store with atomic compaction.

    One record per run, appended at ``stop()`` — the writer opens, appends
    one flushed frame and closes per call (no long-lived handle to leak
    across a crash), then rotates when the store outgrows ``rotate_bytes``
    or ``policy.max_records``: the newest ``max_records`` are rewritten into
    a temp file and ``os.replace``d over the store — the same atomic-publish
    discipline every sidecar in this repo uses. Appends are serialized by an
    internal lock (a loader and its reader may both record at teardown)."""

    def __init__(self, path: str,
                 policy: Optional[HistoryPolicy] = None,
                 registry: Optional[MetricsRegistry] = None,
                 rotate_bytes: int = DEFAULT_ROTATE_BYTES) -> None:
        self.path = path
        self.policy = policy if policy is not None else HistoryPolicy()
        self.rotate_bytes = rotate_bytes
        self._registry = registry
        self._lock = threading.Lock()
        self._appended = 0
        self._last_dropped = 0

    def append(self, record: Dict[str, Any]) -> bool:
        """Append one run record (flushed to the OS — it survives any
        SIGKILL of the owner). Store write failures are logged, not raised:
        the historian is an upgrade, never a new way to fail a run that
        already succeeded. Returns True when the record landed."""
        frame = _frame(record)
        with self._lock:
            try:
                parent = os.path.dirname(self.path)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                with open(self.path, 'ab') as f:
                    f.write(frame)
                    f.flush()
                self._appended += 1
                self._maybe_rotate(latest=record)
            except OSError:
                logger.exception('history: append to %s failed; this run is '
                                 'not recorded', self.path)
                return False
        if self._registry is not None and _registry.telemetry_enabled():
            self._registry.inc('history_record_written')
        return True

    def _maybe_rotate(self, latest: Optional[Dict[str, Any]] = None) -> None:
        # called under _lock
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size < self.rotate_bytes:
            records, dropped = read_history(self.path)
            if dropped == 0 and len(records) <= self.policy.max_records:
                return
        else:
            records, dropped = read_history(self.path)
        if dropped and latest is not None:
            # replay stops at the torn frame, so the frame just appended
            # after it is invisible to read_history — re-add it or the
            # healing compaction would silently drop this run's record
            records = records + [latest]
        keep = records[-self.policy.max_records:]
        parent = os.path.dirname(self.path) or '.'
        fd, tmp_path = tempfile.mkstemp(dir=parent, prefix='.history-rotate-')
        try:
            with os.fdopen(fd, 'wb') as tmp:
                for record in keep:
                    tmp.write(_frame(record))
                tmp.flush()
                os.fsync(tmp.fileno())
            os.replace(tmp_path, self.path)
        except OSError:
            logger.exception('history: rotation of %s failed; store keeps '
                             'growing until the next attempt', self.path)
        finally:
            # no-op after a successful os.replace; on ANY failure path
            # (OSError or not) the orphaned temp file is removed
            try:
                os.unlink(tmp_path)
            except OSError:
                pass

    def records(self) -> List[Dict[str, Any]]:
        """Replay the store (CRC-verified records, store order); a torn tail
        is counted into ``history_frames_dropped`` and surfaced by
        :meth:`state`."""
        records, dropped = load_records(self.path)
        with self._lock:
            self._last_dropped = dropped
        if (dropped and self._registry is not None
                and _registry.telemetry_enabled()):
            self._registry.inc('history_frames_dropped', dropped)
        return records

    def state(self) -> Dict[str, Any]:
        """JSON-safe store status for diagnostics / doctor."""
        with self._lock:
            return {'path': self.path, 'appended': self._appended,
                    'frames_dropped': self._last_dropped,
                    'max_records': self.policy.max_records}


# ---------------------------------------------------------------- baseline


def select_records(records: List[Dict[str, Any]],
                   dataset_token: Optional[str] = None,
                   platform: Optional[str] = None,
                   owner: Optional[str] = None) -> List[Dict[str, Any]]:
    """The records comparable to one run: same token, same platform (and
    optionally same owner layer), store order preserved."""
    out = []
    for record in records:
        if dataset_token is not None \
                and record.get('dataset_token') != dataset_token:
            continue
        if platform is not None and record.get('platform') != platform:
            continue
        if owner is not None and record.get('owner') != owner:
            continue
        out.append(record)
    return out


def robust_baseline(values: List[float]) -> Dict[str, float]:
    """Median/MAD summary of one metric series — the noise model a trailing
    baseline holds a candidate against (robust: one outlier run cannot drag
    the baseline the way a mean would)."""
    if not values:
        return {'count': 0, 'median': 0.0, 'mad': 0.0}
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    median = (ordered[mid] if n % 2
              else (ordered[mid - 1] + ordered[mid]) / 2.0)
    deviations = sorted(abs(v - median) for v in ordered)
    mad = (deviations[mid] if n % 2
           else (deviations[mid - 1] + deviations[mid]) / 2.0)
    return {'count': n, 'median': median, 'mad': mad}


def trailing_baseline(records: List[Dict[str, Any]],
                      dataset_token: str,
                      platform: str,
                      window: int = 8,
                      owner: Optional[str] = None) -> Dict[str, Any]:
    """The robust trailing baseline for one (token, platform) stream: the
    last ``window`` comparable records summarized as median/MAD of rows/s
    and efficiency, plus the per-stage median shares the attribution engine
    diffs against."""
    comparable = select_records(records, dataset_token, platform,
                                owner=owner)[-window:]
    rates = [float(r.get('rows_per_sec', 0.0)) for r in comparable]
    efficiencies = [float(r['efficiency']) for r in comparable
                    if r.get('efficiency') is not None]
    stages: Dict[str, List[float]] = {}
    for record in comparable:
        for stage, share in (record.get('stage_shares') or {}).items():
            stages.setdefault(stage, []).append(float(share))
    return {
        'count': len(comparable),
        'window': window,
        'rows_per_sec': robust_baseline(rates),
        'efficiency': robust_baseline(efficiencies),
        'stage_shares': {stage: robust_baseline(values)['median']
                         for stage, values in stages.items()},
        'records': comparable,
    }


# ----------------------------------------------------------- compare engine


def _diff_fingerprints(candidate: Dict[str, Any],
                       reference: Dict[str, Any]) -> List[str]:
    changed = []
    cand = candidate.get('fingerprints') or {}
    ref = reference.get('fingerprints') or {}
    for key in sorted(set(cand) | set(ref)):
        if cand.get(key) != ref.get(key):
            changed.append('{} {} -> {}'.format(key, ref.get(key),
                                                cand.get(key)))
    return changed


def _diff_knobs(candidate: Dict[str, Any],
                reference: Dict[str, Any]) -> List[str]:
    changed = []
    cand = candidate.get('knobs') or {}
    ref = reference.get('knobs') or {}
    for key in sorted(set(cand) | set(ref)):
        if cand.get(key) != ref.get(key):
            changed.append('knob {} {} -> {}'.format(
                key, _fmt_value(ref.get(key)), _fmt_value(cand.get(key))))
    return changed


def _fmt_value(value: Any) -> str:
    if isinstance(value, float) and value == int(value):
        return str(int(value))
    return str(value)


def _grown_stages(candidate: Dict[str, Any],
                  baseline_shares: Dict[str, float],
                  min_share_delta: float = 0.03) -> List[Dict[str, Any]]:
    grown = []
    for stage, share in (candidate.get('stage_shares') or {}).items():
        delta = float(share) - float(baseline_shares.get(stage, 0.0))
        if delta >= min_share_delta:
            grown.append({'stage': stage, 'share': round(float(share), 4),
                          'share_delta': round(delta, 4)})
    grown.sort(key=lambda entry: -float(entry['share_delta']))
    return grown


def compare_records(candidate: Dict[str, Any],
                    baseline: Dict[str, Any],
                    policy: Optional[HistoryPolicy] = None
                    ) -> Dict[str, Any]:
    """Judge one run against a :func:`trailing_baseline` and attribute the
    outcome.

    Verdicts: ``insufficient-history`` (fewer than
    ``policy.min_baseline_runs`` comparable records), ``regressed`` /
    ``improved`` (the rows/s delta clears the noise band — ``noise_mads``
    robust sigmas AND ``min_rel_delta`` relative, capped at
    ``max_rel_delta`` of the median), else ``within-noise``.
    A regression's ``attribution`` names the grown stage(s) and every
    changed fingerprint/knob vs the newest baseline record."""
    policy = policy if policy is not None else HistoryPolicy()
    base_rate = baseline.get('rows_per_sec') or {}
    count = int(baseline.get('count', 0))
    rate = float(candidate.get('rows_per_sec', 0.0))
    report: Dict[str, Any] = {
        'candidate': {
            'owner': candidate.get('owner'),
            'dataset_token': candidate.get('dataset_token'),
            'recorded_unix_s': candidate.get('recorded_unix_s'),
            'rows_per_sec': rate,
            'efficiency': candidate.get('efficiency'),
        },
        'baseline': {
            'count': count,
            'window': baseline.get('window'),
            'median_rows_per_sec': round(float(base_rate.get('median', 0.0)),
                                         3),
            'mad_rows_per_sec': round(float(base_rate.get('mad', 0.0)), 3),
            'median_efficiency': round(float(
                (baseline.get('efficiency') or {}).get('median', 0.0)), 6),
        },
    }
    if count < policy.min_baseline_runs:
        report['verdict'] = 'insufficient-history'
        report['exit_code'] = COMPARE_EXIT_CODES['insufficient-history']
        report['reason'] = ('{} comparable record(s); need >= {}'
                            .format(count, policy.min_baseline_runs))
        return report
    median = float(base_rate.get('median', 0.0))
    mad = float(base_rate.get('mad', 0.0))
    # MAD band floored at min_rel_delta and CAPPED at max_rel_delta of the
    # median: a 4-run history with one cold-start outlier can push the MAD
    # past the median itself, and an uncapped band would then read a halved
    # throughput as within-noise
    band = max(policy.noise_mads * _MAD_SIGMA * mad,
               policy.min_rel_delta * median)
    band = min(band, policy.max_rel_delta * median)
    delta = rate - median
    delta_pct = (delta / median * 100.0) if median > 0 else 0.0
    report['delta_rows_per_sec'] = round(delta, 3)
    report['delta_pct'] = round(delta_pct, 2)
    report['noise_band_rows_per_sec'] = round(band, 3)
    if delta < -band:
        verdict = 'regressed'
    elif delta > band:
        verdict = 'improved'
    else:
        verdict = 'within-noise'
    report['verdict'] = verdict
    report['exit_code'] = COMPARE_EXIT_CODES[verdict]
    baseline_records = baseline.get('records') or []
    reference = baseline_records[-1] if baseline_records else {}
    attribution: Dict[str, Any] = {
        'grown_stages': _grown_stages(
            candidate, baseline.get('stage_shares') or {}),
        'changed_fingerprints': _diff_fingerprints(candidate, reference),
        'changed_knobs': _diff_knobs(candidate, reference),
    }
    report['attribution'] = attribution
    clauses: List[str] = []
    for entry in attribution['grown_stages'][:2]:
        clauses.append('{} share {:+.0f}%'.format(
            entry['stage'], float(entry['share_delta']) * 100.0))
    clauses.extend(attribution['changed_knobs'][:3])
    clauses.extend(attribution['changed_fingerprints'][:2])
    report['reason'] = ('rows/s {:+.1f}% vs trailing median {:.1f}{}'
                        .format(delta_pct, median,
                                ' ({})'.format(', '.join(clauses))
                                if clauses else ''))
    return report


def compare_against_history(records: List[Dict[str, Any]],
                            candidate: Dict[str, Any],
                            policy: Optional[HistoryPolicy] = None
                            ) -> Dict[str, Any]:
    """One-call form: build the candidate's trailing baseline from ``records``
    (excluding the candidate itself when it is the stored tail) and compare.
    What a CI gate or the bench baseline check calls."""
    policy = policy if policy is not None else HistoryPolicy()
    pool = [r for r in records if r is not candidate]
    baseline = trailing_baseline(pool,
                                 str(candidate.get('dataset_token')),
                                 str(candidate.get('platform')),
                                 window=policy.baseline_window,
                                 owner=candidate.get('owner'))
    return compare_records(candidate, baseline, policy)


def last_good_record(records: List[Dict[str, Any]],
                     dataset_token: str,
                     platform: Optional[str] = None
                     ) -> Optional[Dict[str, Any]]:
    """The newest same-token (and same-platform, when given) record — the
    autotuner's warm-start seed (``AutotunePolicy(warm_start=True)``); None
    when no comparable record exists, which gates warm start off."""
    comparable = select_records(records, dataset_token, platform)
    return comparable[-1] if comparable else None


# --------------------------------------------------------------------- CLI


def _record_summary(index: int, record: Dict[str, Any]) -> str:
    recorded = record.get('recorded_unix_s')
    stamp = (time.strftime('%Y-%m-%d %H:%M:%S',
                           time.localtime(float(recorded)))
             if recorded else '-')
    return ('[{:>3}] {}  {:<10} token={} {:>10.1f} rows/s  eff={}  {}'
            .format(index, stamp, str(record.get('owner', '?')),
                    record.get('dataset_token'),
                    float(record.get('rows_per_sec', 0.0)),
                    record.get('efficiency'),
                    record.get('platform', '')))


def format_compare(report: Dict[str, Any]) -> str:
    """Human rendering of one :func:`compare_records` report."""
    lines = ['history compare: {}'.format(report['verdict'].upper()),
             '  candidate: {:.1f} rows/s (owner={}, token={})'.format(
                 float(report['candidate']['rows_per_sec']),
                 report['candidate'].get('owner'),
                 report['candidate'].get('dataset_token')),
             '  baseline:  median {:.1f} rows/s over {} run(s) '
             '(MAD {:.1f})'.format(
                 float(report['baseline']['median_rows_per_sec']),
                 report['baseline']['count'],
                 float(report['baseline']['mad_rows_per_sec']))]
    if 'delta_pct' in report:
        lines.append('  delta: {:+.1f}% (noise band +/-{:.1f} rows/s)'
                     .format(float(report['delta_pct']),
                             float(report['noise_band_rows_per_sec'])))
    attribution = report.get('attribution') or {}
    grown = attribution.get('grown_stages') or []
    if grown:
        lines.append('  grown stages:')
        for entry in grown:
            lines.append('    - {} share {:+.0f}% (now {:.0f}%)'.format(
                entry['stage'], float(entry['share_delta']) * 100.0,
                float(entry['share']) * 100.0))
    for key, label in (('changed_knobs', 'changed knobs'),
                       ('changed_fingerprints', 'changed fingerprints')):
        entries = attribution.get(key) or []
        if entries:
            lines.append('  {}:'.format(label))
            for entry in entries:
                lines.append('    - {}'.format(entry))
    lines.append('  reason: {}'.format(report.get('reason', '')))
    lines.append('  verdict: {} (exit {})'.format(report['verdict'],
                                                  report['exit_code']))
    return '\n'.join(lines)


def _resolve_store(target: str) -> Optional[str]:
    """A CLI ``store`` argument is either the store file itself or a dataset
    path/URL whose local state home holds one."""
    if os.path.isfile(target):
        return target
    return default_history_path(target)


def main(argv: Optional[List[str]] = None) -> int:
    """``petastorm-tpu-throughput history list|show|compare``: inspect the
    longitudinal run-record store and judge runs against their trailing
    baseline. ``compare`` exits with the verdict's code (within-noise 0 /
    improved 5 / regressed 6 / insufficient-history 7; 2 = unreadable
    store)."""
    parser = argparse.ArgumentParser(
        prog='petastorm-tpu-throughput history',
        description='Longitudinal run history: list/show/compare recorded '
                    'runs (docs/observability.md "Longitudinal '
                    'observatory").')
    sub = parser.add_subparsers(dest='cmd', required=True)

    p_list = sub.add_parser('list', help='list recorded runs, oldest first')
    p_list.add_argument('store', help='history store file, or a dataset '
                                      'path/URL with a local state home')
    p_list.add_argument('--token', default=None,
                        help='only runs of this dataset token')
    p_list.add_argument('--json', action='store_true')

    p_show = sub.add_parser('show', help='print one run record as JSON')
    p_show.add_argument('store')
    p_show.add_argument('--index', type=int, default=-1,
                        help='record index from `list` (default: newest)')

    p_cmp = sub.add_parser(
        'compare',
        help='diff two runs, or a run against its trailing baseline')
    p_cmp.add_argument('store')
    p_cmp.add_argument('--index', type=int, default=-1,
                       help='candidate record index (default: newest)')
    p_cmp.add_argument('--against', type=int, default=None,
                       help='baseline record index (default: the trailing '
                            'median/MAD baseline of the candidate\'s '
                            'token+platform stream)')
    p_cmp.add_argument('--window', type=int, default=None,
                       help='trailing-baseline window (default: policy '
                            'default)')
    p_cmp.add_argument('--json', action='store_true')

    args = parser.parse_args(argv)
    path = _resolve_store(args.store)
    if path is None:
        print('history: {!r} has no local state home; pass the store file '
              'path'.format(args.store), file=sys.stderr)
        return EXIT_BAD_STORE
    records, dropped = load_records(path)
    if not records and not os.path.exists(path):
        print('history: no store at {!r}'.format(path), file=sys.stderr)
        return EXIT_BAD_STORE
    if dropped:
        print('history: WARNING: {} torn/corrupt frame(s) dropped from the '
              'store tail'.format(dropped), file=sys.stderr)

    if args.cmd == 'list':
        listed = (select_records(records, dataset_token=args.token)
                  if args.token else records)
        if args.json:
            print(json.dumps(listed, indent=1, sort_keys=True))
        else:
            for index, record in enumerate(listed):
                print(_record_summary(index, record))
            print('{} record(s) in {}'.format(len(listed), path))
        return 0

    try:
        candidate = records[args.index]
    except IndexError:
        print('history: no record at index {} ({} recorded)'
              .format(args.index, len(records)), file=sys.stderr)
        return EXIT_BAD_STORE

    if args.cmd == 'show':
        print(json.dumps(candidate, indent=1, sort_keys=True))
        return 0

    # compare
    policy = HistoryPolicy() if args.window is None else HistoryPolicy(
        baseline_window=args.window)
    if args.against is not None:
        try:
            reference = records[args.against]
        except IndexError:
            print('history: no record at index {}'.format(args.against),
                  file=sys.stderr)
            return EXIT_BAD_STORE
        baseline = {
            'count': 1, 'window': 1,
            'rows_per_sec': robust_baseline(
                [float(reference.get('rows_per_sec', 0.0))]),
            'efficiency': robust_baseline(
                [float(reference['efficiency'])]
                if reference.get('efficiency') is not None else []),
            'stage_shares': {k: float(v) for k, v in
                             (reference.get('stage_shares') or {}).items()},
            'records': [reference],
        }
        report = compare_records(candidate, baseline,
                                 HistoryPolicy(min_baseline_runs=1,
                                               baseline_window=1))
    else:
        report = compare_against_history(records, candidate, policy)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(format_compare(report))
    return int(report['exit_code'])


if __name__ == '__main__':  # pragma: no cover
    sys.exit(main())
