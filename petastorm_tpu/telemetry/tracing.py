"""Flight recorder: bounded per-process ring-buffer event tracing with a
causal rowgroup context (docs/observability.md "Flight recorder").

PR-3's histograms answer "which stage is slow on average"; this module answers
"what happened to *this* rowgroup during *that* stall". Every process keeps a
**bounded, lock-free ring buffer** of timestamped events:

- **complete events** (``'X'``): one per stage span from the 16-stage catalog
  (``telemetry/spans.py`` emits them from ``stage_span`` / ``record_stage``
  whenever tracing is on);
- **instant events** (``'i'``): the anomalies — watchdog reaps, circuit-breaker
  transitions, quarantines, shm CRC drops, shm wire fallbacks, re-ventilations
  (the declared catalog is ``spans.TRACE_INSTANTS``; pipecheck's
  telemetry-names rule rejects undeclared names).

Events are tagged with the **causal trace context** ``(epoch, rowgroup,
attempt)``: the epoch/rowgroup pair originates at the ventilator (it already
rides every ventilated item as ``epoch_index``/``piece_index``), the dispatch
*attempt* rides the process pool's existing work frames, and
``process_worker_main`` installs it before each item so worker-side spans are
stitched to the exact delivery attempt — a re-ventilated rowgroup's second life
is a *different* attempt on the timeline.

Cross-process collection reuses the telemetry sidecar ride: the rowgroup worker
**drains** its thread's ring into each published batch's ``trace`` sidecar
(``{'pid': ..., 'events': [...]}``) and the reader merges it into the
consumer-side recorder, so one :func:`trace_snapshot` covers every process.
Ring capacity is ``PETASTORM_TPU_TRACE_RING`` events per thread ring (default
65536); overwritten events are **counted, never silently lost** — the drop
count rides every snapshot and summary. Two bounded tails are inherent to the
sidecar ride and documented rather than counted: spans recorded *during* a
publish (``serialize``/``shm_slot_wait``) ship one batch late — so each
worker's final such span stays in its ring at shutdown — and a thread's
undrained ring is released when the thread exits (same one-item-late contract
as the ``telemetry`` sidecar).

Timestamps are ``time.perf_counter()`` microseconds: on Linux that is
``CLOCK_MONOTONIC``, which is system-wide per boot, so worker and consumer
events of one host share a timebase and interleave correctly on the exported
timeline (the only deployment shape the process pool supports).

Tracing is **off by default** (``PETASTORM_TPU_TRACE=1``, ``make_reader(...,
trace=True)`` or :func:`set_trace_enabled` turn it on); when off, every hook is
one attribute read. Export is :mod:`petastorm_tpu.telemetry.trace_export`
(Chrome-trace/Perfetto JSON + anomaly summary).
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: causal trace context: (absolute epoch, rowgroup piece index, dispatch attempt)
TraceContext = Tuple[int, int, int]
#: one recorded event: (ts_us, dur_us, phase 'X'|'i', name, ctx, tid, args)
TraceEvent = Tuple[float, float, str, str, Optional[TraceContext], int,
                   Optional[Dict[str, Any]]]

_ENV_SWITCH = 'PETASTORM_TPU_TRACE'
_ENV_RING = 'PETASTORM_TPU_TRACE_RING'

#: default per-thread ring capacity (events); also the foreign-event buffer cap
DEFAULT_RING_EVENTS = 65536

_enabled = os.environ.get(_ENV_SWITCH, '0') not in ('0', '', 'false', 'off')


def _ring_capacity_from_env() -> int:
    raw = os.environ.get(_ENV_RING, '')
    try:
        value = int(raw) if raw else DEFAULT_RING_EVENTS
    except ValueError:
        return DEFAULT_RING_EVENTS
    return max(value, 16)


def trace_enabled() -> bool:
    """True when the flight recorder is armed (``PETASTORM_TPU_TRACE=1`` /
    :func:`set_trace_enabled`). Off by default; when off every trace hook is a
    single attribute read."""
    return _enabled


def set_trace_enabled(value: bool) -> None:
    """Override the env-derived tracing switch. Scope mirrors
    :func:`~petastorm_tpu.telemetry.registry.set_telemetry_enabled`: this
    process, plus process-pool workers spawned AFTER the call (the pool
    captures the switch into the worker environment at ``start()``)."""
    global _enabled
    _enabled = bool(value)


class _Ring(object):
    """One thread's private bounded entry storage: a preallocated list written
    round-robin (plain :data:`TraceEvent` tuples in per-thread rings;
    ``(pid, TraceEvent)`` wrappers in the foreign buffer). Single-writer (the
    owning thread); readers tolerate the one in-flight slot being
    mid-overwrite (CPython list-slot assignment is atomic, so they see the
    old or the new entry, never a torn one)."""

    # __weakref__: the recorder's registry holds only weak refs to rings
    __slots__ = ('buf', 'cap', 'n', 'dropped', '__weakref__')

    def __init__(self, cap: int) -> None:
        self.buf: List[Optional[Any]] = [None] * cap
        self.cap = cap
        self.n = 0
        self.dropped = 0

    def append(self, event: Any) -> None:
        if self.n >= self.cap:
            self.dropped += 1
        self.buf[self.n % self.cap] = event
        self.n += 1

    def events(self) -> List[Any]:
        """Buffered entries, oldest first (never clears)."""
        if self.n <= self.cap:
            raw: Sequence[Optional[Any]] = self.buf[:self.n]
        else:
            pivot = self.n % self.cap
            raw = self.buf[pivot:] + self.buf[:pivot]
        return [event for event in raw if event is not None]

    def clear(self) -> None:
        self.buf = [None] * self.cap
        self.n = 0


class _RingHolder(object):
    """The one STRONG reference to a thread's ring, stored in thread-local
    storage: when the thread exits, CPython drops the holder, its finalizer
    retires the ring's undrained tail, and the ring memory is released."""

    __slots__ = ('ring', '__weakref__')

    def __init__(self, ring: _Ring) -> None:
        self.ring = ring


class TraceRecorder(object):
    """Per-process flight recorder: per-thread bounded rings (lock-free record
    path, same discipline as the histogram shards) plus one bounded buffer of
    **foreign** events merged from other processes' ``trace`` sidecars.

    ``record`` appends to the calling thread's ring; ``drain`` hands off and
    clears the calling thread's ring (the worker-publish path); ``snapshot``
    gathers every ring plus the foreign buffer without clearing (the consumer
    dump path). The only lock guards ring REGISTRATION and the foreign buffer
    — never the record path.

    Ring lifetime is thread lifetime: the registry holds only WEAK references
    (the strong one lives in the owning thread's local storage), so a
    long-lived process that keeps creating short-lived reader/worker threads
    does not accumulate dead rings without bound. When a thread exits, a
    finalizer **retires** its undrained tail — remaining events and drop
    count — into one bounded process-wide retired buffer (overflow counted
    there like everywhere else): a ventilator or loader thread that finishes
    before ``snapshot()`` still contributes its events to the capture."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self._capacity = capacity if capacity is not None \
            else _ring_capacity_from_env()
        self._local = threading.local()
        self._rings: List['weakref.ref[_Ring]'] = []
        self._lock = threading.Lock()
        self._foreign = _Ring(self._capacity)
        self._foreign_dropped = 0
        #: dead threads' undrained events (own process), moved here by the
        #: per-thread finalizer so thread exit never erases a capture
        self._retired = _Ring(self._capacity)
        self._retired_dropped = 0

    def _ring(self) -> _Ring:
        holder = getattr(self._local, 'holder', None)
        if holder is None:
            ring = _Ring(self._capacity)
            holder = _RingHolder(ring)
            with self._lock:
                self._rings = [ref for ref in self._rings
                               if ref() is not None]
                self._rings.append(weakref.ref(ring))
            # The holder lives only in this thread's local storage: thread
            # exit drops it, the finalizer retires the ring's leftovers, and
            # the finalizer's own ref to the ring is released — memory stays
            # bounded while the capture stays complete.
            weakref.finalize(holder, self._retire_ring, ring)
            self._local.holder = holder
        ring_out: _Ring = holder.ring
        return ring_out

    def _retire_ring(self, ring: _Ring) -> None:
        """Move a dead thread's undrained events into the retired buffer."""
        with self._lock:
            for event in ring.events():
                self._retired.append(event)
            self._retired_dropped += ring.dropped
        ring.clear()
        ring.dropped = 0

    def _live_rings(self) -> List[_Ring]:
        # caller holds self._lock
        return [ring for ring in (ref() for ref in self._rings)
                if ring is not None]

    def record(self, ts_us: float, dur_us: float, phase: str, name: str,
               ctx: Optional[TraceContext],
               args: Optional[Dict[str, Any]] = None) -> None:
        """Append one event to the calling thread's ring (no locks)."""
        self._ring().append((ts_us, dur_us, phase, name, ctx,
                             threading.get_ident(), args))

    def drain(self) -> Optional[Tuple[List[TraceEvent], int]]:
        """Hand off and clear the calling thread's ring (None when empty) —
        the worker side of the ``trace`` batch sidecar. Returns ``(events,
        dropped)`` where ``dropped`` is the overwrite count SINCE THE LAST
        DRAIN (a delta, zeroed here): the consumer sums sidecar drop counts,
        so a cumulative figure would be re-added once per later batch."""
        holder = getattr(self._local, 'holder', None)
        ring = holder.ring if holder is not None else None
        if ring is None or ring.n == 0:
            return None
        events = ring.events()
        dropped = ring.dropped
        ring.dropped = 0
        ring.clear()
        return events, dropped

    def merge(self, pid: int, events: Sequence[Sequence[Any]],
              dropped: int = 0) -> None:
        """Fold another process's drained events (one ``trace`` sidecar) into
        the bounded foreign buffer. The producing ``pid`` is kept out-of-band
        (a wrapper tuple, not an ``args`` key) so an event whose own args
        carry a ``pid`` — e.g. an anomaly marker naming a reaped child —
        survives the merge untouched."""
        with self._lock:
            self._foreign_dropped += int(dropped)
            for event in events:
                # sidecars arrive JSON-decoded (lists); normalize the ctx
                ts_us, dur_us, phase, name, ctx, tid, args = event
                norm_ctx: Optional[TraceContext] = (
                    (int(ctx[0]), int(ctx[1]), int(ctx[2])) if ctx else None)
                # foreign-buffer entry shape: (pid, TraceEvent)
                self._foreign.append(
                    (pid, (float(ts_us), float(dur_us), str(phase), str(name),
                           norm_ctx, int(tid), dict(args) if args else None)))

    def snapshot(self) -> Dict[str, Any]:
        """JSON-safe view of the whole recorder: ``{'pid', 'events':
        [{'pid','tid','ts_us','dur_us','ph','name','ctx','args'}, ...],
        'dropped_events', 'capacity'}``. Events are sorted by timestamp;
        foreign events keep their producing pid."""
        own_pid = os.getpid()
        with self._lock:
            rings = self._live_rings()
            foreign_entries = self._foreign.events()
            own_events = [event for ring in rings for event in ring.events()]
            own_events.extend(self._retired.events())
            dropped = (self._foreign.dropped + self._foreign_dropped
                       + self._retired.dropped + self._retired_dropped
                       + sum(ring.dropped for ring in rings))
        records: List[Dict[str, Any]] = []
        for ts_us, dur_us, phase, name, ctx, tid, args in own_events:
            records.append({'pid': own_pid, 'tid': tid, 'ts_us': ts_us,
                            'dur_us': dur_us, 'ph': phase, 'name': name,
                            'ctx': list(ctx) if ctx else None,
                            'args': args})
        for entry in foreign_entries:
            pid, (ts_us, dur_us, phase, name, ctx, tid, args) = entry
            records.append({'pid': int(pid), 'tid': tid, 'ts_us': ts_us,
                            'dur_us': dur_us, 'ph': phase, 'name': name,
                            'ctx': list(ctx) if ctx else None,
                            'args': args})
        records.sort(key=lambda rec: rec['ts_us'])
        return {'pid': own_pid, 'events': records, 'dropped_events': dropped,
                'capacity': self._capacity}

    def dropped_events(self) -> int:
        """Events overwritten (own/retired rings) or discarded (foreign
        buffer) so far."""
        with self._lock:
            rings = self._live_rings()
            dropped = (self._foreign.dropped + self._foreign_dropped
                       + self._retired.dropped + self._retired_dropped)
        return dropped + sum(ring.dropped for ring in rings)

    def reset(self) -> None:
        """Clear every ring and the foreign/retired buffers (tests, between
        captures)."""
        with self._lock:
            for ring in self._live_rings():
                ring.clear()
                ring.dropped = 0
            self._foreign = _Ring(self._capacity)
            self._foreign_dropped = 0
            self._retired = _Ring(self._capacity)
            self._retired_dropped = 0


#: the process-wide recorder every trace hook writes to
_process_recorder = TraceRecorder()

#: thread-local causal context (set around each worker item)
_ctx_local = threading.local()


def set_trace_context(epoch: int, rowgroup: int, attempt: int) -> None:
    """Install the calling thread's causal context ``(epoch, rowgroup,
    attempt)``; every event recorded until :func:`clear_trace_context` is
    tagged with it (explicit ``ctx=`` arguments win)."""
    _ctx_local.ctx = (int(epoch), int(rowgroup), int(attempt))


def clear_trace_context() -> None:
    """Drop the calling thread's causal context."""
    _ctx_local.ctx = None


def current_trace_context() -> Optional[TraceContext]:
    """The calling thread's causal context, or None outside an item."""
    ctx: Optional[TraceContext] = getattr(_ctx_local, 'ctx', None)
    return ctx


def set_dispatch_attempt(attempt: int) -> None:
    """Record the dispatch attempt the pool sent with the current work item
    (``process_worker_main`` calls this per item; thread/dummy pools leave the
    default 0). Thread-local, like the context it feeds."""
    _ctx_local.attempt = int(attempt)


def current_dispatch_attempt() -> int:
    """The dispatch attempt installed for the calling thread (0 by default)."""
    attempt: int = getattr(_ctx_local, 'attempt', 0)
    return attempt


def trace_complete(name: str, start_s: float, dur_s: float,
                   ctx: Optional[TraceContext] = None,
                   args: Optional[Dict[str, Any]] = None) -> None:
    """Record one complete ('X') event for a stage span measured on the
    ``time.perf_counter`` clock (``start_s`` seconds, ``dur_s`` duration).
    No-op while tracing is off."""
    if not _enabled:
        return
    if ctx is None:
        ctx = current_trace_context()
    _process_recorder.record(start_s * 1e6, dur_s * 1e6, 'X', name, ctx, args)


def trace_instant(name: str, ctx: Optional[TraceContext] = None,
                  args: Optional[Dict[str, Any]] = None) -> None:
    """Record one instant ('i') event — an anomaly marker on the timeline.
    ``name`` must be declared in ``spans.TRACE_INSTANTS`` (pipecheck's
    telemetry-names rule enforces it statically). No-op while tracing is off."""
    if not _enabled:
        return
    if ctx is None:
        ctx = current_trace_context()
    _process_recorder.record(time.perf_counter() * 1e6, 0.0, 'i', name, ctx,
                             args)


def drain_trace_events() -> Optional[Dict[str, Any]]:
    """Drain the calling thread's ring into a JSON-safe ``trace`` batch sidecar
    (``{'pid', 'events', 'dropped'}``), or None when empty/disabled — the
    worker side of cross-process collection (rides next to the ``telemetry``
    sidecar)."""
    if not _enabled:
        return None
    drained = _process_recorder.drain()
    if drained is None:
        return None
    events, dropped = drained
    return {'pid': os.getpid(),
            'events': [list(event) for event in events],
            'dropped': dropped}


def merge_trace_events(sidecar: Optional[Dict[str, Any]]) -> None:
    """Fold a ``trace`` batch sidecar produced by :func:`drain_trace_events`
    in another process into this process's recorder (consumer side)."""
    if not sidecar or not _enabled:
        return
    _process_recorder.merge(int(sidecar.get('pid', 0)),
                            sidecar.get('events') or (),
                            dropped=int(sidecar.get('dropped', 0)))


def trace_snapshot() -> Dict[str, Any]:
    """One JSON-safe snapshot of the process recorder (own + merged foreign
    events, sorted by timestamp, with the cumulative drop count). Feed it to
    :func:`petastorm_tpu.telemetry.trace_export.to_chrome_trace` or
    :func:`~petastorm_tpu.telemetry.trace_export.summarize_trace`."""
    return _process_recorder.snapshot()


def reset_tracing() -> None:
    """Clear the process recorder (tests / between flight captures)."""
    _process_recorder.reset()
