"""Export surfaces for telemetry snapshots: Prometheus text exposition and a
periodic JSONL event log (docs/observability.md "Export formats").

Both operate on the plain-dict snapshots produced by
:meth:`~petastorm_tpu.telemetry.registry.MetricsRegistry.snapshot` (also found
under ``Reader.diagnostics['telemetry']`` and
``JaxDataLoader.telemetry_snapshot()``), so exporting never holds any pipeline
lock — take a snapshot, hand it to an exporter.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from petastorm_tpu.telemetry.registry import (DEFAULT_NUM_BUCKETS,
                                              bucket_upper_bound)

_NAME_SANITIZE = re.compile(r'[^a-zA-Z0-9_:]')
#: the full legal Prometheus metric-name grammar — what every emitted name
#: must match after sanitization (first char may not be a digit)
METRIC_NAME_RE = re.compile(r'^[a-zA-Z_:][a-zA-Z0-9_:]*$')


def sanitize_metric_name(name: str) -> str:
    """Map an arbitrary metric id onto the legal Prometheus name grammar
    ``[a-zA-Z_:][a-zA-Z0-9_:]*``: every illegal character becomes ``_`` and a
    leading digit (or empty name) gets a ``_`` prefix — so a stage/knob id
    containing ``.``/``-``/spaces or starting with a digit degrades to an ugly
    but VALID name instead of an exposition the scraper rejects."""
    sanitized = _NAME_SANITIZE.sub('_', name)
    if not sanitized or sanitized[0].isdigit():
        sanitized = '_' + sanitized
    return sanitized


def _metric_name(prefix: str, name: str) -> str:
    return sanitize_metric_name('{}_{}'.format(prefix, name)
                                if prefix else name)


def _series_labels(name: str, metric: str, prefix: str,
                   labels: Optional[Dict[str, str]]) -> Dict[str, str]:
    """The label set every series of this metric carries: the caller's
    ``labels`` plus a ``raw_name`` label whenever the metric id itself is
    not already a legal Prometheus name (``.``/``-``/spaces, a leading
    digit) — the original id must stay queryable after sanitization."""
    out = dict(labels or {})
    if sanitize_metric_name(name) != name:
        out['raw_name'] = name
    return out


def _format_labels(labels: Dict[str, str]) -> str:
    """``{k="v",...}`` rendering (empty string for no labels), values escaped
    per the exposition format."""
    if not labels:
        return ''
    return '{{{}}}'.format(','.join(
        '{}="{}"'.format(key, escape_label_value(value))
        for key, value in sorted(labels.items())))


def _format_value(value: float) -> str:
    if value == float('inf'):
        return '+Inf'
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def escape_label_value(value: str) -> str:
    """Escape a label value per the Prometheus text exposition format: inside
    the double quotes of ``{label="..."}``, backslash, double-quote and newline
    must appear as ``\\\\``, ``\\"`` and ``\\n`` — a raw newline splits the
    series line and makes scrapers reject the whole exposition."""
    return (str(value).replace('\\', '\\\\').replace('"', '\\"')
            .replace('\n', '\\n'))


def _escape_help(text: str) -> str:
    # HELP text escaping differs from label values: only backslash and newline
    # (quotes are legal in HELP text per the exposition format)
    return str(text).replace('\\', '\\\\').replace('\n', '\\n')


def _help_line(metric: str, kind: str, name: str) -> str:
    return '# HELP {} petastorm_tpu {} {} (docs/observability.md)'.format(
        metric, kind, _escape_help(name))


def _render_histogram_series(lines: List[str], metric: str,
                             hist: Dict[str, Any],
                             labels: Dict[str, str]) -> None:
    """Append one label-set's cumulative ``_bucket``/``_sum``/``_count``
    series for ``metric`` (HELP/TYPE are the caller's job — they must appear
    exactly once per metric name across all label sets)."""
    unit = float(hist.get('unit', 1e-6))
    buckets = {int(k): int(v) for k, v in (hist.get('buckets') or {}).items()}
    cumulative = 0
    top = max(buckets) if buckets else -1
    # finite buckets only — the histogram's last bucket IS +Inf, which the
    # unconditional line below emits exactly once (duplicate le="+Inf"
    # series make scrapers reject the whole exposition)
    for idx in range(min(top + 1, DEFAULT_NUM_BUCKETS - 1)):
        cumulative += buckets.get(idx, 0)
        le = bucket_upper_bound(idx, unit)
        bucket_labels = dict(labels)
        bucket_labels['le'] = _format_value(le)
        lines.append('{}_bucket{} {}'.format(
            metric, _format_labels(bucket_labels), cumulative))
    inf_labels = dict(labels)
    inf_labels['le'] = '+Inf'
    lines.append('{}_bucket{} {}'.format(
        metric, _format_labels(inf_labels),
        int(hist.get('count', cumulative))))
    suffix = _format_labels(labels)
    lines.append('{}_sum{} {}'.format(
        metric, suffix, _format_value(float(hist.get('sum', 0.0)))))
    lines.append('{}_count{} {}'.format(metric, suffix,
                                        int(hist.get('count', 0))))


def to_prometheus_text(snapshot: Dict[str, Any],
                       prefix: str = 'petastorm_tpu',
                       labels: Optional[Dict[str, str]] = None) -> str:
    """Render a registry snapshot in the Prometheus text exposition format.

    Every metric emits a ``# HELP``/``# TYPE`` pair. Histograms emit the
    conventional cumulative ``_bucket{le=...}`` series plus ``_sum`` and
    ``_count``; bucket boundaries come from the histogram's power-of-two layout
    (``le`` values are in the histogram's base unit — seconds for latency
    stages). Counters map to ``counter``, gauges to ``gauge``. Metric names are
    sanitized onto the legal grammar ``[a-zA-Z_:][a-zA-Z0-9_:]*``
    (:func:`sanitize_metric_name`); whenever sanitization changed the id, the
    original rides a ``raw_name`` label so it stays queryable. Label values /
    HELP text are escaped per the exposition format (backslash, quote,
    newline — :func:`escape_label_value`), so a pathological stage name
    degrades to an ugly series, never to an exposition the scraper rejects.
    ``labels`` (optional) is stamped onto every series — the per-worker /
    per-client labeling hook of the fleet scrape surface."""
    lines: List[str] = []
    for name, value in sorted((snapshot.get('counters') or {}).items()):
        metric = _metric_name(prefix, name)
        series = _format_labels(_series_labels(name, metric, prefix, labels))
        lines.append(_help_line(metric, 'counter', name))
        lines.append('# TYPE {} counter'.format(metric))
        lines.append('{}{} {}'.format(metric, series, _format_value(value)))
    for name, value in sorted((snapshot.get('gauges') or {}).items()):
        metric = _metric_name(prefix, name)
        series = _format_labels(_series_labels(name, metric, prefix, labels))
        lines.append(_help_line(metric, 'gauge', name))
        lines.append('# TYPE {} gauge'.format(metric))
        lines.append('{}{} {}'.format(metric, series, _format_value(value)))
    for name, hist in sorted((snapshot.get('histograms') or {}).items()):
        metric = _metric_name(prefix, name)
        lines.append(_help_line(metric, 'histogram', name))
        lines.append('# TYPE {} histogram'.format(metric))
        _render_histogram_series(lines, metric, hist,
                                 _series_labels(name, metric, prefix, labels))
    return '\n'.join(lines) + '\n'


def to_prometheus_text_labeled(snapshots: Dict[str, Dict[str, Any]],
                               label: str,
                               prefix: str = 'petastorm_tpu') -> str:
    """Render several registry snapshots as ONE exposition where every series
    carries ``{label="<key>"}`` — the fleet scrape's per-worker block
    (docs/observability.md "Live metrics plane").

    Unlike calling :func:`to_prometheus_text` once per snapshot, metric names
    are grouped: each emits exactly one ``# HELP``/``# TYPE`` pair followed by
    one series (or bucket family) per label value, because a repeated TYPE
    line for the same metric name makes scrapers reject the exposition."""
    counters: Dict[str, List[str]] = {}
    gauges: Dict[str, List[str]] = {}
    histograms: Dict[str, List[str]] = {}
    for key in sorted(snapshots):
        snapshot = snapshots[key] or {}
        for name in snapshot.get('counters') or {}:
            counters.setdefault(name, []).append(key)
        for name in snapshot.get('gauges') or {}:
            gauges.setdefault(name, []).append(key)
        for name in snapshot.get('histograms') or {}:
            histograms.setdefault(name, []).append(key)
    lines: List[str] = []
    for name in sorted(counters):
        metric = _metric_name(prefix, name)
        lines.append(_help_line(metric, 'counter', name))
        lines.append('# TYPE {} counter'.format(metric))
        for key in counters[name]:
            series = _series_labels(name, metric, prefix, {label: key})
            lines.append('{}{} {}'.format(
                metric, _format_labels(series),
                _format_value(snapshots[key]['counters'][name])))
    for name in sorted(gauges):
        metric = _metric_name(prefix, name)
        lines.append(_help_line(metric, 'gauge', name))
        lines.append('# TYPE {} gauge'.format(metric))
        for key in gauges[name]:
            series = _series_labels(name, metric, prefix, {label: key})
            lines.append('{}{} {}'.format(
                metric, _format_labels(series),
                _format_value(snapshots[key]['gauges'][name])))
    for name in sorted(histograms):
        metric = _metric_name(prefix, name)
        lines.append(_help_line(metric, 'histogram', name))
        lines.append('# TYPE {} histogram'.format(metric))
        for key in histograms[name]:
            series = _series_labels(name, metric, prefix, {label: key})
            _render_histogram_series(
                lines, metric, snapshots[key]['histograms'][name], series)
    return '\n'.join(lines) + '\n' if lines else ''


class JsonlEventLogger(object):
    """Append-only JSONL telemetry log: one ``{"ts", "event", "telemetry", ...}``
    object per line.

    ``maybe_emit`` is the periodic entry point — call it from any hot-ish loop
    (the device loader calls it once per yielded batch when
    ``PETASTORM_TPU_TELEMETRY_JSONL`` names a path); it writes at most once per
    ``interval_s`` and costs one monotonic-clock read otherwise. ``emit`` writes
    unconditionally (final flush, epoch boundary). Thread-safe; write failures
    disable the logger after one warning rather than breaking the pipeline.

    ``max_bytes`` (default None = unbounded, the prior behavior) caps the log
    file: when appending a line would push it past the cap, the current file
    rotates to ``<path>.1`` and a fresh file starts — a week-long run driven
    by ``PETASTORM_TPU_TELEMETRY_JSONL`` keeps bounded disk instead of
    filling it. ``max_rotations`` (default 1, the prior behavior) is how many
    rotated generations survive: each rotation shifts the chain
    ``<path>.1 -> <path>.2 -> ... -> <path>.N`` (the oldest falls off), so a
    long-running manifest log keeps ``(max_rotations + 1) * max_bytes`` of
    history instead of losing everything but one generation. Env forms:
    ``PETASTORM_TPU_TELEMETRY_JSONL_MAX_BYTES`` /
    ``PETASTORM_TPU_TELEMETRY_JSONL_ROTATIONS`` (read by
    :func:`logger_from_env`)."""

    def __init__(self, path: str, interval_s: float = 10.0,
                 max_bytes: Optional[int] = None,
                 max_rotations: int = 1) -> None:
        self._path = path
        self._interval_s = float(interval_s)
        self._max_bytes = int(max_bytes) if max_bytes else None
        self._max_rotations = max(1, int(max_rotations))
        self._lock = threading.Lock()
        self._next_emit = 0.0
        self._failed = False

    @property
    def path(self) -> str:
        """Destination file path."""
        return self._path

    def due(self) -> bool:
        """Cheap periodicity check (one clock read): True when the next
        ``maybe_emit`` would write. Lets hot loops skip building the snapshot
        entirely between intervals."""
        return not self._failed and time.monotonic() >= self._next_emit

    def maybe_emit(self, snapshot: Dict[str, Any], event: str = 'interval',
                   **extra: Any) -> bool:
        """Emit if at least ``interval_s`` elapsed since the last write; returns
        whether a line was written."""
        now = time.monotonic()
        if now < self._next_emit:
            return False
        return self.emit(snapshot, event=event, **extra)

    def emit(self, snapshot: Dict[str, Any], event: str = 'snapshot',
             **extra: Any) -> bool:
        """Append one JSONL record unconditionally; returns success.

        Dual-clock convention (docs/observability.md): every record carries
        BOTH ``ts_unix`` (``time.time()`` — aligns the stream with external
        monitoring systems that live on the wall clock) and ``ts_mono``
        (``time.perf_counter()`` — the same monotonic timebase the flight
        recorder's ``ts_us`` stamps use, so a JSONL record can be placed on a
        trace timeline without wall-clock skew). ``ts`` is kept as an alias of
        ``ts_unix`` for pre-existing consumers."""
        if self._failed:
            return False
        now_unix = time.time()
        record = {'ts': now_unix, 'ts_unix': now_unix,
                  'ts_mono': time.perf_counter(), 'event': event,
                  'pid': os.getpid(), 'telemetry': snapshot}
        record.update(extra)
        line = json.dumps(record) + '\n'
        with self._lock:
            self._next_emit = time.monotonic() + self._interval_s
            try:
                self._maybe_rotate(len(line))
                with open(self._path, 'a') as f:
                    f.write(line)
            except OSError:
                import logging
                logging.getLogger(__name__).warning(
                    'telemetry JSONL log %s is unwritable; disabling the logger',
                    self._path, exc_info=True)
                self._failed = True
                return False
        return True

    def _maybe_rotate(self, incoming_bytes: int) -> None:
        """Size-capped rotation (caller holds the lock): when the pending line
        would push the file past ``max_bytes``, the generation chain shifts —
        ``.{N-1} -> .N`` (oldest dropped), down to the current file becoming
        ``.1`` — each link an atomic ``os.replace``. A missing file counts as
        size 0; other stat errors fall through to the append, whose own
        failure path disables the logger."""
        if self._max_bytes is None:
            return
        try:
            size = os.path.getsize(self._path)
        except OSError:
            return  # nothing to rotate (first write, or unstatable path)
        if size + incoming_bytes <= self._max_bytes:
            return
        for generation in range(self._max_rotations - 1, 0, -1):
            older = '{}.{}'.format(self._path, generation)
            if os.path.exists(older):
                os.replace(older, '{}.{}'.format(self._path, generation + 1))
        os.replace(self._path, self._path + '.1')


def env_rotation_settings() -> Tuple[Optional[int], int]:
    """The ``(max_bytes, max_rotations)`` pair the env configures:
    ``$PETASTORM_TPU_TELEMETRY_JSONL_MAX_BYTES`` (default unbounded) arms
    size-capped rotation, ``$PETASTORM_TPU_TELEMETRY_JSONL_ROTATIONS``
    (default 1) sets how many rotated generations survive. Shared by
    :func:`logger_from_env` and the lineage manifest logger, so one env
    convention bounds every JSONL stream."""
    raw_cap = os.environ.get('PETASTORM_TPU_TELEMETRY_JSONL_MAX_BYTES', '')
    try:
        max_bytes: Optional[int] = int(raw_cap) if raw_cap else None
    except ValueError:
        max_bytes = None
    raw_rotations = os.environ.get('PETASTORM_TPU_TELEMETRY_JSONL_ROTATIONS',
                                   '')
    try:
        max_rotations = int(raw_rotations) if raw_rotations else 1
    except ValueError:
        max_rotations = 1
    return max_bytes, max_rotations


def logger_from_env(interval_s: float = 10.0) -> Optional[JsonlEventLogger]:
    """A :class:`JsonlEventLogger` targeting ``$PETASTORM_TPU_TELEMETRY_JSONL``,
    or None when the variable is unset/empty.
    ``$PETASTORM_TPU_TELEMETRY_JSONL_MAX_BYTES`` (optional, default unbounded)
    arms size-capped rotation and
    ``$PETASTORM_TPU_TELEMETRY_JSONL_ROTATIONS`` (optional, default 1) sets
    the surviving generation count (:func:`env_rotation_settings`)."""
    path = os.environ.get('PETASTORM_TPU_TELEMETRY_JSONL')
    if not path:
        return None
    max_bytes, max_rotations = env_rotation_settings()
    return JsonlEventLogger(path, interval_s=interval_s, max_bytes=max_bytes,
                            max_rotations=max_rotations)


def load_snapshot(path: str) -> Dict[str, Any]:
    """Read a telemetry snapshot from ``path``: either a bare snapshot JSON file,
    a doctor/bench JSON report containing a ``telemetry`` key, or a JSONL event
    log (the LAST line's ``telemetry`` field wins — the cumulative view)."""
    with open(path) as f:
        text = f.read().strip()
    if not text:
        raise ValueError('{} is empty'.format(path))
    lines = text.splitlines()
    try:
        obj = json.loads(text)
    except ValueError:
        obj = json.loads(lines[-1])  # JSONL: last (cumulative) record
    if isinstance(obj, dict) and 'telemetry' in obj:
        obj = obj['telemetry']
    if isinstance(obj, dict) and 'snapshot' in obj and 'histograms' not in obj:
        obj = obj['snapshot']  # doctor --json nests under telemetry.snapshot
    if not isinstance(obj, dict) or 'histograms' not in obj:
        raise ValueError('{} does not contain a telemetry snapshot '
                         '(expected a "histograms" key)'.format(path))
    return obj
