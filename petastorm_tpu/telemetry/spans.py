"""Stage spans: named timing scopes over the data-plane pipeline stages.

A *stage* is one step a row batch passes through on its way to the device —
``fs_open``, ``rowgroup_read``, ``decode``, ``transform``, ``shuffle``,
``cache_hit`` / ``cache_miss`` / ``cache_store``, ``serialize``,
``shm_slot_wait`` / ``shm_map`` / ``shm_release``, ``shuffle_wait``, ``collate``,
``h2d``, ``device_decode`` / ``d2d_wait`` (the catalog with semantics:
docs/observability.md). Worker-side stages
execute in whatever process the pool runs them in, so their timings cannot be
written into the consumer's registry directly; instead each worker thread
accumulates them in a process-local :class:`StageRecorder` and the rowgroup
worker **drains** the accumulation into the published batch's ``telemetry``
sidecar — the same results-channel ride ``cache_hit`` takes — where
``Reader._note_item_consumed`` merges it into the consumer-side registry. One
snapshot therefore covers every process, and a respawned worker's fresh recorder
merges additively like any other (no double counting, no loss beyond the
unpublished in-flight item).

The recorder is sharded per THREAD (``threading.local``): a drain returns only
the calling thread's accumulation, so thread-pool workers never race each other,
and the serialize/slot-wait stages recorded by the process-pool worker main land
on the same thread that publishes the next batch (they ride one item late —
still the same process total).
"""

from __future__ import annotations

import threading
import time
from types import TracebackType
from typing import Any, Dict, List, Optional, Type

from petastorm_tpu.telemetry import registry as _registry
from petastorm_tpu.telemetry import tracing as _tracing
from petastorm_tpu.telemetry.registry import (DEFAULT_NUM_BUCKETS, SECONDS_UNIT,
                                              bucket_index)

#: canonical stage names, pipeline order (docs/observability.md metric catalog)
STAGES = (
    'fs_open',        # filesystem construction / reconnect (worker)
    'rowgroup_read',  # Parquet rowgroup -> Arrow table (worker)
    'decode',         # codec decode, Arrow -> numpy columns (worker)
    'shuffle',        # in-rowgroup seeded permutation (worker)
    'transform',      # TransformSpec application (worker)
    'cache_hit',      # serving a decoded rowgroup from the cache (worker)
    'cache_miss',     # the full fill of a missed key — ENVELOPES read+decode
    'cache_store',    # writing a filled value to the cache (worker)
    'cache_corrupt',  # detecting+deleting a corrupt entry (worker; count = entries)
    'serialize',      # result -> wire frames (process-pool worker main)
    'shm_slot_wait',  # backpressure wait for a free ring slot (worker main)
    'shm_map',        # slot view + deserialize on the consumer (pool)
    'shm_release',    # slot ack back to the producing worker (pool)
    'pool_wait',      # consumer blocked in pool.get_results (pool)
    'shuffle_wait',   # consumer blocked on the loader's prefetch queue (loader)
    'collate',        # host batch assembly / sanitize (loader)
    'h2d',            # host->device upload (loader)
    'device_decode',  # decode-tail work on raw-shipped fields: pack/inflate +
                      # jitted device decode dispatch, or the host fallback
                      # decode (loader; docs/performance.md)
    'd2d_wait',       # blocked on the prefetch-to-device ring: the oldest
                      # dispatched device batch had not finished (loader)
    'decode_field',   # ONE field's kernel inside 'decode' — emitted to the
                      # flight-recorder timeline only (never a histogram),
                      # and only while tracing is armed: the per-field leg of
                      # the cost profiler (telemetry/cost_model.py)
    'range_fetch',    # one planned multi-range fetch of a rowgroup's column
                      # chunks (storage/fetcher.py) — disjoint from
                      # 'rowgroup_read', which covers only the Parquet
                      # decode of the already-fetched bytes when the storage
                      # engine is armed (docs/performance.md "Object-store
                      # ingest engine")
    'range_hedge',    # lifetime of one hedged duplicate GET, win or lose
                      # (storage/fetcher.py)
)

#: stages whose span ENVELOPES other recorded stages (cache_miss wraps
#: rowgroup_read+decode) — excluded from time-share attribution so shares of the
#: leaf stages sum sensibly (telemetry/analyze.py)
ENVELOPE_STAGES = frozenset({'cache_miss'})

#: declared event counters (``registry.inc(name)`` call sites). Part of the
#: telemetry name catalog alongside STAGES: pipecheck's telemetry-names rule
#: (docs/static-analysis.md) rejects any ``inc`` of a name not listed here,
#: so a typo'd counter fails the tier-1 self-check instead of silently
#: minting an orphan metric.
COUNTERS = (
    'breaker_open',    # a circuit breaker tripped open (pool consumer side)
    'watchdog_reap',   # a hung worker was SIGKILLed by the watchdog (pool)
    'shm_crc_fail',    # a shm frame failed CRC verification (pool)
    'service_busy',    # the input service rejected a submit (admission control)
    'service_resubmit',  # a service item was re-requested (lost shm segment)
    'slo_breach',      # input-efficiency fell below the SLO target (edge-
                       # triggered: one count per ok->breach transition —
                       # telemetry/slo.py, docs/observability.md)
    'lineage_divergence',  # a delivered item broke the expected lineage
                           # stream (unknown/duplicate delivery, resume
                           # mismatch) — telemetry/lineage.py,
                           # docs/observability.md "Sample lineage"
    'incidents_captured',      # an incident bundle was written (edge-
                               # triggered black-box capture —
                               # telemetry/incident.py, docs/observability.md
                               # "Incident autopsy plane")
    'incidents_rate_limited',  # an incident trigger was dropped by the
                               # per-kind token bucket (telemetry/incident.py)
    'ledger_frames_dropped',   # dispatcher-ledger journal frames that failed
                               # CRC replay (service/ledger.py — the loud
                               # half of degrade-to-replay-from-clients)
    'storage_footer_cache_hit',   # a Parquet footer was served from the
                                  # metadata cache (storage/metadata_cache.py)
    'storage_footer_cache_miss',  # a footer had to be read from storage
    'storage_ranges_coalesced',   # raw column-chunk ranges merged away by
                                  # gap-threshold coalescing (storage/
                                  # range_planner.py; count = raw - merged)
    'storage_hedge_fired',        # a hedged duplicate GET was launched
                                  # (storage/fetcher.py)
    'storage_hedge_won',          # the hedge returned before the primary
                                  # (its bytes were committed; the primary's
                                  # were dropped)
    'perf_regression',            # the live regression sentinel's drift test
                                  # fired on a goodput collapse / wait-share
                                  # growth (edge-triggered: one count per
                                  # alarm — telemetry/sentinel.py,
                                  # docs/observability.md "Longitudinal
                                  # observatory")
    'history_record_written',     # one run record was appended to the
                                  # longitudinal run-history store
                                  # (telemetry/history.py)
    'history_frames_dropped',     # run-history journal frames that failed
                                  # CRC replay (torn tail / flipped byte —
                                  # telemetry/history.py)
    'host_reshard',               # a reader joined as a reshard survivor —
                                  # undelivered rowgroups were re-dealt
                                  # after a host join/leave/lease expiry
                                  # (parallel/topology.py,
                                  # docs/robustness.md "Elastic pod-scale
                                  # sharding")
    'topology_frames_dropped',    # membership-journal frames that failed
                                  # CRC replay (torn tail / flipped byte —
                                  # parallel/topology.py)
)

#: declared size histograms (``registry.observe(name, n, unit=BYTES_UNIT)``
#: call sites) — same catalog contract as COUNTERS
SIZE_HISTOGRAMS = (
    'wire_bytes_copied',  # bytes materialized into new host memory per batch
)

#: declared flight-recorder instant events (``tracing.trace_instant(name)``
#: call sites — docs/observability.md "Flight recorder"). Same catalog
#: contract as COUNTERS: pipecheck's telemetry-names rule rejects any
#: ``trace_instant`` of a name not listed here, so anomaly markers cannot
#: silently drift from the timeline legend.
TRACE_INSTANTS = (
    'ventilate',           # a work item entered the pool (consumer, ventilator thread)
    'rowgroup_consumed',   # the item's result was popped and accounted (consumer)
    'quarantine',          # a rowgroup was quarantined (worker, or consumer hang path)
    'watchdog_reap',       # a hung worker was SIGKILLed by the watchdog (consumer)
    'worker_respawn',      # a dead worker's in-flight item was re-ventilated (consumer)
    'breaker_transition',  # a circuit breaker changed state (any process)
    'shm_crc_drop',        # a shm frame failed CRC and was dropped unread (consumer)
    'shm_fallback',        # a result rode the ZMQ wire while the shm ring was enabled
    'autotune_decision',   # the closed-loop autotuner proposed/committed/reverted/froze a knob change (controller)
    'slo_breach',          # input-efficiency fell below the SLO target (consumer; telemetry/slo.py)
    'schedule_plan',       # the cost-aware scheduler planned one epoch's ventilation order (ventilator thread; schedule/cost_schedule.py)
    'lineage_divergence',  # a delivered item broke the expected lineage stream (consumer; telemetry/lineage.py)
    'incident_captured',   # an incident bundle was written at this point on the timeline (telemetry/incident.py)
    'reshard',             # undelivered service work was re-split across a changed worker set (dispatcher; service/dispatcher.py)
    'ledger_replay',       # a restarting dispatcher replayed its durable token ledger (service/ledger.py)
    'perf_regression',     # the live regression sentinel fired mid-run (consumer/dispatcher; telemetry/sentinel.py)
    'host_reshard',        # a reader joined as a host-reshard survivor after a topology change (consumer; parallel/topology.py)
)

#: declared gauge ids (``registry.gauge(name)`` call sites with literal
#: names, plus the service scheduler's snapshot gauges) — same catalog
#: contract as COUNTERS: pipecheck's telemetry-names rule rejects a
#: ``gauge('x')`` of a name not listed here
GAUGES = (
    'slo_efficiency',          # latest evaluated input efficiency [0,1] (slo.py)
    'slo_target_efficiency',   # the SLO target the efficiency is held against
    'service_queue_depth',       # accepted items queued fleet-wide (dispatcher)
    'service_ready_workers',     # idle decode workers (dispatcher)
    'service_workers',           # registered decode workers (dispatcher)
    'service_admission_window',  # per-client in-flight cap (dispatcher)
    'service_client_window',     # smallest live client window (dispatcher)
    'lineage_items_folded',      # items folded into the order digest so far
                                 # (reader scrape; telemetry/lineage.py)
    'lineage_pending_items',     # delivered-out-of-order items awaiting
                                 # their fold slot (reader scrape)
    'sentinel_rate_ewma',        # the regression sentinel's smoothed windowed
                                 # rows/s (telemetry/sentinel.py)
    'sentinel_wait_share_ewma',  # the sentinel's smoothed primary-wait share
                                 # of each window (telemetry/sentinel.py)
)


class StageRecorder(object):
    """Per-thread accumulation of stage timings, drained into batch sidecars.

    Each thread owns a private ``{stage: [count, sum, max, {bucket: n}]}`` dict;
    ``record`` appends to it without locks and ``drain`` atomically (per thread)
    hands it off as a JSON-safe ``{stage: histogram_snapshot}`` mapping that
    :meth:`MetricsRegistry.merge_stage_times` understands."""

    __slots__ = ('_local',)

    def __init__(self) -> None:
        self._local = threading.local()

    def _cells(self) -> Dict[str, List[Any]]:
        cells = getattr(self._local, 'cells', None)
        if cells is None:
            cells = {}
            self._local.cells = cells
        return cells

    def record(self, stage: str, seconds: float) -> None:
        """Accumulate one observation of ``stage`` for the calling thread."""
        if not _registry.telemetry_enabled():
            return
        cells = self._cells()
        cell = cells.get(stage)
        if cell is None:
            cell = [0, 0.0, 0.0, {}]
            cells[stage] = cell
        cell[0] += 1
        cell[1] += seconds
        if seconds > cell[2]:
            cell[2] = seconds
        idx = bucket_index(seconds, SECONDS_UNIT, DEFAULT_NUM_BUCKETS)
        cell[3][idx] = cell[3].get(idx, 0) + 1

    def drain(self) -> Optional[Dict[str, Dict[str, Any]]]:
        """Hand off and clear the calling thread's accumulation (None if empty)."""
        cells = getattr(self._local, 'cells', None)
        if not cells:
            return None
        self._local.cells = {}
        return {stage: {'unit': SECONDS_UNIT, 'count': cell[0], 'sum': cell[1],
                        'max': cell[2],
                        'buckets': {str(i): n for i, n in cell[3].items()}}
                for stage, cell in cells.items()}


#: the process-wide recorder every data-plane stage writes to (worker side)
_process_recorder = StageRecorder()


def record_stage(stage: str, seconds: float,
                 trace_args: Optional[Dict[str, Any]] = None) -> None:
    """Record one observation into the process-wide stage recorder (and, when
    the flight recorder is armed, a matching trace event back-dated by the
    measured duration — docs/observability.md "Flight recorder").
    ``trace_args`` rides only the trace event (never the histogram) — the
    storage engine uses it to ship per-fetch byte/range/hedge totals to the
    cost ledger (telemetry/cost_model.py)."""
    _process_recorder.record(stage, seconds)
    if _tracing.trace_enabled():
        _tracing.trace_complete(stage, time.perf_counter() - seconds, seconds,
                                args=trace_args)


def drain_stage_times() -> Optional[Dict[str, Dict[str, Any]]]:
    """Drain the calling thread's accumulated stage times (for batch sidecars)."""
    return _process_recorder.drain()


class stage_span(object):
    """Context manager timing one stage into the process recorder:
    ``with stage_span('decode'): ...``. Near-zero cost when telemetry is
    disabled (one enabled check, no clock reads). Exceptions propagate; the
    partial duration is still recorded (a stage that died slow is exactly the
    signal the bottleneck report wants)."""

    __slots__ = ('_stage', '_start')

    def __init__(self, stage: str) -> None:
        self._stage = stage
        self._start = 0.0

    def __enter__(self) -> 'stage_span':
        if _registry.telemetry_enabled() or _tracing.trace_enabled():
            self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type: Optional[Type[BaseException]],
                 exc: Optional[BaseException],
                 tb: Optional[TracebackType]) -> None:
        if self._start:
            duration = time.perf_counter() - self._start
            _process_recorder.record(self._stage, duration)
            if _tracing.trace_enabled():
                # same measurement feeds both views: the histogram (aggregate)
                # and the flight-recorder timeline (this specific span)
                _tracing.trace_complete(self._stage, self._start, duration)
            self._start = 0.0
