"""Framework error types (reference: petastorm/errors.py:16-17, petastorm/utils.py:50-51,
petastorm/etl/dataset_metadata.py PetastormMetadataError)."""


class PetastormTpuError(Exception):
    """Base class for all framework errors."""


class NoDataAvailableError(PetastormTpuError):
    """Raised when a shard (or predicate-filtered view) of the dataset contains no rowgroups
    (reference: petastorm/reader.py:580-582)."""


class DecodeFieldError(PetastormTpuError):
    """Raised when a codec fails to decode a field value (reference:
    petastorm/utils.py:50-51)."""


class MetadataError(PetastormTpuError):
    """Raised when dataset metadata (schema / rowgroup index) is missing or unreadable
    (reference: petastorm/etl/dataset_metadata.py:30-33)."""
