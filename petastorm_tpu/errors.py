"""Framework error types (reference: petastorm/errors.py:16-17, petastorm/utils.py:50-51,
petastorm/etl/dataset_metadata.py PetastormMetadataError).

The resilience subsystem (petastorm_tpu/resilience.py, docs/robustness.md) splits
failures into two classes: *transient* (retryable — network hiccups, throttled object
stores, flaky tunnels) and *permanent* (corrupt data, schema bugs). ``TransientIOError``
marks the former explicitly; ``QuarantinedRowGroupError`` reports a rowgroup that was
skipped under ``on_error='skip'`` and landed in the quarantine ledger.

Strict-typed (mypy.ini ``[mypy-petastorm_tpu.errors]``): the taxonomy is the
machine-readable contract the retry classifier, ledger and doctor key on, so
its structured attributes carry full signatures.
"""

from __future__ import annotations

from typing import Optional


class PetastormTpuError(Exception):
    """Base class for all framework errors."""


class NoDataAvailableError(PetastormTpuError):
    """Raised when a shard (or predicate-filtered view) of the dataset contains no rowgroups
    (reference: petastorm/reader.py:580-582)."""


class DecodeFieldError(PetastormTpuError):
    """Raised when a codec fails to decode a field value (reference:
    petastorm/utils.py:50-51).

    Structured attributes (machine-readable, not just message text):

    - ``field_name``: the Unischema field that failed to decode (None if unknown).
    - ``fragment_path``: the Parquet fragment being read when the decode failed
      (None when decoding outside a rowgroup read, e.g. ``decode_row``).
    """

    def __init__(self, message: str, field_name: Optional[str] = None,
                 fragment_path: Optional[str] = None) -> None:
        super().__init__(message)
        self.field_name = field_name
        self.fragment_path = fragment_path


class MetadataError(PetastormTpuError):
    """Raised when dataset metadata (schema / rowgroup index) is missing or unreadable
    (reference: petastorm/etl/dataset_metadata.py:30-33)."""


class TransientIOError(PetastormTpuError, OSError):
    """An IO failure that is expected to succeed on retry (connection reset, throttled
    object store, wedged tunnel). Subclasses ``OSError`` so generic IO-error handling
    (and the default transient classifier in :mod:`petastorm_tpu.resilience`) treats it
    uniformly with errno-style failures; raise it from custom filesystems to opt an
    error into the retry path explicitly."""


class CacheCorruptionError(PetastormTpuError):
    """A disk-cache entry failed its integrity check (missing/old footer, length
    mismatch, CRC mismatch — ``petastorm_tpu.cache.ArrowIpcDiskCache``). Never
    propagates out of the cache: ``get`` self-heals by deleting the entry and
    serving the fill function (counted in ``stats['corrupt_entries']``); this
    type exists so the self-heal path can be precise about what it catches."""


class WorkerHangError(PetastormTpuError):
    """A pool worker held an item past ``item_deadline_s`` without producing a
    result and was reaped by the watchdog (docs/robustness.md). Under
    ``on_error='skip'`` the item is quarantined with ``reason='hang'`` rather
    than raised; this type names the failure in ledger entries and anywhere a
    strict consumer converts them back into exceptions."""


class QuarantinedRowGroupError(PetastormTpuError):
    """A rowgroup exhausted its error budget under ``on_error='skip'`` and was excluded
    from the stream. Not raised on the hot path (skip mode degrades silently-but-visibly
    through the quarantine ledger); raised by APIs that convert ledger entries back into
    exceptions (e.g. strict post-epoch validation).

    Structured attributes: ``piece_index``, ``fragment_path``, ``row_group_id``,
    ``attempts``, and ``cause`` (the final underlying exception, if available)."""

    def __init__(self, message: str, piece_index: Optional[int] = None,
                 fragment_path: Optional[str] = None,
                 row_group_id: Optional[int] = None,
                 attempts: Optional[int] = None,
                 cause: Optional[BaseException] = None) -> None:
        super().__init__(message)
        self.piece_index = piece_index
        self.fragment_path = fragment_path
        self.row_group_id = row_group_id
        self.attempts = attempts
        self.cause = cause
